"""In-flight request coalescing index (ISSUE 19).

The store answers "has this result been computed?"; this index answers
"is this result being computed *right now*?" — the window between those
two is where a retry storm re-runs a 32-plane gang program. A volume
request registers its leader here before dispatch; an identical request
(same content digest, or the same ``X-Nm03-Idempotency-Key``) arriving
mid-flight claims the leader and waits on *its* completion instead of
dispatching a second gang.

Aliases are the idempotency-key seam: ``register(digest, req,
alias="idem:K")`` records ``K -> digest`` in a bounded map that OUTLIVES
the in-flight window, so a client retry after a fleet failover — when the
gang has already finished and released — still resolves ``K`` to the
content digest and finds the stored result. The alias map is advisory
(bounded FIFO, oldest dropped): losing an alias degrades to a recompute,
never a wrong answer.

jax- and numpy-free; one lock, NM331-scanned. The leader objects held
here are opaque to this module (the server hands in its ServeRequest /
VolumeRequest and joins on it itself).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Optional

__all__ = ["InflightIndex"]

_MAX_ALIASES = 4096


class InflightIndex:
    """digest -> in-flight leader, plus a bounded alias (idem-key) map."""

    def __init__(self, max_aliases: int = _MAX_ALIASES):
        self._lock = threading.Lock()
        self._leaders: Dict[str, Any] = {}
        self._aliases: "OrderedDict[str, str]" = OrderedDict()
        self._max_aliases = int(max_aliases)
        self._coalesced = 0

    def resolve(self, alias: str) -> Optional[str]:
        """Map an idempotency key to the content digest it last named."""
        with self._lock:
            return self._aliases.get(alias)

    def claim(self, digest: str) -> Optional[Any]:
        """Return the live leader for ``digest``, or None if none in flight."""
        with self._lock:
            leader = self._leaders.get(digest)
            if leader is not None:
                self._coalesced += 1
            return leader

    def register(
        self, digest: str, req: Any, alias: Optional[str] = None
    ) -> Any:
        """Install ``req`` as the leader for ``digest`` (first wins).

        Returns the installed leader: ``req`` itself, or an existing
        leader if one beat us to it — the caller must then join on the
        returned object instead of dispatching. The alias mapping is
        recorded either way (and persists after release).
        """
        with self._lock:
            if alias is not None:
                self._aliases[alias] = digest
                self._aliases.move_to_end(alias)
                while len(self._aliases) > self._max_aliases:
                    self._aliases.popitem(last=False)
            existing = self._leaders.get(digest)
            if existing is not None:
                self._coalesced += 1
                return existing
            self._leaders[digest] = req
            return req

    def release(self, digest: str) -> None:
        """Remove the leader once its result is filled (or failed)."""
        with self._lock:
            self._leaders.pop(digest, None)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "in_flight": len(self._leaders),
                "aliases": len(self._aliases),
                "coalesced": self._coalesced,
            }
