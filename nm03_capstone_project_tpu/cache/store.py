"""Bounded in-memory result store: LRU by bytes, digest-verified reads.

The store holds opaque payload bytes (the serialized response the HTTP
layer would have produced) under a :class:`~.keys.ResultKey` digest. Two
properties the serving tier leans on:

* **Bounded by bytes, not entries.** Masks vary by orders of magnitude
  (a 2D slice vs a 32-plane volume); an entry-count LRU would let a few
  volumes blow the budget. ``fill`` evicts from the cold end until the
  new entry fits; an entry bigger than the whole budget is rejected
  outright (counted, never stored).

* **Verify-on-read.** Every ``lookup`` re-hashes the payload and compares
  against the ETag recorded at fill time. A mismatch — bit-rot, or the
  FaultPlan ``cache``/``corrupt_entry`` drill — evicts the entry and
  reports a miss, so the caller recomputes: a corrupt entry costs one
  recompute, never a wrong answer (stale-result-is-never-an-outcome).

jax- and numpy-free; one lock, NM331-scanned.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "ResultEntry",
    "ResultStore",
    "content_etag",
    "etag_matches",
    "parse_bytes",
]


def content_etag(payload: bytes) -> str:
    """Strong HTTP ETag for a payload: quoted sha256 prefix.

    The ETag doubles as the integrity digest for verify-on-read, so it is
    derived from the bytes and nothing else — two bit-identical results
    always carry the same ETag, which is exactly what lets a client's
    ``If-None-Match`` revalidate across evict/refill cycles.
    """
    return '"' + hashlib.sha256(payload).hexdigest()[:32] + '"'


def etag_matches(if_none_match: Optional[str], etag: str) -> bool:
    """RFC 7232 ``If-None-Match`` against one strong ETag.

    ``*`` matches anything; otherwise the comma list is compared with the
    weak-comparison rule (a ``W/`` prefix on the client's copy still
    revalidates — the payload bytes it names are the same). Lives here,
    not in the HTTP layer, because both tiers (replica and router) answer
    304s and the router must stay jax-free.
    """
    if not if_none_match or not etag:
        return False
    value = if_none_match.strip()
    if value == "*":
        return True
    for candidate in value.split(","):
        c = candidate.strip()
        if c.startswith("W/"):
            c = c[2:]
        if c == etag:
            return True
    return False


_SUFFIXES = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_bytes(text: str) -> int:
    """Parse a human byte size ('512m', '2g', '1048576') to an int."""
    s = str(text).strip().lower()
    if not s:
        raise ValueError("empty byte size")
    mult = 1
    if s[-1] in _SUFFIXES:
        mult = _SUFFIXES[s[-1]]
        s = s[:-1]
    try:
        return int(float(s) * mult)
    except ValueError:
        raise ValueError(f"unparseable byte size: {text!r}") from None


@dataclass
class ResultEntry:
    """One stored result: payload bytes plus serving metadata."""

    digest: str  # ResultKey.digest() — the store address
    payload: bytes  # opaque serialized response
    etag: str  # content_etag(payload), recorded at fill
    algo: str  # "segment" | "segment-volume" (for ls/stats)
    meta: Dict[str, Any] = field(default_factory=dict)
    created: float = field(default_factory=time.time)
    hits: int = 0


class ResultStore:
    """Thread-safe LRU-by-bytes store of :class:`ResultEntry`.

    ``corrupt_hook(digest)`` is the FaultPlan seam: when it returns truthy
    during ``lookup``, the payload is handed back with one byte flipped —
    the verify-on-read path must then evict and miss, which the drill in
    tests/test_result_cache.py asserts end to end.

    ``on_evict(n)`` fires (outside any decision, inside the lock — it must
    be a cheap counter bump) whenever ``n`` entries leave the store, so the
    owner can keep ``serving_result_cache_evict_total`` honest.
    """

    def __init__(
        self,
        max_bytes: int,
        corrupt_hook: Optional[Callable[[str], bool]] = None,
        on_evict: Optional[Callable[[int], None]] = None,
    ):
        if max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        self.max_bytes = int(max_bytes)
        self._corrupt_hook = corrupt_hook
        self._on_evict = on_evict
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, ResultEntry]" = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._fills = 0
        self._evictions = 0
        self._corrupt_evictions = 0
        self._oversize_rejects = 0

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup(self, digest: str) -> Optional[ResultEntry]:
        """Return the live entry for ``digest``, or None (a miss).

        Verify-on-read: the payload is re-hashed under the lock; a digest
        mismatch evicts the entry and reports a miss so the caller
        recomputes. Hits move the entry to the hot end of the LRU.
        """
        with self._lock:
            entry = self._entries.get(digest)
            if entry is None:
                self._misses += 1
                return None
            payload = entry.payload
            if self._corrupt_hook is not None and self._corrupt_hook(digest):
                # simulate bit-rot without mutating the stored entry: the
                # verify below must catch the flipped byte
                flipped = bytearray(payload)
                if flipped:
                    flipped[0] ^= 0xFF
                payload = bytes(flipped)
            if content_etag(payload) != entry.etag:
                del self._entries[digest]
                self._bytes -= len(entry.payload)
                self._corrupt_evictions += 1
                self._evictions += 1
                self._misses += 1
                if self._on_evict is not None:
                    self._on_evict(1)
                return None
            self._entries.move_to_end(digest)
            entry.hits += 1
            self._hits += 1
            return entry

    def fill(
        self,
        digest: str,
        payload: bytes,
        algo: str,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Tuple[Optional[ResultEntry], bool]:
        """Store a computed result; returns ``(entry, created)``.

        Idempotent on digest: a concurrent fill of the same key keeps the
        existing entry (``created=False``) — both payloads hash identically
        by construction, so there is nothing to reconcile. Oversize
        payloads (> max_bytes) are rejected and counted; LRU eviction from
        the cold end makes room otherwise.
        """
        size = len(payload)
        with self._lock:
            existing = self._entries.get(digest)
            if existing is not None:
                self._entries.move_to_end(digest)
                return existing, False
            if size > self.max_bytes:
                self._oversize_rejects += 1
                return None, False
            evicted = 0
            while self._bytes + size > self.max_bytes and self._entries:
                _, cold = self._entries.popitem(last=False)
                self._bytes -= len(cold.payload)
                evicted += 1
            if evicted:
                self._evictions += evicted
                if self._on_evict is not None:
                    self._on_evict(evicted)
            entry = ResultEntry(
                digest=digest,
                payload=payload,
                etag=content_etag(payload),
                algo=algo,
                meta=dict(meta or {}),
            )
            self._entries[digest] = entry
            self._bytes += size
            self._fills += 1
            return entry, True

    def evict(self, digest: Optional[str] = None) -> int:
        """Drop one entry (or all when ``digest`` is None); returns count."""
        with self._lock:
            if digest is not None:
                entry = self._entries.pop(digest, None)
                if entry is None:
                    return 0
                self._bytes -= len(entry.payload)
                dropped = 1
            else:
                dropped = len(self._entries)
                self._entries.clear()
                self._bytes = 0
            if dropped:
                self._evictions += dropped
                if self._on_evict is not None:
                    self._on_evict(dropped)
            return dropped

    def ls(self) -> List[Dict[str, Any]]:
        """Entries hot-to-cold, as plain dicts (the admin-surface rows)."""
        with self._lock:
            rows = [
                {
                    "digest": e.digest,
                    "algo": e.algo,
                    "bytes": len(e.payload),
                    "etag": e.etag,
                    "hits": e.hits,
                    "age_s": round(time.time() - e.created, 3),
                    "meta": dict(e.meta),
                }
                for e in self._entries.values()
            ]
        rows.reverse()  # OrderedDict is cold-to-hot; present hot first
        return rows

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            lookups = self._hits + self._misses
            return {
                "enabled": True,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hits": self._hits,
                "misses": self._misses,
                "fills": self._fills,
                "evictions": self._evictions,
                "corrupt_evictions": self._corrupt_evictions,
                "oversize_rejects": self._oversize_rejects,
                "hit_ratio": (self._hits / lookups) if lookups else None,
            }
