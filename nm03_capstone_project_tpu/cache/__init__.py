"""Content-addressed result tier (ISSUE 19).

The executable cache (``compilehub/``) amortizes *compiles*; this package
amortizes *whole results*: a segmentation mask keyed on the sha256 of the
input bytes, the algorithm, its parameters, and the program version is
immutable by construction — the key changes whenever anything that could
change the answer changes, so invalidation is free and a stale result is
never an outcome (see docs/RESILIENCE.md).

jax- and numpy-free by contract (NM301-registered, like ``fleet/``): the
router embeds a :class:`ResultStore` in a process that must never pay a
jax import, and the replica-side store only ever holds opaque payload
bytes. The program-version half of the key is produced by
``compilehub.persist.result_version`` on the replica (which may import
jax) and travels to jax-free consumers over the wire (``/readyz``).

Lock discipline: NM331-scanned. Every class owning a sync primitive takes
it around all mutation outside ``__init__``.
"""

from nm03_capstone_project_tpu.cache.inflight import InflightIndex
from nm03_capstone_project_tpu.cache.keys import (
    ResultKey,
    digest_bytes,
    params_digest,
    result_key,
)
from nm03_capstone_project_tpu.cache.store import (
    ResultEntry,
    ResultStore,
    content_etag,
    etag_matches,
    parse_bytes,
)

__all__ = [
    "InflightIndex",
    "ResultEntry",
    "ResultKey",
    "ResultStore",
    "content_etag",
    "digest_bytes",
    "etag_matches",
    "params_digest",
    "parse_bytes",
    "result_key",
]
