"""Content-addressed result keys (ISSUE 19).

A result is addressed by *everything that could change it*:

    (input-bytes digest, algo, params digest, program version)

This is the ``compilehub/persist.py`` versioned-key contract extended one
level up — ``PersistKey`` pins toolchain versions so an executable can
never satisfy a lookup from a different program; ``ResultKey`` pins the
program version (which itself folds in the toolchain triple, see
``compilehub.persist.result_version``) so a cached *mask* can never be
served back by a different algorithm. Bump the algorithm and every entry
misses by construction: invalidation without TTLs, flush RPCs, or any
notion of staleness.

jax- and numpy-free: keys are pure hashing over bytes and JSON.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Optional

__all__ = ["ResultKey", "digest_bytes", "params_digest", "result_key"]


def digest_bytes(data: bytes) -> str:
    """sha256 of the raw input body — the content-address half of the key.

    Full hex: the input digest is the identity clients can precompute and
    the dedup window compares; truncation buys nothing here.
    """
    return hashlib.sha256(data).hexdigest()


def params_digest(params: Optional[Dict[str, Any]]) -> str:
    """Canonical digest of request parameters (mirrors ``config_digest``).

    ``None`` and ``{}`` collapse to the same digest on purpose: "no
    parameters" is one identity, however the caller spells it.
    """
    payload = json.dumps(
        params or {}, sort_keys=True, default=repr, separators=(",", ":")
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class ResultKey:
    """The four-tuple identity of one cacheable result.

    Frozen: a key is a value. ``digest()`` is the store/index address —
    32 hex chars of sha256 over the canonical JSON form, collision-safe
    at any plausible store size.
    """

    input_digest: str
    algo: str  # "segment" | "segment-volume"
    params_digest: str
    program_version: str

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    def digest(self) -> str:
        return hashlib.sha256(self.to_json().encode()).hexdigest()[:32]


def result_key(
    body: bytes,
    algo: str,
    params: Optional[Dict[str, Any]],
    program_version: str,
) -> ResultKey:
    """Build the key for one request: hash the body, digest the params."""
    return ResultKey(
        input_digest=digest_bytes(body),
        algo=algo,
        params_digest=params_digest(params),
        program_version=program_version,
    )
