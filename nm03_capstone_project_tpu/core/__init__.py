"""Core containers, shape policy, and backend identity."""

from nm03_capstone_project_tpu.core.backend import is_tpu_backend  # noqa: F401
from nm03_capstone_project_tpu.core.image import SliceBatch, valid_mask  # noqa: F401
from nm03_capstone_project_tpu.core.padding import pad_to_canvas  # noqa: F401
