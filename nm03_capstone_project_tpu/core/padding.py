"""Static-shape padding policy.

jit traces a program once per shape; DICOM slice sizes vary across the cohort,
so every slice is host-side padded (bottom/right, zeros) to a fixed canvas
before it reaches the device. The true dims travel with the pixels (see
:class:`~nm03_capstone_project_tpu.core.image.SliceBatch`) so downstream ops
can mask out padding.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from nm03_capstone_project_tpu.core.image import SliceBatch


def pad_to_canvas(
    arrays: Sequence[np.ndarray], canvas_hw: Tuple[int, int]
) -> SliceBatch:
    """Pad host-side 2D arrays to a common canvas and stack into a SliceBatch.

    Raises ValueError if any slice exceeds the canvas — choose a canvas at
    least as large as the biggest slice in the cohort (256 covers the TCIA
    Brain-Tumor-Progression T1+C series the reference targets).
    """
    h, w = canvas_hw
    batch = np.zeros((len(arrays), h, w), dtype=np.float32)
    dims = np.zeros((len(arrays), 2), dtype=np.int32)
    for i, a in enumerate(arrays):
        if a.ndim != 2:
            raise ValueError(f"slice {i}: expected 2D array, got shape {a.shape}")
        if a.shape[0] > h or a.shape[1] > w:
            raise ValueError(
                f"slice {i}: shape {a.shape} exceeds canvas {canvas_hw}"
            )
        batch[i, : a.shape[0], : a.shape[1]] = a.astype(np.float32)
        dims[i] = a.shape
    return SliceBatch(pixels=batch, dims=dims)
