"""Backend identity — the single home of the TPU platform allowlist.

Several dispatch sites pick an implementation by whether the default backend
is a real TPU (Pallas kernel lowering, MXU-vs-gather resampling). The
platform names live HERE exactly once: 'tpu', plus 'axon' (TPU behind the
development tunnel). A GPU or CPU backend must never pass this check —
Mosaic lowering crashes there, and the matmul render formulation loses to
the gather one.
"""

from __future__ import annotations

import jax

_TPU_PLATFORMS = ("tpu", "axon")


def is_tpu_backend() -> bool:
    """True iff the default jax backend is a real TPU (incl. tunneled)."""
    return jax.default_backend() in _TPU_PLATFORMS
