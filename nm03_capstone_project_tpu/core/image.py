"""Image containers.

The reference moves ``fast::Image`` shared_ptrs between pipeline stages (e.g.
``getOutputData<Image>(0)``, src/test/test_pipeline.cpp:45). On TPU the
equivalent is a pytree of arrays with **static shapes**: every slice is padded
to a fixed canvas and its true (height, width) ride along as data, so a single
compiled program serves slices of any size (DICOM dims vary across the
cohort) and a whole batch can be vmapped.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SliceBatch:
    """A batch of 2D slices padded to a common static canvas.

    Attributes:
      pixels: float32 array of shape (B, H, W) — padded pixel data. Padding
        values are 0 and must be ignored via :func:`valid_mask`.
      dims: int32 array of shape (B, 2) — the true (height, width) of each
        slice before padding.
    """

    pixels: jax.Array
    dims: jax.Array

    @property
    def batch(self) -> int:
        return self.pixels.shape[0]

    @property
    def canvas_hw(self) -> Tuple[int, int]:
        return self.pixels.shape[-2], self.pixels.shape[-1]

    def __getitem__(self, i) -> "SliceBatch":
        return SliceBatch(pixels=self.pixels[i], dims=self.dims[i])


def valid_mask(dims: jax.Array, canvas_hw: Tuple[int, int]) -> jax.Array:
    """Boolean mask of shape (..., H, W): True inside the true image extent.

    ``dims`` has shape (..., 2) holding (height, width); the mask marks pixels
    with row < height and col < width. Computed with broadcasted iota so it is
    jit-friendly for traced dims.
    """
    h, w = canvas_hw
    rows = jax.lax.broadcasted_iota(jnp.int32, (h, w), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (h, w), 1)
    height = dims[..., 0:1, None]  # (..., 1, 1)
    width = dims[..., 1:2, None]
    return (rows < height) & (cols < width)
