"""``nm03-cache`` — admin surface for the persistent executable cache.

The on-disk cache (:mod:`~.persist`) is self-defending at load time —
corrupt or stale entries are silent misses — but an operator still needs
to SEE it: what is in the directory, whether the entries a fleet depends
on actually verify, and a retention policy that does not require hand-rm.

Subcommands (docs/OPERATIONS.md, "Compile cache management"):

* ``ls``     — one row per entry: size, age, program/shape/device, the
  toolchain that built it, and its integrity status;
* ``verify`` — full checksum + toolchain validation; exit 1 when any
  entry is corrupt (stale entries are expected after an upgrade and do
  not fail the check — they report, and ``gc`` reclaims them);
* ``gc``     — retention: corrupt and stale entries always go (both can
  only ever miss for this toolchain), then anything older than
  ``--max-age``, then oldest-first until under ``--max-bytes``;
* ``result`` — the RESULT tier's admin surface (ISSUE 19): ``ls`` /
  ``stats`` / ``evict`` against a live replica's (or fleet front-end's)
  ``/debug/result-cache`` endpoint — the store lives in serving-process
  memory, so its admin path is HTTP (``--url``), not ``--dir``.

Diagnostics go to stderr, results to stdout (``--format json`` for
scripting) — the same discipline as the sibling CLIs. Exit codes:
0 ok, 1 findings (corrupt entries on ``verify``), 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from nm03_capstone_project_tpu.compilehub.persist import (
    ENTRY_SUFFIX,
    ENV_CACHE_DIR,
    cache_dir_from_env,
    gc_entries,
    scan_entries,
)


def _fmt_age(seconds: float) -> str:
    for unit, div in (("d", 86400.0), ("h", 3600.0), ("m", 60.0)):
        if seconds >= div:
            return f"{seconds / div:.1f}{unit}"
    return f"{seconds:.0f}s"


def _fmt_bytes(n: float) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.1f} {unit}"
    return f"{int(n)} B"


def _resolve_dir(arg_dir: Optional[str]) -> Path:
    # usage errors exit 2, never 1: a CI script must be able to tell "no
    # such directory" from "verify found corrupt entries"
    d = arg_dir or cache_dir_from_env()
    if not d:
        print(
            f"nm03-cache: no cache directory (pass --dir or set "
            f"${ENV_CACHE_DIR})",
            file=sys.stderr,
        )
        raise SystemExit(2)
    path = Path(d)
    if not path.is_dir():
        print(f"nm03-cache: {path} is not a directory", file=sys.stderr)
        raise SystemExit(2)
    return path


def _parse_bytes(text: str) -> int:
    """'512m', '2g', '100k' or plain bytes -> int."""
    t = text.strip().lower()
    mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}.get(t[-1:], None)
    if mult is not None:
        t = t[:-1]
    try:
        return int(float(t) * (mult or 1))
    except ValueError as e:
        raise argparse.ArgumentTypeError(
            f"bad byte size {text!r} (want e.g. 512m, 2g, 1048576)"
        ) from e


def _parse_age(text: str) -> float:
    """'7d', '12h', '30m', '90s' or plain seconds -> float seconds."""
    t = text.strip().lower()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}.get(t[-1:], None)
    if mult is not None:
        t = t[:-1]
    try:
        return float(t) * (mult or 1.0)
    except ValueError as e:
        raise argparse.ArgumentTypeError(
            f"bad age {text!r} (want e.g. 7d, 12h, 3600)"
        ) from e


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-cache", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument(
        "--dir",
        default=None,
        metavar="DIR",
        help=f"cache directory (default: ${ENV_CACHE_DIR})",
    )
    p.add_argument(
        "--format",
        choices=["text", "json"],
        default="text",
        help="output format",
    )
    sub = p.add_subparsers(dest="command", required=True)
    sub.add_parser("ls", help="list entries with size/age/identity/status")
    sub.add_parser(
        "verify",
        help="checksum + toolchain validation; exit 1 on corrupt entries",
    )
    gc = sub.add_parser("gc", help="apply the retention policy")
    gc.add_argument(
        "--max-bytes",
        type=_parse_bytes,
        default=None,
        metavar="N",
        help="total size budget (suffixes k/m/g); oldest entries beyond it go",
    )
    gc.add_argument(
        "--max-age",
        type=_parse_age,
        default=None,
        metavar="AGE",
        help="entry age cap (suffixes s/m/h/d)",
    )
    gc.add_argument(
        "--dry-run",
        action="store_true",
        help="report what WOULD be removed without touching the directory",
    )
    res = sub.add_parser(
        "result",
        help="administer a live process's content-addressed result store",
        description="The result tier's admin surface (ISSUE 19): ls/stats "
        "read GET /debug/result-cache on a replica started with "
        "--result-cache-bytes (or an nm03-fleet front-end); evict POSTs "
        "/debug/result-cache/evict — one --digest, or everything without "
        "it. Invalidation normally needs neither: the program version in "
        "every key retires stale results by construction.",
    )
    res.add_argument(
        "action", choices=["ls", "stats", "evict"],
        help="ls = entry rows hot-to-cold; stats = counters + hit ratio; "
        "evict = drop one --digest (or all entries when omitted)",
    )
    res.add_argument(
        "--url", required=True, metavar="URL",
        help="base URL of the replica or fleet front-end to administer",
    )
    res.add_argument(
        "--digest", default=None, metavar="D",
        help="result-key digest to evict (evict only; omit to drop all)",
    )
    res.add_argument(
        "--timeout-s", type=float, default=10.0, help="HTTP timeout",
    )
    return p


def _cmd_ls(rows: List[dict], fmt: str) -> int:
    if fmt == "json":
        print(json.dumps({"entries": rows}, indent=1))
        return 0
    if not rows:
        print("(empty cache)")
        return 0
    header = f"{'SIZE':>9}  {'AGE':>7}  {'STATUS':8}  {'JAXLIB':10}  ENTRY"
    print(header)
    for r in rows:
        ident = r["file"]
        if r.get("name"):
            shape = "x".join(str(d) for d in r["shape"] or [])
            ident = f"{r['name']}[{shape}] @{r.get('device') or r.get('platform')}"
        print(
            f"{_fmt_bytes(r['bytes']):>9}  {_fmt_age(r['age_s']):>7}  "
            f"{r['status']:8}  {r.get('jaxlib_version') or '?':10}  {ident}"
        )
    total = sum(r["bytes"] for r in rows)
    print(f"{len(rows)} entries, {_fmt_bytes(total)} total")
    return 0


def _cmd_verify(rows: List[dict], fmt: str) -> int:
    corrupt = [r for r in rows if r["status"] == "corrupt"]
    stale = [r for r in rows if r["status"] == "stale"]
    # reported but NOT a failure and never gc-fodder: the entry may be
    # healthy under the service uid (permissions mismatch, NFS blip)
    unreadable = [r for r in rows if r["status"] == "unreadable"]
    ok = len(rows) - len(corrupt) - len(stale) - len(unreadable)
    if fmt == "json":
        print(
            json.dumps(
                {
                    "entries": len(rows),
                    "ok": ok,
                    "stale": [r["file"] for r in stale],
                    "unreadable": [
                        {"file": r["file"], "error": r.get("error")}
                        for r in unreadable
                    ],
                    "corrupt": [
                        {"file": r["file"], "error": r.get("error")}
                        for r in corrupt
                    ],
                },
                indent=1,
            )
        )
    else:
        for r in corrupt:
            print(f"corrupt: {r['file']}: {r.get('error')}")
        for r in unreadable:
            print(f"unreadable: {r['file']}: {r.get('error')}")
        for r in stale:
            print(
                f"stale:   {r['file']}: built by "
                f"{'/'.join(str(r.get(f)) for f in ('jax_version', 'jaxlib_version', 'nm03_version'))}"
            )
        print(
            f"nm03-cache: {len(rows)} entries — "
            f"{ok} ok, {len(stale)} stale, {len(unreadable)} unreadable, "
            f"{len(corrupt)} corrupt"
        )
    return 1 if corrupt else 0


def _cmd_gc(root: Path, args: argparse.Namespace, fmt: str) -> int:
    report = gc_entries(
        root,
        max_bytes=args.max_bytes,
        max_age_s=args.max_age,
        dry_run=args.dry_run,
    )
    if fmt == "json":
        report["dry_run"] = args.dry_run
        print(json.dumps(report, indent=1))
        return 0
    verb = "would remove" if args.dry_run else "removed"
    for name in report["removed"]:
        print(f"{verb}: {name}")
    print(
        f"nm03-cache: {verb} {len(report['removed'])} entries "
        f"({_fmt_bytes(report['freed_bytes'])}); kept {report['kept']} "
        f"({_fmt_bytes(report['kept_bytes'])})"
    )
    return 0


def _cmd_result(args: argparse.Namespace) -> int:
    """The result tier's admin actions (ISSUE 19) — HTTP, never ``--dir``.

    Exit codes keep the sibling discipline: 0 ok, 2 usage/unreachable —
    a disabled tier is a usage error (the operator pointed the admin
    surface at a process that runs no store), never a silent empty list.
    """
    import urllib.error
    import urllib.request

    base = args.url.rstrip("/")
    try:
        if args.action == "evict":
            q = f"?digest={args.digest}" if args.digest else ""
            req = urllib.request.Request(
                f"{base}/debug/result-cache/evict{q}", data=b"",
                method="POST",
            )
        else:
            req = urllib.request.Request(
                f"{base}/debug/result-cache", method="GET"
            )
        with urllib.request.urlopen(req, timeout=args.timeout_s) as resp:
            payload = json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        detail = (e.read() or b"")[:200].decode(errors="replace")
        print(
            f"nm03-cache result: {base} answered HTTP {e.code}: {detail}",
            file=sys.stderr,
        )
        return 2
    except Exception as e:  # noqa: BLE001 — unreachable is a usage error
        print(f"nm03-cache result: {base} unreachable: {e}", file=sys.stderr)
        return 2
    if payload.get("enabled") is False:
        print(
            f"nm03-cache result: the result tier is disabled on {base} "
            "(start the process with --result-cache-bytes)",
            file=sys.stderr,
        )
        return 2
    if args.format == "json":
        print(json.dumps(payload, indent=1))
        return 0
    if args.action == "evict":
        print(
            f"nm03-cache result: evicted {payload.get('evicted')} entr"
            f"{'y' if payload.get('evicted') == 1 else 'ies'}"
        )
        return 0
    if args.action == "stats":
        hr = payload.get("hit_ratio")
        print(
            f"entries {payload.get('entries')}  "
            f"bytes {_fmt_bytes(payload.get('bytes') or 0)} / "
            f"{_fmt_bytes(payload.get('max_bytes') or 0)}  "
            f"hits {payload.get('hits')}  misses {payload.get('misses')}  "
            f"fills {payload.get('fills')}  "
            f"evictions {payload.get('evictions')} "
            f"(corrupt {payload.get('corrupt_evictions')})  "
            f"hit_ratio {'-' if hr is None else round(hr, 4)}  "
            f"program {payload.get('program_version') or '?'}"
        )
        return 0
    rows = payload.get("ls") or []
    if not rows:
        print("(empty result store)")
        return 0
    print(f"{'SIZE':>9}  {'AGE':>7}  {'HITS':>5}  {'ALGO':<15}  DIGEST")
    for r in rows:
        print(
            f"{_fmt_bytes(r['bytes']):>9}  {_fmt_age(r['age_s']):>7}  "
            f"{r['hits']:>5}  {r['algo']:<15}  {r['digest']}"
        )
    total = sum(r["bytes"] for r in rows)
    print(f"{len(rows)} entries, {_fmt_bytes(total)} total")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "result":
        # the result tier lives in a serving process, not a directory —
        # no --dir resolution, no filesystem scan
        return _cmd_result(args)
    root = _resolve_dir(args.dir)
    # one guard around every directory read: an unreadable dir is a usage
    # error (exit 2) on ANY subcommand, never a traceback or a fake
    # "findings" exit 1
    try:
        rows: List[dict] = []
        if args.command != "gc":
            # ls is header-only (length-checked, not hashed) — a listing
            # must not read a multi-GiB cache end to end; verify hashes.
            # gc scans inside gc_entries — scanning here too would read
            # the whole cache twice
            rows = scan_entries(root, checksum=args.command != "ls")
        stray = [
            p.name
            for p in root.iterdir()
            if p.is_file() and not p.name.endswith(ENTRY_SUFFIX)
            and not p.name.endswith(".tmp")  # gc reclaims orphaned temps
        ]
        if stray:
            print(
                f"nm03-cache: ignoring {len(stray)} non-cache file(s) in "
                f"{root} (e.g. {stray[0]})",
                file=sys.stderr,
            )
        if args.command == "ls":
            return _cmd_ls(rows, args.format)
        if args.command == "verify":
            return _cmd_verify(rows, args.format)
        return _cmd_gc(root, args, args.format)
    except OSError as e:
        print(f"nm03-cache: cannot read {root}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
