"""Persistent AOT executable cache — cold start in milliseconds.

Every serving replica, bench run and driver process used to re-pay full
compilation at warmup: PR 7's compile-cost accounting measured ~26.8 s
across 8 specs on CPU, and `/readyz` stayed 503 for exactly that long on
every restart. This module persists the hub's AOT executables to disk so
a *second* process start deserializes instead of compiling — the
OpenCLIPER thesis (PAPERS.md) applied to the compiler itself: amortize
device/compile overhead out of the startup path, not just the request
path (ROADMAP open item 2).

Layers:

* :class:`PersistKey` — the versioned cache-key **contract** (ImageCL's
  portability argument: a cache entry is only valid for the exact program
  identity + toolchain that built it, so the key covers every
  :class:`~.hub.CompileSpec` field plus the jax/jaxlib/nm03 versions and
  the device identity. nm03-lint rule NM381 statically enforces that no
  CompileSpec field is ever added without being folded in here).
* :class:`ExecutableCache` — the on-disk store behind
  :meth:`CompileHub.get`: ``store()`` serializes a compiled executable to
  ``<dir>/<key>.nm03exe`` via the ``utils/atomicio`` tmp+rename idiom;
  ``load()`` deserializes on a key-exact, checksum-verified hit. **Any**
  mismatch, unreadable header, truncated payload or deserialization
  failure is a silent miss that recompiles — a cache must never be able
  to crash (or corrupt) the process it exists to speed up.
* :func:`scan_entries` / :func:`gc_entries` — the ``nm03-cache`` admin
  CLI's workhorses (``ls`` / ``verify`` / ``gc --max-bytes/--max-age``).

Serialization formats, in preference order:

* ``pjrt-pickle`` — ``jax.experimental.serialize_executable``: the real
  compiled PJRT executable (plus pickled arg trees); loading it skips
  tracing, lowering AND XLA compilation entirely.
* ``jax-export`` — ``jax.export`` StableHLO serialization, the fallback
  where the PJRT executable is not serializable on this backend: loading
  skips tracing+lowering, and XLA re-compiles the pre-lowered module at
  first execute (paid inside warmup, never by a request). The export is
  device-id-agnostic, so device-pinned or buffer-donating specs refuse
  this format (no entry beats one that collapses every lane onto the
  default device) — on such backends they recompile every start.

Trust boundary: both formats deserialize via pickle/StableHLO loading,
which executes code paths that trust the bytes. The checksum defends
against *corruption*, not tampering — point ``--compile-cache-dir`` at a
directory with the same trust level as the installed packages
(docs/OPERATIONS.md, "Compile cache management").
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import pickle
import re
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from nm03_capstone_project_tpu.utils.atomicio import atomic_write_bytes

__all__ = [
    "ENTRY_SUFFIX",
    "ENV_CACHE_DIR",
    "ExecutableCache",
    "PersistKey",
    "attach_jax_compilation_cache",
    "cache_dir_from_env",
    "config_digest",
    "gc_entries",
    "jax_cache_stats",
    "result_version",
    "scan_entries",
]

SCHEMA = "nm03.exe.v1"
ENTRY_SUFFIX = ".nm03exe"
ENV_CACHE_DIR = "NM03_COMPILE_CACHE_DIR"
# opt-out for the jax-compilation-cache sidecar (below): the jax cache has
# misbehaved on exotic backends before (cli/common.enable_compile_cache's
# history) and an operator must be able to keep the nm03 executable cache
# while refusing the jax one
ENV_JAX_CACHE_OPT_OUT = "NM03_JAX_CACHE"
# subdirectory of the executable cache the jax compilation cache lives in
# (separate namespace: nm03 entries are *.nm03exe, jax writes its own
# layout — nm03-cache ls/verify/gc deliberately never touch it)
JAX_CACHE_SUBDIR = "jax"

# the configured jax compilation cache dir (None = never attached); module
# state because the jax config itself is process-global
_JAX_CACHE_LOCK = threading.Lock()
_JAX_CACHE_DIR: Optional[str] = None


def attach_jax_compilation_cache(root: "str | os.PathLike") -> Optional[str]:
    """Point jax's OWN persistent compilation cache at ``<root>/jax``.

    The nm03 executable cache (ISSUE 9) covers shape-pinned AOT specs;
    deferred-trace programs — the batch drivers' jit paths, the CPU
    fallback — still retraced and recompiled cold every process start.
    jax's builtin compilation cache (``jax_compilation_cache_dir``) closes
    exactly that gap, so attaching an ``--compile-cache-dir`` now wires
    both layers (ISSUE 10 satellite). Accounting stays SEPARATE by
    design: jax's cache hits shorten deferred first-call compiles but are
    never counted under ``compile_cache_*`` (those series are the ISSUE 9
    honesty split for *deserialized executables*) — ``jax_cache_*`` in
    ``/readyz``'s compile_hub block reports this layer's dir/entries/bytes.

    Returns the configured dir, or None when unavailable or refused via
    ``NM03_JAX_CACHE=0``. Idempotent; never raises (an optimization layer
    must not cost a start).
    """
    if os.environ.get(ENV_JAX_CACHE_OPT_OUT, "") == "0":
        return None
    global _JAX_CACHE_DIR
    path = os.path.join(str(root), JAX_CACHE_SUBDIR)
    with _JAX_CACHE_LOCK:
        if _JAX_CACHE_DIR == path:
            return path
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # deferred driver programs compile in ~seconds; the default 1 s
        # floor would skip caching exactly the cheap-but-numerous ones
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # jax lazily builds ONE cache object at the dir configured when
        # the first compile happens; a later config.update alone keeps
        # writing to the old dir — reset the singleton so re-attaching
        # (a second ServingApp in one process, tests) really re-points it
        try:
            from jax._src import compilation_cache as _jax_cc

            _jax_cc.reset_cache()
        except Exception:  # noqa: BLE001 — private surface; fresh processes
            pass  # never configured a dir before, so there is nothing stale
    except Exception as e:  # noqa: BLE001 — best-effort layer, never a crash
        from nm03_capstone_project_tpu.utils.reporter import get_logger

        get_logger("compilehub").warning(
            "jax compilation cache at %s unavailable (%s); deferred-trace "
            "programs recompile cold each start", path, e,
        )
        return None
    with _JAX_CACHE_LOCK:
        _JAX_CACHE_DIR = path
    return path


def jax_cache_stats() -> Dict[str, Any]:
    """The jax-compilation-cache sidecar's accounting (``jax_cache_*``).

    Entry/byte counts come from listing the dir (jax exposes no hit/miss
    counters); a growing entry count across starts is the evidence the
    deferred-trace layer is being warmed.
    """
    with _JAX_CACHE_LOCK:
        path = _JAX_CACHE_DIR
    out: Dict[str, Any] = {"jax_cache_dir": path}
    if path is None:
        return out
    entries = 0
    size = 0
    try:
        for dirpath, _dirnames, filenames in os.walk(path):
            for fname in filenames:
                entries += 1
                try:
                    size += os.stat(os.path.join(dirpath, fname)).st_size
                except OSError:
                    continue
    except OSError:
        pass
    out["jax_cache_entries"] = entries
    out["jax_cache_bytes"] = size
    return out

FORMAT_PJRT = "pjrt-pickle"
FORMAT_EXPORT = "jax-export"

# the key fields whose mismatch means "this entry was built by a different
# toolchain/package" — reported as `stale` (expected after an upgrade, the
# runbook's invalidation case) rather than `corrupt` (bit rot / torn write)
_VERSION_FIELDS = ("jax_version", "jaxlib_version", "nm03_version")

_SAFE_CHARS = re.compile(r"[^A-Za-z0-9_.-]+")


def cache_dir_from_env(environ=os.environ) -> Optional[str]:
    """The ``NM03_COMPILE_CACHE_DIR`` value, or None when unset/empty."""
    return environ.get(ENV_CACHE_DIR) or None


def config_digest(cfg: Any) -> str:
    """Stable digest of a pipeline config (or None) for the cache key.

    Dataclasses digest their sorted field dict — two configs that compare
    equal digest equal regardless of construction order; anything else
    falls back to ``repr`` (stable for the frozen configs this codebase
    uses; an unstable repr only costs a cache miss, never a wrong hit).
    """
    if cfg is None:
        payload = "none"
    elif dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        payload = json.dumps(
            dataclasses.asdict(cfg), sort_keys=True, default=repr
        )
    else:
        payload = repr(cfg)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _versions() -> Dict[str, str]:
    import jax
    import jaxlib

    try:
        from nm03_capstone_project_tpu import __version__ as nm03_version
    except Exception:  # noqa: BLE001 — a dev tree without metadata still caches
        nm03_version = "unknown"
    return {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "nm03_version": str(nm03_version),
    }


def result_version(cfg: Any = None) -> str:
    """The program-identity half of a RESULT-tier cache key (ISSUE 19).

    The executable cache's :class:`PersistKey` pins toolchain versions so
    an entry can never satisfy a lookup from a different program; the
    result tier (``nm03_capstone_project_tpu.cache``) extends the same
    contract one level up: a cached *mask* is only valid for the exact
    algorithm + toolchain + pipeline config that produced it. This digest
    — sha256 over the jax/jaxlib/nm03 version triple plus the config
    digest — is that identity: bump any of them and every stored result
    misses by construction (invalidation without TTLs or flush RPCs).

    Imports jax (via :func:`_versions`); callers in jax-free packages
    (fleet/, cache/) receive the string over the wire instead of calling
    this (the replica publishes it on ``/readyz``).
    """
    payload = {"cfg_digest": config_digest(cfg), **_versions()}
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class PersistKey:
    """The versioned identity of one on-disk executable — the contract.

    Built ONLY by :meth:`from_spec`, which must consume **every**
    :class:`~.hub.CompileSpec` field (nm03-lint NM381 fails the build the
    moment a spec field exists that this derivation does not read): a
    field that names two different programs but is absent from the key
    would hand one program the other's compiled binary, silently.
    """

    name: str
    variant: str
    shape: Optional[Tuple[int, ...]]
    mesh: Optional[str]
    device: Optional[str]
    device_kind: Optional[str]
    platform: str
    lane: Optional[int]
    backend: Optional[str]
    donate: bool
    cfg_digest: str
    jax_version: str
    jaxlib_version: str
    nm03_version: str

    @classmethod
    def from_spec(cls, spec: Any) -> "PersistKey":
        import jax

        device = spec.device
        mesh = spec.mesh
        return cls(
            name=spec.name,
            variant=spec.variant,
            shape=tuple(int(d) for d in spec.shape) if spec.shape else None,
            # the mesh descriptor, not the object — but axis sizes ALONE
            # are not an identity: two meshes of shape {'z': 4} over
            # different chips must not share an entry (the serialized
            # executable embeds the first mesh's device assignment), so
            # the device list rides along, same rationale as `device`
            mesh=(
                json.dumps(
                    {
                        "shape": dict(mesh.shape),
                        "devices": [
                            str(d) for d in getattr(mesh, "devices", []).flat
                        ]
                        if getattr(mesh, "devices", None) is not None
                        else [],
                    },
                    sort_keys=True,
                )
                if mesh is not None
                else None
            ),
            # str(device) carries backend + id ("TFRT_CPU_3"): a lane's
            # executable embeds its device assignment, so lane 3's entry
            # must never satisfy lane 0's lookup
            device=str(device) if device is not None else None,
            device_kind=(
                getattr(device, "device_kind", None)
                if device is not None
                else None
            ),
            platform=(
                getattr(device, "platform", None) or jax.default_backend()
            ),
            lane=spec.lane,
            backend=spec.backend,
            donate=bool(spec.donate),
            cfg_digest=config_digest(spec.cfg),
            **_versions(),
        )

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["shape"] = list(self.shape) if self.shape else None
        return d

    def digest(self) -> str:
        return hashlib.sha256(
            json.dumps(self.to_json(), sort_keys=True).encode()
        ).hexdigest()[:20]

    def filename(self) -> str:
        """``<readable-prefix>-<digest>.nm03exe`` — ls-able, collision-free.

        The digest alone is the identity; the prefix only exists so
        ``nm03-cache ls`` and a shell glob mean something to a human.
        """
        parts = [self.name]
        if self.shape:
            parts.append("x".join(str(d) for d in self.shape))
        if self.device is not None:
            parts.append(self.device)
        prefix = _SAFE_CHARS.sub("_", "-".join(parts))[:80]
        return f"{prefix}-{self.digest()}{ENTRY_SUFFIX}"


class CacheEntryError(Exception):
    """An unusable on-disk entry; ``kind`` classifies it for stats/CLI."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind  # corrupt | stale | mismatch


def _compose_entry(key: PersistKey, fmt: str, payload: bytes) -> bytes:
    header = {
        "schema": SCHEMA,
        "format": fmt,
        "key": key.to_json(),
        "payload_sha256": hashlib.sha256(payload).hexdigest(),
        "payload_len": len(payload),
        "created_unix": time.time(),
    }
    line = json.dumps(header, sort_keys=True).encode()
    if len(line) + 1 > _HEADER_CAP:
        # enforced at WRITE time so every reader may trust the cap: a
        # header the header-only scan would reject (and gc then delete)
        # must never be written as an entry load() would accept
        raise ValueError(
            f"entry header of {len(line)} bytes exceeds the "
            f"{_HEADER_CAP} cap (pathological key, e.g. a giant mesh "
            "device list) — entry not persisted"
        )
    return line + b"\n" + payload


def _parse_header(head: bytes) -> dict:
    """The one header grammar, shared by the full and header-only readers."""
    try:
        header = json.loads(head.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CacheEntryError("corrupt", f"unparseable header: {e}") from e
    if not isinstance(header, dict) or header.get("schema") != SCHEMA:
        raise CacheEntryError(
            "corrupt", f"bad schema {header.get('schema')!r}"
            if isinstance(header, dict) else "header is not an object"
        )
    return header


def _split_entry(raw: bytes) -> Tuple[dict, bytes]:
    """Parse header + verify checksum; CacheEntryError('corrupt') otherwise."""
    head, sep, payload = raw.partition(b"\n")
    if not sep:
        raise CacheEntryError("corrupt", "no header/payload separator")
    header = _parse_header(head)
    if header.get("payload_len") != len(payload):
        raise CacheEntryError(
            "corrupt",
            f"payload is {len(payload)} bytes, header says "
            f"{header.get('payload_len')} (truncated write?)",
        )
    if hashlib.sha256(payload).hexdigest() != header.get("payload_sha256"):
        raise CacheEntryError("corrupt", "payload checksum mismatch")
    return header, payload


def _classify_key_mismatch(want: dict, got: Any) -> CacheEntryError:
    if not isinstance(got, dict):
        return CacheEntryError("corrupt", "header key is not an object")
    drift = [
        f for f in _VERSION_FIELDS if got.get(f) != want.get(f)
    ]
    if drift:
        pairs = ", ".join(
            f"{f}={got.get(f)!r} (want {want.get(f)!r})" for f in drift
        )
        return CacheEntryError("stale", f"built by a different toolchain: {pairs}")
    return CacheEntryError(
        "mismatch",
        "key digest collision or tampered header (entry ignored)",
    )


def _deserialize(fmt: str, payload: bytes) -> Callable:
    """Payload -> callable executable; any failure raises (caller misses)."""
    if fmt == FORMAT_PJRT:
        from jax.experimental import serialize_executable

        serialized, in_tree, out_tree = pickle.loads(payload)
        return serialize_executable.deserialize_and_load(
            serialized, in_tree, out_tree
        )
    if fmt == FORMAT_EXPORT:
        import jax
        from jax import export

        exported = export.deserialize(bytearray(payload))
        # pre-lowered StableHLO: jit here only pays the XLA compile of the
        # serialized module at first call (inside warmup), never a retrace
        return jax.jit(exported.call)
    raise CacheEntryError("corrupt", f"unknown payload format {fmt!r}")


def _serialize(spec: Any, built: Any) -> Tuple[str, bytes]:
    """Compiled executable -> (format, payload); raises when unsupported."""
    try:
        from jax.experimental import serialize_executable

        serialized, in_tree, out_tree = serialize_executable.serialize(built)
        return FORMAT_PJRT, pickle.dumps((serialized, in_tree, out_tree))
    except Exception:  # noqa: BLE001 — fall through to the export form
        pass
    if spec.device is not None or spec.donate:
        # the StableHLO export is device-id-agnostic and reloads as a bare
        # jax.jit — a lane-pinned executable would silently collapse every
        # lane onto the default device (and donation would be dropped).
        # Better no entry at all: these specs recompile every start on
        # backends whose PJRT executables cannot serialize.
        raise RuntimeError(
            "export fallback cannot preserve device pinning/donation — "
            "spec not persisted"
        )
    src = getattr(built, "_nm03_export_src", None)
    if src is None:
        raise RuntimeError(
            "executable is not serializable on this backend and carries no "
            "export source (aot_compile attaches one)"
        )
    from jax import export

    jitted, arg_structs = src
    exported = export.export(jitted)(*arg_structs)
    return FORMAT_EXPORT, bytes(exported.serialize())


class ExecutableCache:
    """The on-disk executable store behind :meth:`CompileHub.get`.

    Thread-safe (warmup threads race through the hub); every failure mode
    is a counted miss, never an exception to the caller. ``fault_hook``
    is the chaos-injection point (resilience.FaultPlan site ``cache``,
    kind ``io_error``): called with the entry filename before a store
    writes, so drills prove a failed write degrades to a clean recompile.
    """

    def __init__(
        self,
        root: "str | os.PathLike",
        fault_hook: Optional[Callable[[str], None]] = None,
    ):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._fault_hook = fault_hook
        self._lock = threading.Lock()
        self._stats = {
            "hits": 0,
            "misses": 0,
            "corrupt": 0,
            "stale": 0,
            "stores": 0,
            "store_errors": 0,
            "bytes_read": 0,
            "bytes_written": 0,
            "load_seconds": 0.0,
        }

    def _bump(self, **deltas: float) -> None:
        with self._lock:
            for k, v in deltas.items():
                self._stats[k] += v

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._stats)
        out["load_seconds"] = round(out["load_seconds"], 4)
        return out

    def readyz_stats(self) -> Dict[str, float]:
        """The ``/readyz`` ``compile_hub`` cache fields (ISSUE 9)."""
        s = self.stats()
        return {
            "cache_hits": s["hits"],
            "cache_misses": s["misses"],
            "cache_bytes": s["bytes_read"] + s["bytes_written"],
            "cache_load_seconds": s["load_seconds"],
        }

    # -- load / store ------------------------------------------------------

    def load(self, spec: Any) -> Optional[Tuple[Callable, float, bool]]:
        """``(executable, load_seconds, aot)`` for the spec, or None.

        ``aot`` is True for the pjrt format (the real compiled binary —
        nothing left to compile) and False for the jax-export fallback,
        whose pre-lowered module still pays an XLA compile at first
        execute: the hub must account it like any other deferred spec,
        not report a compile the process will still pay as already free.

        None means MISS — absent, corrupt, stale, mismatched or
        undeserializable, each counted, none raised: the caller's
        recompile path is the recovery for every one of them.
        """
        t0 = time.perf_counter()
        try:
            key = PersistKey.from_spec(spec)
            path = self.root / key.filename()
            try:
                raw = path.read_bytes()
            except OSError:
                self._bump(misses=1)
                return None
            header, payload = _split_entry(raw)
            if header.get("key") != key.to_json():
                raise _classify_key_mismatch(key.to_json(), header.get("key"))
            fmt = header.get("format")
            fn = _deserialize(fmt, payload)
        except CacheEntryError as e:
            self._bump(
                misses=1, **({e.kind: 1} if e.kind in ("corrupt", "stale") else {})
            )
            _log().warning(
                "compile cache: ignoring %s entry for %s: %s",
                e.kind, getattr(spec, "name", spec), e,
            )
            return None
        except Exception as e:  # noqa: BLE001 — a cache must never crash a build
            self._bump(misses=1, corrupt=1)
            _log().warning(
                "compile cache: load failed for %s (recompiling): %s",
                getattr(spec, "name", spec), e,
            )
            return None
        load_s = time.perf_counter() - t0
        self._bump(hits=1, bytes_read=len(raw), load_seconds=load_s)
        return fn, load_s, fmt == FORMAT_PJRT

    def store(self, spec: Any, built: Any) -> bool:
        """Persist one compiled executable; False (counted) on any failure."""
        try:
            key = PersistKey.from_spec(spec)
            name = key.filename()
            if self._fault_hook is not None:
                self._fault_hook(name)
            fmt, payload = _serialize(spec, built)
            entry = _compose_entry(key, fmt, payload)
            atomic_write_bytes(self.root / name, entry)
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            self._bump(store_errors=1)
            _log().warning(
                "compile cache: store failed for %s (entry skipped): %s",
                getattr(spec, "name", spec), e,
            )
            return False
        self._bump(stores=1, bytes_written=len(entry))
        return True


def _log():
    from nm03_capstone_project_tpu.utils.reporter import get_logger

    return get_logger("compilehub")


# -- admin-surface helpers (nm03-cache) --------------------------------------


# One header-size contract, enforced at BOTH ends: _compose_entry refuses
# to write a header past the cap, so the header-only readers (ls/gc) may
# reject anything larger as corrupt without ever disagreeing with load()/
# verify about a valid entry. A real header is ~1 KiB.
_HEADER_CAP = 1 << 16


def _read_header_only(path: Path, file_size: int) -> dict:
    """Header + cheap length validation WITHOUT reading the payload.

    Catches every torn-write shape by size arithmetic (the file must be
    exactly header-line + newline + payload_len bytes); only same-length
    bit rot needs the full checksum (``nm03-cache verify``).
    """
    with open(path, "rb") as f:
        head = f.readline(_HEADER_CAP)
    if not head.endswith(b"\n"):
        raise CacheEntryError("corrupt", "no header/payload separator")
    header = _parse_header(head[:-1])
    want = len(head) + header.get("payload_len", -1)
    if want != file_size:
        raise CacheEntryError(
            "corrupt",
            f"file is {file_size} bytes, header promises {want} "
            "(truncated write?)",
        )
    return header


def scan_entries(
    root: "str | os.PathLike", checksum: bool = True
) -> List[Dict[str, Any]]:
    """One row per ``*.nm03exe`` file: header facts + integrity status.

    ``status`` is ``ok`` (parses, length — and with ``checksum`` the
    payload hash — verifies), ``stale`` (verifies but was built by a
    different jax/jaxlib/nm03 than THIS process), ``corrupt``, or
    ``unreadable`` (an I/O error reading it — possibly healthy, e.g. a
    permissions mismatch; gc keeps these).
    ``checksum=False`` reads only headers (``nm03-cache ls`` over a
    multi-GiB production cache must not hash every binary; length
    arithmetic still catches truncation). Never raises on entry content;
    an unreadable directory raises OSError to the caller (that is an
    operator error, not an entry).
    """
    rows: List[Dict[str, Any]] = []
    want_versions = None
    for path in sorted(Path(root).glob(f"*{ENTRY_SUFFIX}")):
        try:
            st = path.stat()
        except OSError:
            continue  # vanished between glob and stat (a concurrent gc)
        row: Dict[str, Any] = {
            "file": path.name,
            "bytes": st.st_size,
            "age_s": max(0.0, time.time() - st.st_mtime),
            "mtime": st.st_mtime,
        }
        try:
            try:
                if checksum:
                    header, _payload = _split_entry(path.read_bytes())
                else:
                    header = _read_header_only(path, st.st_size)
            except OSError as e:
                # EACCES/EIO/NFS blip — the ENTRY may be perfectly healthy
                # (e.g. gc running under an account that cannot read the
                # service uid's files). Distinct from corrupt on purpose:
                # gc deletes corrupt unconditionally, and destroying a
                # fleet's warm cache over a permissions problem is the
                # worst thing a janitor can do.
                row["status"] = "unreadable"
                row["error"] = f"{type(e).__name__}: {e}"
                rows.append(row)
                continue
            key = header.get("key") or {}
            row.update(
                {
                    "name": key.get("name"),
                    "shape": key.get("shape"),
                    "device": key.get("device"),
                    "platform": key.get("platform"),
                    "format": header.get("format"),
                    "jax_version": key.get("jax_version"),
                    "jaxlib_version": key.get("jaxlib_version"),
                    "nm03_version": key.get("nm03_version"),
                    "created_unix": header.get("created_unix"),
                }
            )
            if want_versions is None:
                want_versions = _versions()
            drift = [
                f for f in _VERSION_FIELDS
                if key.get(f) != want_versions[f]
            ]
            row["status"] = "stale" if drift else "ok"
            if drift:
                row["stale_fields"] = drift
        except CacheEntryError as e:
            row["status"] = "corrupt"
            row["error"] = str(e)
        except Exception as e:  # noqa: BLE001 — one bad entry never hides the rest
            row["status"] = "corrupt"
            row["error"] = f"{type(e).__name__}: {e}"
        rows.append(row)
    return rows


# how old an orphaned atomic-write temp must be before gc reclaims it: a
# real store's temp lives milliseconds, so anything past this is the
# leavings of a SIGKILL/OOM mid-store, not a writer in flight
TMP_ORPHAN_GRACE_S = 600.0


def gc_entries(
    root: "str | os.PathLike",
    max_bytes: Optional[int] = None,
    max_age_s: Optional[float] = None,
    dry_run: bool = False,
) -> Dict[str, Any]:
    """Delete dead and expired entries, then the oldest to the byte budget.

    Policy (docs/OPERATIONS.md): orphaned ``*.tmp`` files from killed
    atomic writes (older than :data:`TMP_ORPHAN_GRACE_S`) and corrupt AND
    stale entries always go — the latter two can only ever miss for THIS
    toolchain (the entry filename digest embeds the versions, so a new
    toolchain never even opens an old entry; do not run gc from one side
    of a cache dir deliberately shared by mixed-version fleets) — then
    anything older than ``max_age_s``, then oldest-mtime-first until
    total size fits ``max_bytes``. Retention needs only header facts
    (toolchain, length arithmetic, mtime, size), so the scan is
    header-only; same-length bit rot is already a self-defending miss at
    ``load()`` and ``nm03-cache verify``'s full checksum names it. Returns
    ``{"removed": [names], "freed_bytes": n, "kept": n, "kept_bytes": n}``.
    """
    rows = scan_entries(root, checksum=False)
    removed: List[str] = []
    freed = 0
    now = time.time()
    for tmp in sorted(Path(root).glob("*.tmp")):
        try:
            st = tmp.stat()
        except OSError:
            continue
        if now - st.st_mtime <= TMP_ORPHAN_GRACE_S:
            continue  # possibly a live writer's temp — not ours to take
        if not dry_run:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
        removed.append(tmp.name)
        freed += st.st_size

    def drop(row: Dict[str, Any]) -> None:
        nonlocal freed
        if not dry_run:
            with contextlib.suppress(OSError):
                os.unlink(Path(root) / row["file"])
        removed.append(row["file"])
        freed += row["bytes"]

    keep: List[Dict[str, Any]] = []
    protected: List[Dict[str, Any]] = []  # unreadable: NEVER gc-fodder
    for row in rows:
        if row["status"] == "unreadable":
            # possibly healthy, just not ours to read (perms/NFS blip) —
            # exempt from EVERY retention branch, age and byte budget
            # included: a wrong-uid gc cron with --max-age must not
            # mass-delete a fleet's warm cache
            protected.append(row)
        elif row["status"] in ("corrupt", "stale"):
            drop(row)
        elif max_age_s is not None and row["age_s"] > max_age_s:
            drop(row)
        else:
            keep.append(row)
    if max_bytes is not None:
        keep.sort(key=lambda r: r["mtime"])  # oldest first
        total = sum(r["bytes"] for r in keep)
        while keep and total > max_bytes:
            victim = keep.pop(0)
            total -= victim["bytes"]
            drop(victim)
    keep += protected
    return {
        "removed": removed,
        "freed_bytes": freed,
        "kept": len(keep),
        "kept_bytes": sum(r["bytes"] for r in keep),
    }
