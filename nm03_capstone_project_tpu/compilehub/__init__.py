"""compilehub — the single mesh-aware compile home for every chip.

Public surface:

* :func:`~.compat.shard_map` / :func:`~.compat.pjit` /
  :func:`~.compat.distributed_is_initialized` — the version-compat shim
  (the ONLY place those jax entry points may be named; nm03-lint NM361);
* :func:`~.hub.hub_jit` — the tracked ``jax.jit`` every call site uses;
* :class:`~.hub.CompileSpec` / :class:`~.hub.CompileHub` /
  :func:`~.hub.get_hub` — the registry of compile specs returning warm
  executables;
* :mod:`~.programs` — the named pipeline programs (slice/batch/volume/
  serve-lane), including :func:`~.programs.lane_devices` for the serving
  fleet's per-chip replica lanes;
* :class:`~.persist.ExecutableCache` / :class:`~.persist.PersistKey` —
  the persistent AOT executable cache (``nm03-serve
  --compile-cache-dir`` / ``$NM03_COMPILE_CACHE_DIR``): a second process
  start deserializes warm executables instead of compiling them.

Importing this package never initializes a backend; jax is paid for when
a program is built, not when the hub is named.
"""

from nm03_capstone_project_tpu.compilehub import programs
from nm03_capstone_project_tpu.compilehub.compat import (
    distributed_is_initialized,
    ensure_cpu_multiprocess_collectives,
    pjit,
    shard_map,
)
from nm03_capstone_project_tpu.compilehub.hub import (
    CompileHub,
    CompileSpec,
    aot_compile,
    executable_cost,
    get_hub,
    hub_jit,
)
from nm03_capstone_project_tpu.compilehub.persist import (
    ExecutableCache,
    PersistKey,
)

__all__ = [
    "CompileHub",
    "CompileSpec",
    "ExecutableCache",
    "PersistKey",
    "aot_compile",
    "executable_cost",
    "distributed_is_initialized",
    "ensure_cpu_multiprocess_collectives",
    "get_hub",
    "hub_jit",
    "pjit",
    "programs",
    "shard_map",
]
