"""The compile hub: one home for lowering, compiling, and caching.

Every pipeline callable this framework dispatches — the 2D slice programs,
the vmapped batch programs, the volume pipeline, the mesh-sharded z-shard
and data-parallel programs, the serving executor's per-bucket executables —
is compiled *here*, through one registry keyed by :class:`CompileSpec`.
Before this module, ``jax.jit`` call sites were scattered across ``ops/``,
``cli/runner.py``, ``cli/volume.py``, ``serving/executor.py`` and
``parallel/``, each with its own ``lru_cache`` and its own idea of
donation and warmup; OpenCLIPER's thesis (PAPERS.md) applies directly:
hoist device/compile management out of the request path into one
overhead-reduced home, so compilation policy (AOT vs deferred, donation,
device pinning, mesh placement) is decided once and observable in one
place.

Layers:

* :func:`hub_jit` — the tracked ``jax.jit`` wrapper every call site uses
  (nm03-lint NM361 bans naming ``jax.jit`` anywhere else, Pallas kernel
  wrappers excepted). Thin by design: it adds accounting, not semantics.
* :class:`CompileSpec` / :class:`CompileHub` — the registry of compile
  specs (program name, config, bucket shape, mesh, donation, backend,
  lane) returning cached warm executables. Builders run outside the lock;
  first completed build wins (the racing loser's executable is dropped,
  mirroring the serving executor's historical contract).
* :func:`aot_compile` — ``lower().compile()`` with the documented
  fallback: AOT is an optimization, not a contract, on backends where
  lowering at abstract shapes is unavailable.

The concrete pipeline programs live in :mod:`.programs`; mesh/sharding
version compatibility lives in :mod:`.compat`.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = [
    "CompileHub",
    "CompileSpec",
    "aot_compile",
    "get_hub",
    "hub_jit",
]

# The registry deliberately never evicts: dropping a warm serving
# executable mid-traffic is a recompile stall — the exact cliff the hub
# exists to prevent — and the lru_cache(maxsize=4..8) caches it replaced
# could do exactly that under a config sweep. Spec diversity is small and
# fixed in every production process (one cfg, a handful of buckets x
# lanes); a process that keeps minting NEW specs (unbounded cfg sweep in
# one process) is leaking executables, so the hub warns once past this
# soft cap instead of silently growing.
REGISTRY_SOFT_CAP = 64

# one-time flag: a jaxlib whose Compiled refuses attribute attach disables
# the persistent cache's export fallback — warned once, not per spec
_EXPORT_SRC_WARNED = False


@dataclasses.dataclass(frozen=True)
class CompileSpec:
    """Identity of one compiled executable in the hub's registry.

    ``name`` is the program family (``serve_mask``, ``batch_render``,
    ``zshard_volume`` ...); ``cfg`` the :class:`PipelineConfig` (hashable
    frozen dataclass) the program was specialized for; ``shape`` the
    static input shape the executable was AOT-compiled at (``None`` for
    deferred-trace callables that compile per call shape); ``mesh`` the
    device mesh for sharded programs; ``device`` the concrete device a
    pinned (replica-lane) executable is committed to — the DEVICE OBJECT,
    not its id: ids are only unique per backend, and two distinct devices
    colliding on one key would silently defeat the lane fan-out; ``lane``
    the human-facing lane index for display; ``backend`` a backend
    override (the CPU degradation target); ``donate`` whether the leading
    input's buffer is donated; ``variant`` a free-form discriminator.
    """

    name: str
    cfg: Any = None
    shape: Optional[Tuple[int, ...]] = None
    mesh: Any = None
    device: Any = None
    lane: Optional[int] = None
    backend: Optional[str] = None
    donate: bool = False
    variant: str = ""

    def describe(self) -> dict:
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape else None,
            "mesh": dict(self.mesh.shape) if self.mesh is not None else None,
            "device": str(self.device) if self.device is not None else None,
            "lane": self.lane,
            "backend": self.backend,
            "donate": self.donate,
            "variant": self.variant or None,
        }

    def label(self) -> str:
        """Compact stable series label for this spec — the ``spec`` label
        of the ``compile_seconds`` / ``executable_flops`` /
        ``executable_hbm_bytes`` gauges and the ``/readyz``
        ``compile_seconds`` map (bounded cardinality: the spec set is
        fixed per process by design, see REGISTRY_SOFT_CAP)."""
        parts = [self.name]
        if self.shape:
            parts.append("x".join(str(d) for d in self.shape))
        if self.lane is not None:
            parts.append(f"lane{self.lane}")
        if self.backend:
            parts.append(self.backend)
        if self.variant:
            parts.append(self.variant)
        return "/".join(parts)


def aot_compile(jitted: Callable, *arg_structs) -> Tuple[Callable, bool]:
    """``jitted.lower(*arg_structs).compile()`` with deferred fallback.

    Returns ``(executable, aot_ok)``. AOT means the executable exists the
    moment this returns — serve-time calls never trace; on backends where
    abstract lowering is unavailable the jitted callable itself is
    returned and the first call pays the compile (the historical serving
    behavior, kept as the documented fallback).
    """
    try:
        built = jitted.lower(*arg_structs).compile()
    except Exception:  # noqa: BLE001 — AOT is an optimization, not a contract
        return jitted, False
    try:
        # persist.py's jax-export fallback re-exports from the jitted
        # callable when the PJRT executable itself is not serializable on
        # this backend; attribute attach is best-effort (a jaxlib whose
        # Compiled refuses attributes loses the fallback format). The hub
        # deletes this after the store attempt; in a cache-less process
        # it retains only the jitted wrapper + arg structs (no extra
        # traced artifacts — the wrapper is lazy)
        built._nm03_export_src = (jitted, arg_structs)
    except Exception as e:  # noqa: BLE001 — see above
        global _EXPORT_SRC_WARNED
        if not _EXPORT_SRC_WARNED:
            # once, not per spec: without the source the persistent
            # cache's export fallback is silently unavailable, and a
            # process paying full compiles every start deserves one line
            # naming why
            _EXPORT_SRC_WARNED = True
            from nm03_capstone_project_tpu.utils.reporter import get_logger

            get_logger("compilehub").warning(
                "compiled executable refuses attribute attach (%s): the "
                "persistent cache's jax-export fallback is unavailable in "
                "this process", e,
            )
    return built, True


def executable_cost(built: Any) -> Dict[str, float]:
    """Best-effort ``cost_analysis()``/``memory_analysis()`` of a compiled
    executable, normalized to flat numeric fields.

    Only AOT executables (``jitted.lower().compile()`` results) expose
    these; deferred-trace callables return ``{}``. Every field is optional
    — jaxlib's analysis surface varies by version and backend — so callers
    treat presence as evidence, absence as "not exposed here", never as
    zero. ``peak_hbm_bytes`` is the arguments+outputs+temps resident set
    (aliased/donated bytes subtracted): the roofline denominator the bench
    records carry (ISSUE 7).
    """
    out: Dict[str, float] = {}
    try:
        ca = built.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jaxlib: one dict per device
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            for src_key, key in (("flops", "flops"),
                                 ("bytes accessed", "bytes_accessed")):
                v = ca.get(src_key)
                if v is not None:
                    out[key] = float(v)
    except Exception:  # noqa: BLE001 — analysis is evidence, not a contract
        pass
    try:
        ma = built.memory_analysis()
        parts = {}
        for attr, key in (
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("alias_size_in_bytes", "alias_bytes"),
            ("generated_code_size_in_bytes", "code_bytes"),
        ):
            v = getattr(ma, attr, None)
            if v is not None:
                parts[key] = float(v)
        out.update(parts)
        if {"argument_bytes", "output_bytes", "temp_bytes"} <= parts.keys():
            out["peak_hbm_bytes"] = (
                parts["argument_bytes"] + parts["output_bytes"]
                + parts["temp_bytes"] - parts.get("alias_bytes", 0.0)
            )
    except Exception:  # noqa: BLE001 — see above
        pass
    return out


class CompileHub:
    """Registry of compile specs returning warm executables.

    Thread-safe: handler/warmup threads race through :meth:`get` during
    serving startup, and the batch drivers' IO pools may trigger fallback
    builds concurrently. Builds run outside the lock (a compile can take
    seconds and must not serialize unrelated lookups); the first build to
    publish wins.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: Dict[CompileSpec, Callable] = {}
        self._aot: Dict[CompileSpec, bool] = {}
        # per-spec cost accounting (ISSUE 7): build wall-time always; the
        # XLA cost/memory analysis where the executable exposes it
        self._cost: Dict[CompileSpec, Dict[str, float]] = {}
        self._builds = 0
        self._cache_loads = 0
        self._jit_wraps = 0
        self._cap_warned = False
        # the persistent executable cache (compilehub/persist.py), attached
        # by nm03-serve --compile-cache-dir / $NM03_COMPILE_CACHE_DIR; None
        # = every miss compiles (the historical behavior)
        self._persist = None

    # -- the persistent layer ----------------------------------------------

    def attach_cache(self, cache) -> None:
        """Attach (or, with None, detach) the persistent executable cache.

        Attach BEFORE warmup: specs built earlier were not consulted
        against the disk and are not written back retroactively.
        Detaching from the PROCESS hub re-arms the one-shot
        ``$NM03_COMPILE_CACHE_DIR`` check, so a component detaching its
        own cache (ServingApp.close) hands the next :func:`get_hub`
        caller the env-requested cache back instead of silently disabling
        it for the rest of the process — the env resolution and its
        OSError degrade live HERE, in one place.
        """
        with self._lock:
            self._persist = cache
        if cache is None and self is _HUB:
            global _ENV_CACHE_CHECKED
            _ENV_CACHE_CHECKED = False
        if cache is not None and self is _HUB:
            # sidecar (ISSUE 10 satellite): the same dir also backs jax's
            # own compilation cache, so DEFERRED-trace programs (driver
            # jit paths, the CPU fallback) stop retracing cold each start.
            # Process-hub only — a test's private hub against a tmp dir
            # must not repoint the process-global jax config. Accounted
            # under jax_cache_*, never compile_cache_* (the ISSUE 9
            # honesty split covers deserialized executables only).
            from nm03_capstone_project_tpu.compilehub import persist

            persist.attach_jax_compilation_cache(cache.root)

    def persistent_cache(self):
        with self._lock:
            return self._persist

    # -- the registry ------------------------------------------------------

    def get(
        self, spec: CompileSpec, build: Callable[[CompileSpec], Callable]
    ) -> Callable:
        """The spec's executable: registry hit, persistent-cache load, or
        build — in that order, cheapest first.

        A persistent-cache load is accounted as a ``cache_load``, NEVER a
        build, and its cost dict carries ``load_s`` instead of
        ``compile_s`` — a deserialized executable must not report a fake
        compile cost (``total_compile_seconds`` is the promise ``/readyz``
        makes about what THIS process paid the compiler).
        """
        with self._lock:
            fn = self._cache.get(spec)
            persist = self._persist
        if fn is not None:
            return fn
        # only shape-pinned (AOT) specs are persistable: a deferred-trace
        # callable has no executable to serialize until first call, and a
        # lookup for one must not pollute the hit/miss accounting
        if persist is not None and spec.shape is not None:
            loaded = persist.load(spec)
            if loaded is not None:
                # aot False = the jax-export fallback format: pre-lowered,
                # but XLA still compiles at first execute — accounted like
                # any deferred spec (serving warmup times that), never as
                # a zero-cost compile
                fn, load_s, aot = loaded
                cost: Dict[str, float] = {"load_s": round(load_s, 4)}
                if aot:
                    cost.update(executable_cost(fn))
                return self._publish(spec, fn, aot_ok=aot, cost=cost,
                                     from_cache=True)
        t0 = time.perf_counter()
        built = build(spec)
        build_s = time.perf_counter() - t0
        if isinstance(built, tuple):  # (executable, aot_ok) from aot_compile
            built, aot_ok = built
        else:
            aot_ok = False
        # compile-cost accounting: the build wall covers lowering+compile
        # for AOT specs (deferred specs pay their compile at first call —
        # serving warmup times that separately); the XLA analyses only
        # exist on AOT executables
        cost = {"compile_s": round(build_s, 4)}
        if aot_ok:
            cost.update(executable_cost(built))
        out = self._publish(spec, built, aot_ok=aot_ok, cost=cost,
                            from_cache=False)
        if (
            persist is not None and aot_ok and spec.shape is not None
            and out is built  # the racing loser's twin is not worth a write
        ):
            persist.store(spec, built)
        # the export source is dead weight from here in EVERY case — a
        # spec stores at most once per process (first publisher wins), and
        # a cache attached after warmup never stores retroactively — so
        # drop it unconditionally: the never-evicting registry must not
        # pin jitted wrappers and their closures for the process lifetime
        if aot_ok:
            try:
                del built._nm03_export_src
            except AttributeError:
                pass
        return out

    def _publish(
        self,
        spec: CompileSpec,
        built: Callable,
        aot_ok: bool,
        cost: Dict[str, float],
        from_cache: bool,
    ) -> Callable:
        """First-publisher-wins registry insert + accounting + cap warning."""
        with self._lock:
            if spec not in self._cache:
                self._cache[spec] = built
                self._aot[spec] = aot_ok
                self._cost[spec] = cost
                if from_cache:
                    self._cache_loads += 1
                else:
                    self._builds += 1
            over_cap = (
                len(self._cache) > REGISTRY_SOFT_CAP and not self._cap_warned
            )
            if over_cap:
                self._cap_warned = True
            out = self._cache[spec]
        if over_cap:
            from nm03_capstone_project_tpu.utils.reporter import get_logger

            get_logger("compilehub").warning(
                "compile hub holds %d executables (> soft cap %d): specs "
                "keep differing — an unbounded cfg/mesh sweep in one "
                "process leaks executables; use hub.drop() for hot-swaps",
                len(self._cache), REGISTRY_SOFT_CAP,
            )
        return out

    def peek(self, spec: CompileSpec) -> Optional[Callable]:
        """The cached executable, or None — never builds (readiness probes)."""
        with self._lock:
            return self._cache.get(spec)

    def drop(self, spec: CompileSpec) -> None:
        """Evict one executable (tests; a config hot-swap would use this)."""
        with self._lock:
            self._cache.pop(spec, None)
            self._aot.pop(spec, None)
            self._cost.pop(spec, None)

    def jit(self, fn: Callable, **kwargs: Any) -> Callable:
        """The hub's ``jax.jit``: semantics untouched, creation counted."""
        import jax

        with self._lock:
            self._jit_wraps += 1
        return jax.jit(fn, **kwargs)

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Registry state for ``/readyz`` payloads and tests.

        ``total_compile_seconds`` is the warmup-cost rollup ISSUE 7's
        ``/readyz`` fix demands: what this process paid the compiler,
        visible without grepping logs. ``builds`` counts real compiles
        only; a persistent-cache hit counts under ``cache_loads`` and
        contributes NOTHING to ``total_compile_seconds`` (its
        deserialization wall lives in ``cache_load_seconds``) — the
        ISSUE 9 honesty split.
        """
        with self._lock:
            out = {
                "executables": len(self._cache),
                "aot": sum(1 for ok in self._aot.values() if ok),
                "builds": self._builds,
                "cache_loads": self._cache_loads,
                "jit_wraps": self._jit_wraps,
                "total_compile_seconds": round(
                    sum(c.get("compile_s", 0.0) for c in self._cost.values()), 4
                ),
            }
            persist = self._persist
        if persist is not None:
            out.update(persist.readyz_stats())
        if self is _HUB:
            # the jax-compilation-cache sidecar is process-global state, so
            # only the process hub reports it (a private test hub must not
            # claim another component's cache)
            from nm03_capstone_project_tpu.compilehub.persist import (
                jax_cache_stats,
            )

            out.update(jax_cache_stats())
        return out

    def compile_seconds(self) -> Dict[str, float]:
        """Per-spec compile wall-time, keyed by :meth:`CompileSpec.label`.

        Labels that collide (two cfg variants of one program family) sum —
        the map answers "what did warming THIS family/bucket/lane cost",
        not "enumerate every cfg hash".
        """
        with self._lock:
            items = [(k.label(), c.get("compile_s", 0.0)) for k, c in self._cost.items()]
        out: Dict[str, float] = {}
        for label, s in items:
            out[label] = round(out.get(label, 0.0) + s, 4)
        return out

    def cost_report(self) -> list:
        """Every spec's identity + compile cost + XLA cost/memory analysis
        (the ``/readyz`` detail, the serving cost gauges' source, and the
        bench records' roofline columns)."""
        with self._lock:
            items = [(k, dict(c)) for k, c in self._cost.items()]
        out = []
        for spec, cost in items:
            entry = spec.describe()
            entry["label"] = spec.label()
            entry.update(cost)
            if cost.get("flops") and cost.get("bytes_accessed"):
                entry["intensity_flops_per_byte"] = round(
                    cost["flops"] / cost["bytes_accessed"], 4
                )
            out.append(entry)
        out.sort(key=lambda e: e["label"])
        return out

    def specs(self) -> list:
        with self._lock:
            keys = list(self._cache)
        return [k.describe() for k in keys]


_HUB = CompileHub()
_ENV_CACHE_CHECKED = False


def get_hub() -> CompileHub:
    """The process-wide hub. One registry per process: executables are
    shared wherever the spec matches (two serving apps with one config
    warm once), and the spec's fields are exactly what may differ.

    ``$NM03_COMPILE_CACHE_DIR`` attaches the persistent executable cache
    on first use (checked once per process — set it before the first
    program builds; ``nm03-serve --compile-cache-dir`` attaches
    explicitly and wins over the environment).
    """
    global _ENV_CACHE_CHECKED
    if not _ENV_CACHE_CHECKED:
        _ENV_CACHE_CHECKED = True
        if _HUB.persistent_cache() is None:
            from nm03_capstone_project_tpu.compilehub import persist

            cache_dir = persist.cache_dir_from_env()
            if cache_dir:
                try:
                    _HUB.attach_cache(persist.ExecutableCache(cache_dir))
                except OSError as e:
                    from nm03_capstone_project_tpu.utils.reporter import (
                        get_logger,
                    )

                    get_logger("compilehub").warning(
                        "compile cache dir %s unusable (%s); running "
                        "without the persistent cache", cache_dir, e,
                    )
    return _HUB


def hub_jit(fn: Callable, **kwargs: Any) -> Callable:
    """Module-level alias of :meth:`CompileHub.jit` on the process hub —
    the one-line migration target for the historical ``jax.jit`` sites."""
    return _HUB.jit(fn, **kwargs)
