"""Version-compat shim over jax's partitioned-compilation entry points.

THE ONLY module in this codebase allowed to name ``shard_map`` / ``pjit``
(nm03-lint NM361 enforces it). The reason is recorded in the repo's own
history: the z-shard and distributed paths were written against the
promoted ``jax.shard_map`` API and 8 tier-1 tests failed from the seed
onward on a jaxlib that only ships ``jax.experimental.shard_map`` — an
AttributeError that sat unnoticed precisely because the call sites were
scattered. One shim, resolved once, means an API migration is a one-file
change and a version drift is a loud import-time error here, not a
mid-cohort crash three layers down.

Resolution order (cached after first use):

* ``shard_map`` — the promoted ``jax.shard_map`` (keyword ``check_vma``)
  when present, else ``jax.experimental.shard_map.shard_map`` (the same
  knob spelled ``check_rep``). Callers always write ``check_vma=``; the
  shim translates.
* ``pjit`` — ``jax.experimental.pjit.pjit`` when present, else ``jax.jit``
  (on modern jax they are the same function; the alias keeps old call
  sites compiling).
* ``distributed_is_initialized`` — ``jax.distributed.is_initialized`` when
  present, else a fenced probe of the runtime's global distributed state
  (absent on older jax, where only ``initialize``/``shutdown`` exist).

Everything resolves lazily inside the functions so importing this module
never initializes a backend.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

# (kind, callable) caches — resolved on first use, stable for the process
_SHARD_MAP: Optional[Tuple[str, Callable]] = None
_PJIT: Optional[Callable] = None


def _resolve_shard_map() -> Tuple[str, Callable]:
    global _SHARD_MAP
    if _SHARD_MAP is None:
        import jax

        impl = getattr(jax, "shard_map", None)
        if impl is not None:
            _SHARD_MAP = ("check_vma", impl)
        else:
            from jax.experimental.shard_map import shard_map as impl

            _SHARD_MAP = ("check_rep", impl)
    return _SHARD_MAP


def shard_map(
    fn: Callable,
    *,
    mesh,
    in_specs,
    out_specs,
    check_vma: bool = True,
) -> Callable:
    """``shard_map`` under either spelling of the replication-check knob.

    ``check_vma`` follows the promoted API's name; on a jax that only has
    the experimental entry point it is passed through as ``check_rep``
    (same semantics: verify per-output replication claims).
    """
    knob, impl = _resolve_shard_map()
    kwargs = {knob: check_vma}
    return impl(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def pjit(fn: Callable, **kwargs: Any) -> Callable:
    """``pjit`` where it exists, ``jax.jit`` where they have merged."""
    global _PJIT
    if _PJIT is None:
        import jax

        try:
            from jax.experimental.pjit import pjit as impl
        except ImportError:  # modern jax: pjit IS jit
            impl = jax.jit
        _PJIT = impl
    return _PJIT(fn, **kwargs)


def ensure_cpu_multiprocess_collectives() -> bool:
    """Best-effort cross-process collectives for the CPU backend (gloo).

    On jaxlibs of this vintage a multi-process job on the CPU backend
    fails at dispatch with "Multiprocess computations aren't implemented
    on the CPU backend" unless the gloo collectives implementation is
    selected BEFORE the backend initializes. Newer jax selects it
    automatically (and may drop the knob entirely), and an operator may
    have chosen mpi explicitly — so this sets gloo only when the knob
    exists and still holds its empty default, and reports False (never
    raises) otherwise. Called by ``parallel.distributed.initialize`` on
    the join path; harmless on accelerator backends (the knob only
    affects CPU backend creation).
    """
    import jax

    try:
        current = getattr(jax.config, "jax_cpu_collectives_implementation", None)
        if current:  # operator already chose (gloo/mpi) — respect it
            return True
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except Exception:  # noqa: BLE001 — knob moved/removed; newer jax auto-selects
        return False


def distributed_is_initialized() -> bool:
    """True once this process has joined a ``jax.distributed`` job.

    ``jax.distributed.is_initialized`` only exists on newer jax; older
    releases expose the same fact through the private global state. The
    probe is fenced: if the private layout moved too, report False and let
    the caller's own idempotence flag (``parallel.distributed``) carry the
    second-call no-op.
    """
    import jax

    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # noqa: BLE001 — private layout moved; undetermined
        return False
