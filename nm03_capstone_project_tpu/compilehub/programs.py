"""Named pipeline programs, built and cached through the hub.

The drivers' historical per-file ``functools.lru_cache`` compile caches
(``cli/runner.py`` held four, ``cli/volume.py`` three, the serving
executor one per bucket) collapse into these builders: each public getter
makes a :class:`~.hub.CompileSpec` and asks the process hub, so every
layer that dispatches compute shares one registry, one cache policy, and
one accounting surface.

Program families:

* ``slice_*`` — one slice through the 2D pipeline (sequential driver);
* ``batch_*`` — the vmapped fixed-shape batch programs (parallel driver),
  leading input donated where the host keeps its own copy;
* ``volume_*`` — the 3D pipeline with fused/deferred render variants;
* ``serve_mask`` — the serving executor's mask-only bucket program, AOT
  lowered+compiled at the bucket shape and (for the sharded fleet) pinned
  to one replica-lane device, so one ``nm03-serve`` process drives every
  chip with per-chip executables instead of one single-device program.

Everything imports jax lazily: building a program is the moment a backend
is paid for, never importing this module.
"""

from __future__ import annotations

from typing import List, Optional

from nm03_capstone_project_tpu.compilehub.hub import (
    CompileSpec,
    aot_compile,
    get_hub,
    hub_jit,
)

__all__ = [
    "batch_pipeline",
    "lane_devices",
    "serve_mask",
    "serve_volume",
    "slice_pipeline",
    "volume_pipeline",
]


# -- replica-lane planning ---------------------------------------------------


def lane_devices(lanes: Optional[int] = None, backend: Optional[str] = None) -> List:
    """The serving fleet's replica-lane devices (one lane = one chip).

    Local devices only: in a multi-process job each serving replica owns
    its own chips (the admission tier spreads traffic across replicas).
    ``lanes`` caps the count (``nm03-serve --lanes``); None or 0 takes
    every local device.
    """
    import jax

    devs = jax.local_devices() if backend is None else jax.local_devices(
        backend=backend
    )
    if lanes is not None and lanes > 0:
        if lanes > len(devs):
            raise ValueError(
                f"requested {lanes} lanes, only {len(devs)} local devices"
            )
        devs = devs[:lanes]
    return list(devs)


# -- 2D slice programs -------------------------------------------------------


def slice_pipeline(cfg, render: bool = True):
    """One-slice program: pipeline (+ on-device render pair when ``render``)."""

    def build(spec: CompileSpec):
        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        if spec.variant == "render":
            from nm03_capstone_project_tpu.render.render import render_pair

            def f(pixels, dims):
                out = process_slice(pixels, dims, spec.cfg)
                gray, seg = render_pair(out["original"], out["mask"], dims, spec.cfg)
                return gray, seg, out["grow_converged"]

        else:

            def f(pixels, dims):
                out = process_slice(pixels, dims, spec.cfg)
                return out["mask"], out["grow_converged"]

        return hub_jit(f)

    spec = CompileSpec(
        name="slice_pipeline",
        cfg=cfg,
        variant="render" if render else "mask",
    )
    return get_hub().get(spec, build)


def batch_pipeline(cfg, render: bool = False):
    """Vmapped fixed-shape batch program (the parallel driver's dispatch).

    The mask-only variant donates the pixel stack (the host keeps its own
    copy for rendering); the render variant cannot donate nothing less —
    the pixels die after the pipeline reads them either way, so both
    donate the leading input.
    """

    def build(spec: CompileSpec):
        import jax

        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        if spec.variant == "render":
            from nm03_capstone_project_tpu.render.render import render_pair

            def one(pixels, dims):
                out = process_slice(pixels, dims, spec.cfg)
                gray, seg = render_pair(out["original"], out["mask"], dims, spec.cfg)
                return gray, seg, out["grow_converged"]

        else:

            def one(pixels, dims):
                out = process_slice(pixels, dims, spec.cfg)
                return out["mask"], out["grow_converged"]

        return hub_jit(jax.vmap(one), donate_argnums=(0,))

    spec = CompileSpec(
        name="batch_pipeline",
        cfg=cfg,
        donate=True,
        variant="render" if render else "mask",
    )
    return get_hub().get(spec, build)


# -- 3D volume programs ------------------------------------------------------


def volume_pipeline(cfg, variant: str = "render"):
    """The volume driver's programs, one per export layout.

    ``render`` — mask + vmapped render pair in one program (one dispatch
    per patient); ``mask`` — mask-only (host-render export fetches 65
    KB/plane, not two rendered canvases); ``render_only`` — the deferred
    (vol, mask, dims) -> (gray, seg) render used by the z-shard/student
    paths whose compute ran elsewhere.
    """
    if variant not in ("render", "mask", "render_only"):
        raise ValueError(f"unknown volume program variant {variant!r}")

    def build(spec: CompileSpec):
        import jax

        if spec.variant == "render":
            from nm03_capstone_project_tpu.pipeline.volume_pipeline import (
                process_volume,
            )
            from nm03_capstone_project_tpu.render.render import render_pair

            def f(vol, dims):
                out = process_volume(vol, dims, spec.cfg)
                gray, seg = jax.vmap(
                    lambda p, m: render_pair(p, m, dims, spec.cfg)
                )(vol, out["mask"])
                return out["mask"], gray, seg, out["grow_converged"]

        elif spec.variant == "mask":
            from nm03_capstone_project_tpu.pipeline.volume_pipeline import (
                process_volume,
            )

            def f(vol, dims):
                out = process_volume(vol, dims, spec.cfg)
                return out["mask"], out["grow_converged"]

        else:  # render_only
            from nm03_capstone_project_tpu.render.render import render_pair

            def f(vol, mask, dims):
                return jax.vmap(lambda p, m: render_pair(p, m, dims, spec.cfg))(
                    vol, mask
                )

        return hub_jit(f)

    spec = CompileSpec(name="volume_pipeline", cfg=cfg, variant=variant)
    return get_hub().get(spec, build)


# -- serving: per-lane bucket executables ------------------------------------


def serve_mask(cfg, bucket: Optional[int] = None, device=None):
    """The serving executor's mask-only batch program.

    With ``bucket`` the program is AOT lowered+compiled at the bucket
    shape (the executable exists the moment this returns — serve-time
    calls never trace), and with ``device`` it is pinned to that replica
    lane via ``SingleDeviceSharding``: inputs commit to the lane's chip
    and outputs stay there until the supervised fetch, so N lanes dispatch
    N batches genuinely concurrently instead of queueing on device 0's
    stream. Without ``bucket`` (the CPU degradation target) the deferred
    jitted callable is returned: XLA retraces per shape, acceptable on the
    degraded path where correct-but-slower is the contract.
    """

    def build(spec: CompileSpec):
        import jax
        import jax.numpy as jnp

        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        def one(px, dm):
            out = process_slice(px, dm, spec.cfg)
            return out["mask"], out["grow_converged"]

        kwargs = {}
        if device is not None:
            from jax.sharding import SingleDeviceSharding

            sh = SingleDeviceSharding(device)
            kwargs = {"in_shardings": sh, "out_shardings": sh}
        # no donation: a supervised retry re-runs the primary with the SAME
        # host arrays, and serving's per-batch HBM footprint is tiny
        fn = hub_jit(jax.vmap(one), **kwargs)
        if spec.shape is None:
            return fn
        c = spec.cfg.canvas
        b = spec.shape[0]
        return aot_compile(
            fn,
            jax.ShapeDtypeStruct((b, c, c), jnp.float32),
            jax.ShapeDtypeStruct((b, 2), jnp.int32),
        )

    spec = CompileSpec(
        name="serve_mask",
        cfg=cfg,
        shape=(int(bucket), cfg.canvas, cfg.canvas) if bucket else None,
        # keyed on the DEVICE OBJECT (hashable): device ids are only
        # unique per backend, and a collision would silently hand lane N
        # an executable pinned to another chip
        device=device,
        lane=getattr(device, "id", None) if device is not None else None,
        variant="pinned" if device is not None else "",
    )
    return get_hub().get(spec, build)


# -- serving: the whole-volume gang program ----------------------------------


def serve_volume(cfg, depth: int, mesh):
    """The volume gang's z-sharded program (ISSUE 15), one per depth bucket.

    The SAME shard_map'd halo-exchanged region-growing program
    ``nm03-volume --z-shard`` dispatches
    (:func:`~nm03_capstone_project_tpu.parallel.zshard.zshard_volume_callable`),
    AOT lowered+compiled at ``(depth, canvas, canvas)`` over ``mesh`` so a
    volume request never pays trace+compile online, and shape-pinned so
    the persistent cache (PR 9) keeps the mesh executable warm across
    restarts. ``depth`` must divide the mesh's ``z`` axis evenly (the
    gang pads the study's stack to the bucket before dispatch). Returns
    the executable computing ``{'original', 'mask', 'grow_converged'}``.
    """

    def build(spec: CompileSpec):
        import jax
        import jax.numpy as jnp

        from nm03_capstone_project_tpu.parallel.zshard import (
            zshard_volume_callable,
        )

        fn = hub_jit(zshard_volume_callable(spec.mesh, spec.cfg))
        d = spec.shape[0]
        c = spec.cfg.canvas
        return aot_compile(
            fn,
            jax.ShapeDtypeStruct((d, c, c), jnp.float32),
            jax.ShapeDtypeStruct((2,), jnp.int32),
        )

    if depth % mesh.shape["z"] != 0:
        raise ValueError(
            f"volume depth bucket {depth} not divisible by z-axis size "
            f"{mesh.shape['z']}"
        )
    spec = CompileSpec(
        name="serve_volume",
        cfg=cfg,
        shape=(int(depth), cfg.canvas, cfg.canvas),
        mesh=mesh,
    )
    return get_hub().get(spec, build)
