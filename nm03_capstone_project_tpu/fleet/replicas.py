"""Replica-level fault domains: ejection, probation, reinstatement.

PR 8 shrank the serving fault domain from the process to the lane; this
module (ISSUE 13) applies the same state machine one level up, to whole
``nm03-serve`` replicas behind the ``nm03-fleet`` front-end:

* **HEALTHY** — the replica takes proxied traffic (the router's
  capacity-weighted pick runs over exactly these targets);
* **EJECTED** — the replica's health poll timed out, refused the
  connection, answered 503, or reported zero capacity — or a proxied
  request died on it mid-flight; it takes no traffic and its in-flight
  riders fail over to healthy replicas;
* **PROBATION** — the health loop has claimed the replica and is sending
  an off-path canary request (a real ``POST /v1/segment`` on a synthetic
  slice); success reinstates it to HEALTHY, failure returns it to
  EJECTED (cause ``probe_failed``).

Unlike the lane machine there is no ``retired`` terminal state: a fleet
whose every replica is ejected keeps polling and answers 503 + Retry-After
meanwhile — replicas are processes, and processes come back (that is the
whole point of the rolling-restart orchestration in ``fleet.manager``).

Every transition is observable: ``fleet_replica_state{replica}`` (0
healthy, 1 probation, 2 ejected), ``fleet_replica_ejections_total
{replica,cause}``, ``fleet_replica_reinstated_total{replica}``, WARNING
``replica_ejected`` / INFO ``replica_reinstated`` events, and
flight-recorder marks. The replica label is the target's ``host:port`` —
stable across that replica's restarts, unlike the per-incarnation ``id``
the ``/readyz`` identity block reports (which rides the events instead).

jax- AND numpy-free at import by contract (NM301 pins the whole
``fleet`` package): the router must come up — and its state machine be
unit-testable — in a process that never pays a backend import. Shared
state is lock-guarded (NM331 scans the package).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from nm03_capstone_project_tpu.obs import flightrec
from nm03_capstone_project_tpu.obs.metrics import (
    FLEET_REPLICA_CAPACITY,
    FLEET_REPLICA_EJECTIONS_TOTAL,
    FLEET_REPLICA_REINSTATED_TOTAL,
    FLEET_REPLICA_STATE,
)
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("fleet")

HEALTHY = "healthy"
PROBATION = "probation"
EJECTED = "ejected"

REPLICA_STATE_VALUES = {HEALTHY: 0, PROBATION: 1, EJECTED: 2}


def normalize_target(target: str) -> str:
    """``host:port`` / ``http://host:port[/]`` -> the base URL (no slash)."""
    t = target.strip().rstrip("/")
    if "://" not in t:
        t = f"http://{t}"
    return t


def target_label(target: str) -> str:
    """The bounded metric label for one target: ``host:port``.

    Stable across the replica's restarts (unlike its ``/readyz`` identity
    ``id``), so the per-replica series survive a rolling redeploy.
    """
    url = normalize_target(target)
    return url.split("://", 1)[1]


class ReplicaStates:
    """The per-replica state machine + last-known health signals.

    One instance per :class:`fleet.router.FleetApp`. Transitions mirror
    ``serving/lanes.py``'s lane machine (all lock-guarded; mutators
    return what the caller needs without re-reading state):

    ``eject(target, cause)`` — HEALTHY → EJECTED; idempotent for any
    target already out of the healthy set (a proxied request failing on
    a replica the health poll already ejected is the same incident).
    Returns ``(changed, healthy_remaining)``.

    ``begin_probation(target)`` — EJECTED → PROBATION; the health loop's
    exclusive canary claim.

    ``reinstate(target)`` — PROBATION → HEALTHY (the canary passed).

    ``fail_probation(target)`` — PROBATION → EJECTED (cause
    ``probe_failed``, counted as a fresh ejection).

    ``update_signals(target, ...)`` records the replica's own published
    routing signals (``/readyz`` capacity, queue depth/capacity, the
    identity block) — the inputs to the router's capacity-weighted pick.
    """

    def __init__(self, targets: Sequence[str], obs=None):
        urls = [normalize_target(t) for t in targets]
        if not urls:
            raise ValueError("a fleet needs at least one replica target")
        if len(set(urls)) != len(urls):
            raise ValueError(f"duplicate replica targets in {list(targets)}")
        self._lock = threading.Lock()
        self._targets: List[str] = urls
        self._states: Dict[str, str] = {t: HEALTHY for t in urls}
        self._causes: Dict[str, Optional[str]] = {t: None for t in urls}
        self._ejections: Dict[str, int] = {t: 0 for t in urls}
        self._signals: Dict[str, dict] = {t: {} for t in urls}
        self.obs = obs
        # the gauge series exist from construction on, so a drill can
        # assert `fleet_replica_state{replica=host:port}=0` and
        # distinguish "healthy" from "never reported"
        for t in urls:
            self._set_state_gauge(t, HEALTHY)

    # -- reads -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._targets)

    @property
    def targets(self) -> List[str]:
        return list(self._targets)

    def state(self, target: str) -> str:
        with self._lock:
            return self._states[target]

    def cause(self, target: str) -> Optional[str]:
        with self._lock:
            return self._causes[target]

    def is_healthy(self, target: str) -> bool:
        with self._lock:
            return self._states[target] == HEALTHY

    def healthy_targets(self) -> List[str]:
        with self._lock:
            return [t for t in self._targets if self._states[t] == HEALTHY]

    def targets_in(self, state: str) -> List[str]:
        with self._lock:
            return [t for t in self._targets if self._states[t] == state]

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s == HEALTHY)

    def ejected_count(self) -> int:
        """Targets currently out of the healthy set (ejected OR under
        probation — neither takes traffic)."""
        with self._lock:
            return sum(1 for s in self._states.values() if s != HEALTHY)

    def signals(self, target: str) -> dict:
        with self._lock:
            return dict(self._signals[target])

    def weight(self, target: str) -> float:
        """The routing weight: published capacity × queue headroom.

        ``capacity`` is the replica's own healthy-lane fraction (PR 8);
        headroom is ``1 - queue_depth/queue_capacity`` (PR 4's bounded
        admission queue). Missing signals default to 1.0 — a replica
        that predates a field is weighted, not starved.
        """
        with self._lock:
            sig = self._signals[target]
        cap = sig.get("capacity")
        cap = 1.0 if cap is None else max(float(cap), 0.0)
        depth, qcap = sig.get("queue_depth"), sig.get("queue_capacity")
        headroom = 1.0
        if depth is not None and qcap:
            headroom = max(1.0 - float(depth) / float(qcap), 0.0)
        return cap * headroom

    def capacity_fraction(self) -> float:
        """The fleet's routed capacity: mean healthy-replica capacity.

        Each healthy replica contributes its own published ``capacity``
        (1.0 when unreported), ejected/probation ones contribute 0 — so
        one dead replica of three reads 2/3, and a surviving replica
        running at 3-of-4 lanes drags the fleet to its true fraction.
        """
        with self._lock:
            total = 0.0
            for t in self._targets:
                if self._states[t] != HEALTHY:
                    continue
                cap = self._signals[t].get("capacity")
                total += 1.0 if cap is None else max(float(cap), 0.0)
            return total / len(self._targets)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "target": t,
                    "replica": target_label(t),
                    "state": self._states[t],
                    "cause": self._causes[t],
                    "ejections": self._ejections[t],
                    "capacity": self._signals[t].get("capacity"),
                    "queue_depth": self._signals[t].get("queue_depth"),
                    "queue_capacity": self._signals[t].get("queue_capacity"),
                    "identity": self._signals[t].get("identity"),
                    "clock_offset_s": self._signals[t].get("clock_offset_s"),
                    "result_version": self._signals[t].get("result_version"),
                }
                for t in self._targets
            ]

    # -- transitions -------------------------------------------------------

    def eject(self, target: str, cause: str):
        """HEALTHY → EJECTED; ``(changed, healthy_left)``.

        Idempotent unless the target is HEALTHY: a proxied request
        failing on a replica the health poll already ejected (or the
        probation canary currently owns) is the same physical incident —
        counting it again would double-book one outage, and flipping
        PROBATION back would steal the canary claim mid-probe.
        """
        with self._lock:
            if target not in self._states:
                raise KeyError(f"unknown replica target {target!r}")
            if self._states[target] != HEALTHY:
                changed = False
            else:
                self._transition_to_ejected(target, cause)
                changed = True
            healthy_left = sum(
                1 for s in self._states.values() if s == HEALTHY
            )
        if not changed:
            return False, healthy_left
        self._emit_ejected(target, cause, healthy_left)
        return True, healthy_left

    def begin_probation(self, target: str) -> bool:
        """EJECTED → PROBATION (the health loop's exclusive canary claim)."""
        with self._lock:
            if self._states.get(target) != EJECTED:
                return False
            self._states[target] = PROBATION
            self._set_state_gauge(target, PROBATION)
        flightrec.note("mark", "replica_probation", replica=target_label(target))
        if self.obs is not None:
            try:
                self.obs.events.emit(
                    "replica_probation", replica=target_label(target)
                )
            except Exception:  # noqa: BLE001
                pass
        return True

    def reinstate(self, target: str) -> bool:
        """PROBATION → HEALTHY: the canary passed; the replica takes traffic."""
        with self._lock:
            if self._states.get(target) != PROBATION:
                return False
            self._states[target] = HEALTHY
            self._causes[target] = None
            self._set_state_gauge(target, HEALTHY)
        if self.obs is not None:
            try:
                self.obs.registry.counter(
                    FLEET_REPLICA_REINSTATED_TOTAL,
                    help="replicas reinstated to HEALTHY by a passing "
                    "probation canary",
                    replica=target_label(target),
                ).inc()
                self.obs.events.emit(
                    "replica_reinstated", replica=target_label(target)
                )
            except Exception:  # noqa: BLE001
                pass
        flightrec.note("mark", "replica_reinstated", replica=target_label(target))
        log.warning("replica %s reinstated by probation canary", target_label(target))
        return True

    def fail_probation(self, target: str, cause: str = "probe_failed") -> bool:
        """PROBATION → EJECTED: the canary failed; keep the replica out."""
        with self._lock:
            if self._states.get(target) != PROBATION:
                return False
            self._transition_to_ejected(target, cause)
            healthy_left = sum(
                1 for s in self._states.values() if s == HEALTHY
            )
        self._emit_ejected(target, cause, healthy_left)
        return True

    def update_signals(
        self,
        target: str,
        capacity: Optional[float] = None,
        queue_depth: Optional[int] = None,
        queue_capacity: Optional[int] = None,
        identity: Optional[dict] = None,
        canvas: Optional[int] = None,
        min_dim: Optional[int] = None,
        clock_offset_s: Optional[float] = None,
        volume_cost: Optional[int] = None,
        result_version: Optional[str] = None,
    ) -> None:
        """Record one health poll's routing signals for ``target``.

        ``canvas``/``min_dim`` are the replica's request-size guards —
        the probation canary sizes itself inside them.
        ``clock_offset_s`` is the replica's monotonic→wall offset from
        the /readyz clock handshake (ISSUE 14): published in the router
        table so cross-replica skew is triageable from one screen (the
        nm03-trace merge derives the same offset from each log itself).
        ``volume_cost`` is the replica's published default slice-
        equivalent cost of one whole-volume request (ISSUE 15): what the
        WRR debits an unsized ``/v1/segment-volume`` proxy by, so a
        volume never weighs like one slice.
        ``result_version`` is the replica's result-key program identity
        (ISSUE 19, ``/readyz`` ``result_cache.program_version``): the
        router's own result tier only engages while every healthy
        replica publishes the SAME value — a mixed fleet mid-rolling-
        restart bypasses the router cache by construction.
        """
        sig = {
            "capacity": capacity,
            "queue_depth": queue_depth,
            "queue_capacity": queue_capacity,
            "identity": identity,
            "canvas": canvas,
            "min_dim": min_dim,
            "clock_offset_s": clock_offset_s,
            "volume_cost": volume_cost,
            "result_version": result_version,
        }
        with self._lock:
            if target not in self._signals:
                raise KeyError(f"unknown replica target {target!r}")
            self._signals[target] = sig
        if self.obs is not None and capacity is not None:
            try:
                self.obs.registry.gauge(
                    FLEET_REPLICA_CAPACITY,
                    help="the replica's own published /readyz capacity "
                    "fraction (healthy-lane share), as last polled",
                    replica=target_label(target),
                ).set(float(capacity))
            except Exception:  # noqa: BLE001
                pass

    # -- telemetry ---------------------------------------------------------

    def _transition_to_ejected(self, target: str, cause: str) -> None:
        """The one EJECTED transition body (caller holds ``_lock``).

        Gauge/counter inside the lock so racing transitions publish in
        state order (the registry lock is a leaf — no ordering cycle);
        events/log stay outside, they do I/O.
        """
        # nm03-lint: disable=NM331 caller holds _lock by contract (eject/fail_probation); the shared helper exists so the two transition paths cannot drift
        self._states[target] = EJECTED
        # nm03-lint: disable=NM331 caller holds _lock, see above
        self._causes[target] = str(cause)
        # nm03-lint: disable=NM331 caller holds _lock, see above
        self._ejections[target] += 1
        self._set_state_gauge(target, EJECTED)
        if self.obs is not None:
            try:
                self.obs.registry.counter(
                    FLEET_REPLICA_EJECTIONS_TOTAL,
                    help="replica ejection transitions by replica and cause "
                    "(refused / timeout / http_503 / zero_capacity / "
                    "proxy_error / probe_failed)",
                    replica=target_label(target),
                    cause=str(cause),
                ).inc()
            except Exception:  # noqa: BLE001
                pass

    def _emit_ejected(self, target: str, cause: str, healthy_left: int) -> None:
        """The ejection's log line, WARNING event, and flight mark (shared
        by ``eject``/``fail_probation`` so the paths cannot drift)."""
        label = target_label(target)
        log.warning(
            "replica %s ejected (%s); %d healthy replica(s) remain",
            label, cause, healthy_left,
        )
        if self.obs is not None:
            try:
                self.obs.events.emit(
                    "replica_ejected", level="WARNING", replica=label,
                    cause=str(cause), healthy_remaining=healthy_left,
                )
            except Exception:  # noqa: BLE001 — telemetry never blocks triage
                pass
        flightrec.note("mark", "replica_ejected", replica=label, cause=str(cause))

    def _set_state_gauge(self, target: str, state: str) -> None:
        if self.obs is None:
            return
        try:
            self.obs.registry.gauge(
                FLEET_REPLICA_STATE,
                help="per-replica fault-domain state "
                "(0 healthy, 1 probation, 2 ejected)",
                replica=target_label(target),
            ).set(REPLICA_STATE_VALUES[state])
        except Exception:  # noqa: BLE001
            pass
