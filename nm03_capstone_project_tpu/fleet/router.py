"""The fleet front-end: capacity-weighted routing, failover, backpressure.

``nm03-fleet serve`` puts a stdlib :class:`ThreadingHTTPServer` in front
of N ``nm03-serve`` replicas (ROADMAP item 3 — the source paper spreads a
patient batch across OpenMP workers inside one host; at production scale
the same move is spreading traffic across replica *processes*, so one
process death is 1/N capacity, not 100%):

* ``POST /v1/segment`` proxies to one replica, chosen by **smooth
  weighted round-robin** over the currently-healthy set with weights from
  the replicas' own published signals — ``/readyz`` ``capacity`` (the
  healthy-lane fraction, PR 8) × admission-queue headroom (PR 4) —
  refreshed by a background health-poll loop;
* a replica that times out, refuses connections, answers 503, or reports
  zero capacity is **ejected** through the same HEALTHY → EJECTED →
  PROBATION → HEALTHY machine ``serving/lanes.py`` runs for chips
  (probation = an off-path canary ``POST /v1/segment`` on a synthetic
  zero slice; reinstatement on success);
* a proxied request that dies on a dying replica (connection reset,
  timeout, aborted body) **fails over** to a healthy replica under a
  bounded hop budget — riders never fail; ``X-Nm03-Replica`` and
  ``replica_hops`` in the payload tell the truth;
* a replica's 503 is **backpressure, honored**: the request reroutes
  while a healthy alternative exists, and when none does the client gets
  the replica's own ``Retry-After`` back instead of having the shed
  swallowed by the middle tier;
* 4xx/5xx application verdicts (a malformed body is malformed on every
  replica) propagate as-is — only transport failures and shed reroute.

``GET /healthz`` / ``/readyz`` / ``/metrics`` / ``/metrics.json`` serve
the FLEET's own state: ``/readyz`` is 200 while ≥1 replica is healthy
(the payload carries the per-replica table and the routed ``capacity``
fraction a chaos drill's plateau is read from) and the ``fleet_*`` series
live in an ordinary obs registry.

jax-/numpy-free at import by contract (NM301 pins the package): the
router is pure orchestration — bytes in, bytes out — and must start in
milliseconds on a host that never pays a backend import.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, FrozenSet, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from nm03_capstone_project_tpu.cache import (
    ResultStore,
    etag_matches,
    result_key,
)
from nm03_capstone_project_tpu.fleet.replicas import (
    EJECTED,
    ReplicaStates,
    normalize_target,
    target_label,
)
from nm03_capstone_project_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    FLEET_FAILOVERS_TOTAL,
    FLEET_PROBES_TOTAL,
    FLEET_REPLICAS_EJECTED,
    FLEET_REPLICAS_READY,
    FLEET_REQUESTS_ROUTED_TOTAL,
    FLEET_REQUESTS_TOTAL,
    FLEET_REQUEST_SECONDS,
    FLEET_ROUTED_CAPACITY,
    FLEET_SHED_TOTAL,
    SERVING_RESULT_CACHE_BYTES,
    SERVING_RESULT_CACHE_EVICT_TOTAL,
    SERVING_RESULT_CACHE_FILL_TOTAL,
    SERVING_RESULT_CACHE_HIT_TOTAL,
    SERVING_RESULT_CACHE_MISS_TOTAL,
)
from nm03_capstone_project_tpu.obs.trace import (
    FLEET_TRACE_EVENT,
    TraceContext,
    new_trace_id,
    sanitize_trace_id,
)
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("fleet")

RETRY_AFTER_S = 1  # the fleet-wide shed hint when no replica named one
# request headers forwarded replica-ward (lowercase); responses echo
# every X-Nm03-* plus the bare ETag — the result tier's revalidation
# token (If-None-Match in, ETag out) must survive the proxy both ways
_FORWARD_HEADERS = ("content-type", "if-none-match")
_FORWARD_PREFIX = "x-nm03-"
_MAX_BODY_BYTES = 64 << 20  # replicas enforce their own canvas-derived cap
_WEIGHT_FLOOR = 0.01  # a healthy replica with a full queue is still pickable


class FleetApp:
    """Everything behind the fleet HTTP handler: states, poller, proxy."""

    def __init__(
        self,
        targets,
        obs=None,
        health_interval_s: float = 1.0,
        probe_interval_s: float = 5.0,
        health_timeout_s: float = 2.0,
        proxy_timeout_s: float = 90.0,
        canary_hw: int = 32,
        canary_timeout_s: float = 30.0,
        fault_plan=None,
        slo=None,
        result_cache_bytes: int = 0,
    ):
        if obs is None:
            from nm03_capstone_project_tpu.obs import RunContext

            obs = RunContext.create(driver="fleet")
        self.obs = obs
        self.registry = obs.registry
        self.fault_plan = fault_plan
        self.replicas = ReplicaStates(targets, obs=obs)
        self.health_interval_s = float(health_interval_s)
        self.probe_interval_s = float(probe_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.canary_hw = int(canary_hw)
        self.canary_timeout_s = float(canary_timeout_s)
        self._lock = threading.Lock()
        # smooth-WRR current weights; the picker state (nginx algorithm:
        # add each candidate's weight, pick the max, subtract the total —
        # deterministic, proportional, no starvation)
        self._wrr: Dict[str, float] = {t: 0.0 for t in self.replicas.targets}
        self._seq = 0  # proxied-request ordinal (the fault-plan index key)
        self._probe_seq = 0
        self._last_probe: Dict[str, float] = {}
        self.draining = False
        self._stop = threading.Event()
        self._poller = threading.Thread(
            target=self._poll_loop, name="nm03-fleet-health", daemon=True
        )
        self._t0 = time.monotonic()
        # the shed counter exists at 0 from startup so a clean run's
        # snapshot proves "nothing shed" rather than saying nothing (the
        # labeled failover/routed counters appear with their first real
        # labels — an empty-label placeholder would be a phantom series)
        self.registry.counter(
            FLEET_SHED_TOTAL,
            help="requests answered 503 by the fleet (every replica shed "
            "or unhealthy); carries the replica's own Retry-After through",
        )
        # the SLO layer's status classes exist at 0 from startup, so a
        # clean run's snapshot proves "zero errors/sheds" exactly and the
        # SLO monitor's first sample has series to read
        for cls in ("ok", "error", "shed"):
            self.registry.counter(
                FLEET_REQUESTS_TOTAL, help=self._REQ_HELP, status=cls
            )
        # the router-side result tier (ISSUE 19): a content-addressed hit
        # is answered HERE — it never spends a WRR round or touches a
        # replica. The program-version half of every key comes from the
        # replicas' own /readyz publications (_fleet_result_version), so
        # the jax-free router never computes it — and a fleet that
        # disagrees on the version (mid-rolling-restart) bypasses the
        # tier by construction.
        self.result_store = (
            ResultStore(
                int(result_cache_bytes), on_evict=self._on_result_evict
            )
            if int(result_cache_bytes) > 0
            else None
        )
        # the bytes gauge exists (at 0) from startup when the tier is on:
        # its presence IS nm03-top's tier-enabled signal
        self._publish_result_bytes()
        # the SLO plane (ISSUE 14): burn rates/budget over the fleet's own
        # request accounting, pull-refreshed by publish_gauges()
        self.slo = None
        if slo is not None:
            from nm03_capstone_project_tpu.obs.slo import SLOMonitor

            self.slo = SLOMonitor(
                self.registry, slo, FLEET_REQUESTS_TOTAL,
                FLEET_REQUEST_SECONDS,
                # the fleet's bad set: propagated replica 5xx verdicts and
                # fleet-wide sheds; `invalid` (4xx) is the client's fault
                bad_statuses=("error", "shed"),
            )

    _REQ_HELP = (
        "terminal proxied-request outcomes by status class (ok = 2xx, "
        "invalid = 4xx application verdicts, error = 5xx, shed = the "
        "fleet-wide 503) — the fleet SLO layer's availability input"
    )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetApp":
        """One synchronous health sweep (routing starts informed), then
        the background poll loop."""
        self._sweep()
        self._poller.start()
        self.obs.events.emit(
            "fleet_ready",
            targets=[target_label(t) for t in self.replicas.targets],
            healthy=self.replicas.healthy_count(),
        )
        return self

    def begin_drain(self, reason: str = "sigterm") -> None:
        """Stop the poll loop, flush telemetry. Idempotent."""
        with self._lock:
            if self.draining:
                return
            self.draining = True
        self._stop.set()
        self._poller.join(timeout=10.0)
        self.obs.events.emit("fleet_drain", level="WARNING", reason=reason)
        try:
            self.publish_gauges()
            self.obs.write_metrics()
        except Exception as e:  # noqa: BLE001 — telemetry never blocks a drain
            log.warning("fleet drain: metrics flush failed: %s", e)

    def close(self, status: str = "ok") -> None:
        self.obs.close(status=status)

    # -- health loop -------------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            try:
                self._sweep()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                log.warning("fleet health sweep failed: %s", e)

    def _sweep(self) -> None:
        """One pass: poll every replica, canary the due ejected ones.

        Polls run CONCURRENTLY (one short-lived thread per target): a
        wedged replica that accepts but never answers costs its own
        ``health_timeout_s``, not a serial N× stretch of every other
        replica's ejection-detection latency — the contract
        ``--health-interval-s`` advertises. A poll that outlives the
        join grace is treated as not-ok for this sweep (its late signal
        write is lock-guarded and harmless).
        """
        targets = self.replicas.targets
        if len(targets) == 1:
            outcomes = {targets[0]: self._poll_one(targets[0])}
        else:
            outcomes: Dict[str, bool] = {}
            guard = threading.Lock()

            def poll(t: str) -> None:
                ok = self._poll_one(t)
                with guard:
                    outcomes[t] = ok

            threads = [
                threading.Thread(
                    target=poll, args=(t,),
                    name=f"nm03-fleet-poll-{target_label(t)}", daemon=True,
                )
                for t in targets
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=self.health_timeout_s + 5.0)
        for target in targets:
            if outcomes.get(target) and self.replicas.state(target) == EJECTED:
                self._maybe_probe(target)
        self.publish_gauges()

    def _poll_one(self, target: str) -> bool:
        """GET ``/readyz``; classify. True = 200 with routable capacity."""
        plan = self.fault_plan
        if plan is not None and plan.has_site("fleet"):
            rule = plan.fire(
                "fleet", obs=self.obs, stem=target_label(target),
                kinds=("replica_unreachable",),
            )
            if rule is not None:
                # the drill's deterministic outage: the poll "refused"
                self._handle_unhealthy(target, "refused")
                return False
        try:
            req = urllib.request.Request(f"{target}/readyz", method="GET")
            try:
                with urllib.request.urlopen(
                    req, timeout=self.health_timeout_s
                ) as resp:
                    status, body = resp.status, resp.read()
            except urllib.error.HTTPError as e:  # 503 still carries a payload
                status, body = e.code, e.read()
        except Exception as e:  # noqa: BLE001 — classified, never raised
            cause = "timeout" if "timed out" in str(e).lower() else "refused"
            self._handle_unhealthy(target, cause)
            return False
        try:
            st = json.loads(body or b"{}")
        except json.JSONDecodeError:
            st = {}
        capacity = st.get("capacity")
        if status != 200:
            self._handle_unhealthy(target, f"http_{status}")
            return False
        if capacity is not None and float(capacity) <= 0.0:
            self._handle_unhealthy(target, "zero_capacity")
            return False
        # the clock handshake (ISSUE 14): the replica echoes its own
        # (mono_s, ts_unix) pair on /readyz, so the router can publish
        # each replica's monotonic→wall offset for skew triage (the
        # nm03-trace merge derives the same offset from each log itself)
        clock = st.get("clock") or {}
        clock_offset_s = None
        if isinstance(clock.get("ts_unix"), (int, float)) and isinstance(
            clock.get("mono_s"), (int, float)
        ):
            clock_offset_s = round(
                float(clock["ts_unix"]) - float(clock["mono_s"]), 6
            )
        # the replica's published volume cost (ISSUE 15): the default
        # slice-equivalent weight of one whole-volume request (its
        # smallest depth bucket) — None on slice-only replicas
        volumes = st.get("volumes") or {}
        volume_cost = (
            volumes.get("default_cost") if volumes.get("enabled") else None
        )
        self.replicas.update_signals(
            target,
            capacity=capacity,
            queue_depth=st.get("queue_depth"),
            queue_capacity=st.get("queue_capacity"),
            identity=st.get("replica"),
            canvas=st.get("canvas"),
            min_dim=st.get("min_dim"),
            clock_offset_s=clock_offset_s,
            volume_cost=volume_cost,
            # the replica's result-tier program version (ISSUE 19): the
            # key half the router's own content-addressed tier borrows —
            # published even when the replica's store is disabled
            result_version=(st.get("result_cache") or {}).get(
                "program_version"
            ),
        )
        return True

    def _handle_unhealthy(self, target: str, cause: str) -> None:
        self.replicas.eject(target, cause)  # no-op unless HEALTHY

    def _maybe_probe(self, target: str) -> None:
        """Probation canary for an ejected replica whose poll just passed.

        Gated on the probe cadence AND on the same-sweep ``/readyz``
        success, so a replica that is simply down never costs a canary —
        and an injected ``replica_unreachable`` outage (which fails the
        poll) deterministically holds the replica out. The canary itself
        runs on its own daemon thread: a wedged replica that accepts the
        connection but never answers would otherwise hold the single
        sweep thread for ``canary_timeout_s``, blinding the health poll
        to every OTHER replica for the duration (the begin_probation
        claim keeps two canaries off one target).
        """
        now = time.monotonic()
        with self._lock:
            if now - self._last_probe.get(target, -1e9) < self.probe_interval_s:
                return
            self._last_probe[target] = now
            self._probe_seq += 1
            n = self._probe_seq
        threading.Thread(
            target=self._probe_one, args=(target, n),
            name=f"nm03-fleet-probe-{target_label(target)}", daemon=True,
        ).start()

    def _probe_one(self, target: str, n: int) -> None:
        """One probation canary: claim, POST, reinstate or re-eject."""
        if not self.replicas.begin_probation(target):
            return
        # size the canary inside the replica's own published guards: a
        # 32x32 default against a --min-dim 100 replica would be a 400
        # on every probe and an ejection that never heals (the bug the
        # first live drill caught) — the replica tells us what fits
        sig = self.replicas.signals(target)
        hw = self.canary_hw
        if sig.get("min_dim"):
            hw = max(hw, int(sig["min_dim"]))
        if sig.get("canvas"):
            hw = min(hw, int(sig["canvas"]))
        body = bytes(hw * hw * 4)  # a zero float32 slice — the warmup input
        label = target_label(target)
        probe_id = f"fleet-probe-{label}-{n}"
        headers = {
            "Content-Type": "application/octet-stream",
            "X-Nm03-Height": str(hw),
            "X-Nm03-Width": str(hw),
            "X-Nm03-Request-Id": probe_id,
            # the probe tag (ISSUE 14 satellite): the replica still serves
            # and traces the canary but keeps it OUT of its request
            # metrics and SLO accounting — a probe every interval against
            # an otherwise-idle replica must not pollute the very series
            # the SLO layer reads
            "X-Nm03-Probe": "1",
        }
        outcome = "failed"
        ctx = TraceContext(probe_id)
        t0 = time.monotonic()
        try:
            req = urllib.request.Request(
                f"{target}/v1/segment?output=mask", data=body,
                headers=headers, method="POST",
            )
            with urllib.request.urlopen(
                req, timeout=self.canary_timeout_s
            ) as resp:
                resp.read()
                ok = resp.status == 200
        except Exception:  # noqa: BLE001 — a failed canary is an outcome
            ok = False
        if ok:
            outcome = "passed"
            self.replicas.reinstate(target)
        else:
            self.replicas.fail_probation(target)
        ctx.add_span(
            "canary_probe", t0, time.monotonic(), replica=label,
            outcome=outcome, probe=True,
        )
        try:
            self.registry.counter(
                FLEET_PROBES_TOTAL,
                help="probation canary requests by replica and outcome",
                replica=label, outcome=outcome,
            ).inc()
            # probes are traced (probe=true) but never counted in
            # fleet_requests_total — the fleet-side half of the satellite
            self.obs.events.emit(
                FLEET_TRACE_EVENT,
                trace_id=probe_id,
                request_id=f"probe-{n:06d}",
                replica=label,
                replica_hops=0,
                status=200 if ok else None,
                probe=True,
                spans=ctx.snapshot(),
            )
        except Exception:  # noqa: BLE001
            pass

    # -- routing -----------------------------------------------------------

    def pick(
        self, exclude: FrozenSet[str] = frozenset(), cost: float = 1.0
    ) -> Optional[str]:
        """Smooth weighted round-robin over healthy, non-excluded targets.

        ``cost`` is the request's slice-equivalent weight (ISSUE 15): the
        picked replica is debited ``cost`` rounds' worth instead of one,
        so a 32-plane volume request "spends" that replica's turn 32
        times over and the next 31 slice picks land elsewhere — WRR never
        mistakes a whole study for one slice.
        """
        healthy = [
            t for t in self.replicas.healthy_targets() if t not in exclude
        ]
        if not healthy:
            return None
        weights = {
            t: max(self.replicas.weight(t), _WEIGHT_FLOOR) for t in healthy
        }
        total = sum(weights.values())
        with self._lock:
            for t, w in weights.items():
                self._wrr[t] = self._wrr.get(t, 0.0) + w
            best = max(healthy, key=lambda t: self._wrr[t])
            self._wrr[best] -= total * max(float(cost), 1.0)
        return best

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _forward(
        self, target: str, body: bytes, headers: dict, query: str,
        path: str = "/v1/segment",
    ) -> Tuple[int, bytes, List[Tuple[str, str]]]:
        """One proxied POST to ``target``; HTTP errors return, transport
        errors raise (the caller's failover trigger)."""
        url = f"{target}{path}" + (f"?{query}" if query else "")
        req = urllib.request.Request(
            url, data=body, headers=headers, method="POST"
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.proxy_timeout_s
            ) as resp:
                return resp.status, resp.read(), list(resp.getheaders())
        except urllib.error.HTTPError as e:
            data = e.read()
            return e.code, data, list(e.headers.items()) if e.headers else []

    def _count_failover(self, target: str, cause: str) -> None:
        self.registry.counter(
            FLEET_FAILOVERS_TOTAL,
            help="proxied requests moved off a replica mid-flight by "
            "replica and cause (io_error = transport death, shed = "
            "rerouted 503 backpressure)",
            replica=target_label(target), cause=cause,
        ).inc()

    def volume_request_cost(self, headers: dict) -> float:
        """The slice-equivalent WRR cost of one volume request (ISSUE 15).

        The request's own declared depth (``X-Nm03-Depth``, the raw
        stacked format) when present; otherwise the largest volume cost
        any replica published on ``/readyz`` (its smallest depth bucket —
        an undeclared DICOM study is at least that deep once padded);
        floor 1.0 so a missing signal degrades to slice weighting, never
        a zero-cost pick.
        """
        for k, v in headers.items():
            if k.lower() == "x-nm03-depth":
                try:
                    return max(float(int(v)), 1.0)
                except (TypeError, ValueError):
                    break
        published = [
            self.replicas.signals(t).get("volume_cost")
            for t in self.replicas.targets
        ]
        costs = [float(c) for c in published if c]
        return max(costs) if costs else 1.0

    # -- the result tier (ISSUE 19, router side) ---------------------------

    def _on_result_evict(self, n: int) -> None:
        # fired from inside the store's lock — a counter bump only (the
        # bytes gauge refreshes outside the lock, in _result_fill and the
        # admin evict handler)
        self.registry.counter(
            SERVING_RESULT_CACHE_EVICT_TOTAL,
            help="result-tier entries evicted by tier (LRU pressure, "
            "explicit evict, or a failed verify-on-read)",
            tier="router",
        ).inc(n)

    def _count_result(self, name: str, help_text: str) -> None:
        self.registry.counter(name, help=help_text, tier="router").inc()

    def _publish_result_bytes(self) -> None:
        if self.result_store is not None:
            self.registry.gauge(
                SERVING_RESULT_CACHE_BYTES,
                help="resident bytes in the router result store",
            ).set(self.result_store.bytes)

    def _fleet_result_version(self) -> Optional[str]:
        """The one program version every healthy replica publishes, or None.

        The router tier only engages while the WHOLE healthy set agrees
        on a single ``result_version`` (each replica's ``/readyz``
        ``result_cache.program_version``). During a rolling restart the
        fleet is mixed, the set has two members, and the tier bypasses by
        construction — a mask the old algorithm computed can never answer
        a request the new one would segment differently. Invalidation is
        the key changing, not a flush.
        """
        versions = {
            self.replicas.signals(t).get("result_version")
            for t in self.replicas.healthy_targets()
        }
        if len(versions) != 1:
            return None
        v = versions.pop()
        return v or None

    def _result_digest(
        self, body: bytes, query: str, path: str
    ) -> Optional[str]:
        """This request's content-addressed key digest, or None (bypass).

        None when the tier is off or the healthy set doesn't currently
        agree on one program version. Router keys hash the raw query
        string's sorted parameters: the router never interprets replica
        semantics (defaults, clamping), so two spellings of one request
        land on different keys and both simply miss — never wrong, at
        worst one extra compute.
        """
        if self.result_store is None:
            return None
        version = self._fleet_result_version()
        if version is None:
            return None
        algo = "segment-volume" if path.endswith("-volume") else "segment"
        params = dict(
            sorted(parse_qs(query, keep_blank_values=True).items())
        )
        return result_key(body, algo, params, version).digest()

    def _serve_cached(
        self, entry, headers: dict, ctx: "TraceContext", seq: int,
        t_req: float,
    ) -> Tuple[int, bytes, List[Tuple[str, str]]]:
        """Answer a router-tier hit: 304 on a matching ``If-None-Match``,
        else the stored payload with this request's own identity fields.

        The HTTP ETag served is the REPLICA's content ETag when one was
        recorded at fill (entry.meta) — so revalidation works identically
        whichever tier answers — falling back to the store's own payload
        digest when the replica tier was off.
        """
        etag = entry.meta.get("etag") or entry.etag
        inm = next(
            (v for k, v in headers.items() if k.lower() == "if-none-match"),
            None,
        )
        base = [
            ("ETag", etag),
            ("X-Nm03-Cache", "hit"),
            ("X-Nm03-Request-Id", ctx.trace_id),
            ("X-Nm03-Replica-Hops", "0"),
        ]
        if etag_matches(inm, etag):
            self._finish_request(ctx, seq, t_req, 304, None, 0)
            return 304, b"", base
        data = entry.payload
        try:
            payload = json.loads(data)
        except (json.JSONDecodeError, UnicodeDecodeError):
            payload = None
        if isinstance(payload, dict):
            # per-execution fields tell THIS request's truth: nothing
            # ran, nothing waited, nothing hopped (replica/replica_id
            # stay — they name who computed the stored result)
            payload["request_id"] = ctx.trace_id
            payload["cached"] = True
            payload["device_seconds"] = 0.0
            payload["queue_wait_s"] = 0.0
            payload["replica_hops"] = 0
            data = json.dumps(payload).encode()
        self._finish_request(ctx, seq, t_req, 200, None, 0)
        return 200, data, [("Content-Type", "application/json"), *base]

    def _result_fill(
        self, digest: str, data: bytes, path: str,
        resp_headers: List[Tuple[str, str]],
    ) -> None:
        """Store one routed 200 at the router tier.

        The stored bytes are the AUGMENTED payload (replica identity
        included) — ``entry.etag`` must stay the digest of exactly those
        bytes because it doubles as the verify-on-read check — while the
        replica's own content ETag (when its tier is on) rides in
        ``entry.meta`` for the HTTP surface.
        """
        if self.result_store is None:
            return
        algo = "segment-volume" if path.endswith("-volume") else "segment"
        replica_etag = next(
            (v for k, v in resp_headers if k.lower() == "etag"), None
        )
        entry, created = self.result_store.fill(
            digest, data, algo,
            meta={"etag": replica_etag} if replica_etag else None,
        )
        if created:
            self._count_result(
                SERVING_RESULT_CACHE_FILL_TOTAL,
                "computed results stored into the tier, by tier",
            )
            self._publish_result_bytes()

    def proxy_segment(
        self, body: bytes, headers: dict, query: str = "",
        trace_id: Optional[str] = None, path: str = "/v1/segment",
        cost: float = 1.0,
    ) -> Tuple[int, bytes, List[Tuple[str, str]]]:
        """Route one ``POST /v1/segment[-volume]``; (status, body, headers).

        ``path`` selects the replica endpoint (``/v1/segment-volume``
        proxies through the same failover/shed ladder — a volume request
        that dies on a dying replica moves on like any rider); ``cost``
        is the request's slice-equivalent WRR debit
        (:meth:`volume_request_cost`).

        The failover ladder: transport death ejects the replica and moves
        the request on; a 503 remembers the replica's Retry-After and
        tries an alternative; each replica is tried at most once, and the
        budget is bounded by the fleet size — no infinite ping-pong even
        against a racing reinstatement.

        Every request is traced (ISSUE 14): ``trace_id`` is the handler's
        minted-or-honored ``X-Nm03-Request-Id`` (minted here for direct
        callers), forwarded replica-ward so the replica's ``serve_trace``
        tree shares it, and the router records its own span chain —
        ``route_pick`` → ``proxy_hop`` per attempt (→ ``failover`` on a
        transport death or shed) — emitted as one ``fleet_trace`` event.
        """
        seq = self._next_seq()
        t_req = time.monotonic()
        ctx = TraceContext(trace_id or new_trace_id())
        # the canonical trace header rides to the replica (replacing any
        # case variant of the client's), so the replica-side span tree
        # shares this request's id — the multi-log merge's join key. The
        # probe tag is STRIPPED from client traffic: only the router's
        # own canary path (_probe_one) may set it — a client smuggling
        # X-Nm03-Probe through the fleet would otherwise have its real
        # requests silently excluded from the replica's request metrics
        # and SLO accounting while the fleet counts them
        headers = {
            k: v for k, v in headers.items()
            if k.lower() not in ("x-nm03-request-id", "x-nm03-probe")
        }
        headers["X-Nm03-Request-Id"] = ctx.trace_id
        # the router-side lookup happens BEFORE admission to the pick
        # loop (ISSUE 19): a hit never spends a WRR round, never costs a
        # replica pick, and charges zero device-seconds anywhere
        cache_digest = self._result_digest(body, query, path)
        if cache_digest is not None:
            entry = self.result_store.lookup(cache_digest)
            if entry is not None:
                self._count_result(
                    SERVING_RESULT_CACHE_HIT_TOTAL,
                    "result-tier lookups served from cache, by tier",
                )
                return self._serve_cached(entry, headers, ctx, seq, t_req)
            self._count_result(
                SERVING_RESULT_CACHE_MISS_TOTAL,
                "result-tier lookups that fell through to compute, by tier",
            )
        plan = self.fault_plan
        tried: set = set()
        hops = 0
        shed: Optional[Tuple[int, bytes, List[Tuple[str, str]]]] = None
        status: int = 503
        data: bytes = b""
        final: Optional[str] = None
        resp_headers: List[Tuple[str, str]] = []
        while True:
            t_pick = time.monotonic()
            target = self.pick(exclude=frozenset(tried), cost=cost)
            ctx.add_span(
                "route_pick", t_pick, time.monotonic(),
                replica=target_label(target) if target else None,
                attempt=hops + 1,
            )
            if target is None:
                break
            tried.add(target)
            label = target_label(target)
            if plan is not None and plan.has_site("fleet"):
                rule = plan.fire(
                    "fleet", obs=self.obs, stem=label,
                    index=seq, kinds=("proxy_io_error",),
                )
                if rule is not None:
                    # the drill's deterministic mid-body abort: same path
                    # a real connection reset takes
                    t0 = time.monotonic()
                    self.replicas.eject(target, "proxy_error")
                    self._count_failover(target, "io_error")
                    now = time.monotonic()
                    ctx.add_span(
                        "proxy_hop", t0, now, replica=label,
                        outcome="io_error", attempt=hops + 1,
                    )
                    ctx.add_span(
                        "failover", now, time.monotonic(), replica=label,
                        cause="io_error",
                    )
                    hops += 1
                    continue
            t0 = time.monotonic()
            try:
                status, data, resp_headers = self._forward(
                    target, body, headers, query, path=path
                )
            except Exception as e:  # noqa: BLE001 — transport death → failover
                log.warning(
                    "proxy to %s failed (%s); failing over", label, e,
                )
                now = time.monotonic()
                ctx.add_span(
                    "proxy_hop", t0, now, replica=label,
                    outcome="io_error", attempt=hops + 1,
                )
                self.replicas.eject(target, "proxy_error")
                self._count_failover(target, "io_error")
                ctx.add_span(
                    "failover", now, time.monotonic(), replica=label,
                    cause="io_error",
                )
                hops += 1
                continue
            if status == 503:
                # backpressure: reroute while an alternative exists,
                # propagate the replica's own Retry-After when none does
                now = time.monotonic()
                ctx.add_span(
                    "proxy_hop", t0, now, replica=label,
                    outcome="shed", attempt=hops + 1,
                )
                shed = (status, data, resp_headers)
                self._count_failover(target, "shed")
                ctx.add_span(
                    "failover", now, time.monotonic(), replica=label,
                    cause="shed",
                )
                hops += 1
                continue
            ctx.add_span(
                "proxy_hop", t0, time.monotonic(), replica=label,
                outcome="ok" if status == 200 else f"http_{status}",
                attempt=hops + 1,
            )
            final = target
            break
        if final is not None:
            # a routed verdict (200 or an application error) returns as-is
            self.registry.counter(
                FLEET_REQUESTS_ROUTED_TOTAL,
                help="requests served to completion by each replica "
                "(non-503 responses returned to the client)",
                replica=target_label(final),
            ).inc()
            out_headers = self._response_headers(resp_headers, final, hops)
            if status == 200:
                data = self._augment_payload(data, final, hops)
                if cache_digest is not None:
                    # replica-side fill rides home through the router's
                    # own tier: the next identical study never leaves it
                    self._result_fill(cache_digest, data, path, resp_headers)
        else:
            # no healthy replica left (or every one shed / died)
            self.registry.counter(
                FLEET_SHED_TOTAL,
                help="requests answered 503 by the fleet (every replica "
                "shed or unhealthy); carries the replica's own Retry-After "
                "through",
            ).inc()
            if shed is not None:
                status, data, resp_headers = shed
                retry_after = next(
                    (v for k, v in resp_headers if k.lower() == "retry-after"),
                    str(RETRY_AFTER_S),
                )
            else:
                retry_after = str(RETRY_AFTER_S)
                data = json.dumps({
                    "error": "no healthy replica "
                    f"({self.replicas.ejected_count()} of "
                    f"{len(self.replicas)} ejected)",
                    "replica_hops": hops,
                }).encode()
            status = 503
            out_headers = [
                ("Content-Type", "application/json"),
                ("Retry-After", retry_after),
                # the echo contract holds on the shed path too: the
                # replica would have echoed it, so the fleet must
                ("X-Nm03-Request-Id", ctx.trace_id),
            ]
        self._finish_request(ctx, seq, t_req, status, final, hops)
        return status, data, out_headers

    def _finish_request(
        self, ctx: TraceContext, seq: int, t_req: float, status: int,
        final: Optional[str], hops: int,
    ) -> None:
        """One proxied request's terminal accounting: the SLO layer's
        status class + latency observation, and the ``fleet_trace``
        event carrying the router's span chain."""
        if 200 <= status < 400:
            # 304 Not Modified is a served verdict (the result tier's
            # revalidation answer), not an error — it burns no budget
            cls = "ok"
        elif status == 503:
            cls = "shed"
        elif status >= 500:
            cls = "error"
        else:
            cls = "invalid"
        try:
            self.registry.counter(
                FLEET_REQUESTS_TOTAL, help=self._REQ_HELP, status=cls
            ).inc()
            self.registry.histogram(
                FLEET_REQUEST_SECONDS,
                help="client-observed proxy latency per request (front-end "
                "admission to the final verdict, failover hops included) — "
                "the fleet SLO layer's latency input",
                buckets=DEFAULT_LATENCY_BUCKETS,
            ).observe(time.monotonic() - t_req)
            self.obs.events.emit(
                FLEET_TRACE_EVENT,
                trace_id=ctx.trace_id,
                request_id=f"fl-{seq:06d}",
                replica=target_label(final) if final else None,
                replica_hops=hops,
                status=status,
                spans=ctx.snapshot(),
            )
        except Exception as e:  # noqa: BLE001 — telemetry never fails a request
            log.warning("fleet trace emit failed: %s", e)

    def _response_headers(
        self, resp_headers: List[Tuple[str, str]], target: str, hops: int
    ) -> List[Tuple[str, str]]:
        """Replica ``X-Nm03-*``/Content-Type headers + the fleet's own.

        The prefix filter drops the replica's Content-Length by
        construction — the handler recomputes it against the (possibly
        augmented) body, so a stale length can never reach the client.
        """
        out = [
            (k, v) for k, v in resp_headers
            if k.lower().startswith(_FORWARD_PREFIX)
            or k.lower() in ("content-type", "etag")
        ]
        out.append(("X-Nm03-Replica", target_label(target)))
        out.append(("X-Nm03-Replica-Hops", str(hops)))
        return out

    def _augment_payload(self, data: bytes, target: str, hops: int) -> bytes:
        """Add ``replica``/``replica_id``/``replica_hops`` to a 200 payload."""
        try:
            payload = json.loads(data)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return data  # non-JSON passes through untouched
        if not isinstance(payload, dict):
            return data
        payload["replica"] = target_label(target)
        identity = self.replicas.signals(target).get("identity") or {}
        payload["replica_id"] = identity.get("id")
        payload["replica_hops"] = hops
        return json.dumps(payload).encode()

    # -- status / telemetry ------------------------------------------------

    def publish_gauges(self) -> None:
        """Refresh the fleet-level gauges from the current state table."""
        if self.slo is not None:
            try:
                self.slo.publish()  # pull-refresh the burn-rate windows
            except Exception as e:  # noqa: BLE001 — telemetry never blocks
                log.warning("fleet SLO publish failed: %s", e)
        healthy = self.replicas.healthy_count()
        self.registry.gauge(
            FLEET_REPLICAS_READY,
            help="replicas currently HEALTHY and taking routed traffic",
        ).set(healthy)
        self.registry.gauge(
            FLEET_REPLICAS_EJECTED,
            help="replicas currently out of rotation (ejected or under "
            "probation)",
        ).set(self.replicas.ejected_count())
        self.registry.gauge(
            FLEET_ROUTED_CAPACITY,
            help="the fleet's routed capacity fraction: mean healthy-replica "
            "published capacity (one dead replica of three reads 0.667)",
        ).set(round(self.replicas.capacity_fraction(), 6))

    @property
    def ready(self) -> bool:
        with self._lock:
            draining = self.draining
        return self.replicas.healthy_count() >= 1 and not draining

    def status(self) -> dict:
        snap = self.replicas.snapshot()
        return {
            "ready": self.ready,
            "draining": self.draining,
            "fleet": True,
            # the SLO verdict rides /readyz like the replica's saturation
            # block: burn rates + budget against the declared objective
            # (null when no objective was declared). last_block, not
            # publish: the /readyz handler already published via
            # publish_gauges() — one probe must sample once
            "slo": self.slo.last_block() if self.slo is not None else None,
            "capacity": round(self.replicas.capacity_fraction(), 6),
            # the router-side result tier (ISSUE 19): stats + the
            # fleet-agreed program version (null while the healthy set
            # disagrees — the rolling-restart bypass window)
            "result_cache": (
                {
                    **self.result_store.stats(),
                    "program_version": self._fleet_result_version(),
                }
                if self.result_store is not None
                else {"enabled": False}
            ),
            "replicas": {
                "count": len(self.replicas),
                "ready": self.replicas.healthy_count(),
                "ejected": self.replicas.ejected_count(),
                "per_replica": snap,
            },
            "uptime_s": round(time.monotonic() - self._t0, 3),
        }


# -- the HTTP layer ---------------------------------------------------------


def make_handler(app: FleetApp):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        server_version = "nm03-fleet/1.0"
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: A003
            log.debug("%s %s", self.address_string(), fmt % args)

        def _reply(self, status: int, data: bytes, headers=()):
            self.send_response(status)
            seen_ct = False
            for k, v in headers:
                if k.lower() == "content-type":
                    seen_ct = True
                self.send_header(k, v)
            if not seen_ct:
                self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _reply_json(self, status: int, body: dict, headers=()):
            self._reply(status, json.dumps(body).encode(), headers)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            path = urlsplit(self.path).path
            if path == "/healthz":
                self._reply_json(
                    200,
                    {"status": "alive",
                     "uptime_s": round(time.monotonic() - app._t0, 3)},
                )
            elif path == "/readyz":
                app.publish_gauges()
                st = app.status()
                self._reply_json(200 if st["ready"] else 503, st)
            elif path == "/metrics":
                app.publish_gauges()
                self._reply(
                    200, app.registry.to_prometheus().encode(),
                    [("Content-Type", "text/plain; version=0.0.4")],
                )
            elif path == "/metrics.json":
                app.publish_gauges()
                self._reply(
                    200,
                    json.dumps(app.obs.metrics_snapshot(), indent=1).encode(),
                    [("Content-Type", "application/json")],
                )
            elif path == "/debug/result-cache":
                # the result tier's admin surface (ISSUE 19): stats plus
                # hot-to-cold rows, and the fleet-agreed program version
                # (null while the healthy set disagrees — the bypass
                # window an operator sees during a rolling restart)
                if app.result_store is None:
                    self._reply_json(200, {"enabled": False})
                else:
                    self._reply_json(
                        200,
                        {
                            **app.result_store.stats(),
                            "program_version": app._fleet_result_version(),
                            "ls": app.result_store.ls(),
                        },
                    )
            elif path == "/debug/flightrec":
                # the remote debug pull (ISSUE 14): the router's own
                # flight rings over HTTP — `nm03-fleet flightrec` fans
                # the same endpoint across the replicas
                from nm03_capstone_project_tpu.obs import flightrec

                snap = flightrec.get_recorder().snapshot(reason="debug_pull")
                self._reply(
                    200, json.dumps(snap, default=str).encode(),
                    [("Content-Type", "application/json")],
                )
            else:
                self._reply_json(404, {"error": f"unknown path {path}"})

        def do_POST(self):  # noqa: N802
            split = urlsplit(self.path)
            # the fleet mints-or-honors the trace identity EXPLICITLY
            # (ISSUE 14): the id is decided here, echoed on every reply
            # (errors included) and forwarded replica-ward, so the whole
            # fleet timeline of this request shares one id
            trace_id = sanitize_trace_id(
                self.headers.get("X-Nm03-Request-Id")
            ) or new_trace_id()
            echo = [("X-Nm03-Request-Id", trace_id)]
            if split.path == "/debug/result-cache/evict":
                # admin evict (?digest=D for one entry, bare for all)
                if app.result_store is None:
                    self._reply_json(
                        404, {"error": "result tier disabled"}, echo
                    )
                    return
                qs = parse_qs(split.query)
                digest = (qs.get("digest") or [None])[0]
                n = app.result_store.evict(digest)
                app._publish_result_bytes()
                self._reply_json(200, {"evicted": n}, echo)
                return
            if split.path not in ("/v1/segment", "/v1/segment-volume"):
                self._reply_json(
                    404, {"error": f"unknown path {split.path}"}, echo
                )
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                self._reply_json(400, {"error": "bad Content-Length"}, echo)
                return
            if length <= 0:
                self._reply_json(400, {"error": "empty body"}, echo)
                return
            if length > _MAX_BODY_BYTES:
                self._reply_json(
                    413,
                    {"error": f"body of {length} bytes exceeds the fleet cap"},
                    echo,
                )
                return
            body = self.rfile.read(length)
            headers = {
                k: v for k, v in self.headers.items()
                if k.lower().startswith(_FORWARD_PREFIX)
                or k.lower() in _FORWARD_HEADERS
            }
            # a whole-volume request weighs its declared depth in the WRR
            # (ISSUE 15) — the router must not treat a 32-plane study as
            # one slice when spreading load
            cost = (
                app.volume_request_cost(headers)
                if split.path == "/v1/segment-volume"
                else 1.0
            )
            try:
                status, data, resp_headers = app.proxy_segment(
                    body, headers, split.query, trace_id=trace_id,
                    path=split.path, cost=cost,
                )
            except Exception as e:  # noqa: BLE001 — per-request containment
                log.warning("fleet request failed: %s", e)
                self._reply_json(
                    500, {"error": str(e), "error_class": type(e).__name__},
                    echo,
                )
                return
            self._reply(status, data, resp_headers)

    return Handler


def make_http_server(app: FleetApp, host: str = "127.0.0.1", port: int = 0):
    """Bind (port 0 = ephemeral); ``.server_address`` carries the real port."""
    from http.server import ThreadingHTTPServer

    httpd = ThreadingHTTPServer((host, port), make_handler(app))
    httpd.daemon_threads = True
    return httpd


def serve_in_thread(app: FleetApp, host: str = "127.0.0.1", port: int = 0):
    """Start a fleet on a daemon thread; ``(httpd, thread, port)`` (tests)."""
    httpd = make_http_server(app, host, port)
    app.start()
    t = threading.Thread(
        target=httpd.serve_forever, name="nm03-fleet-http", daemon=True
    )
    t.start()
    return httpd, t, httpd.server_address[1]


__all__ = [
    "FleetApp",
    "make_handler",
    "make_http_server",
    "normalize_target",
    "serve_in_thread",
]
