"""Replica-level fault domains: the ``nm03-fleet`` front-end (ISSUE 13).

The fleet layer of the serving story (ROADMAP item 3): where PR 8 made
the *lane* the fault domain inside one ``nm03-serve`` process, this
package makes the *replica process* the fault domain across a host —
capacity-weighted routing from the replicas' own published signals,
outlier ejection through a HEALTHY → EJECTED → PROBATION → HEALTHY
machine, bounded-hop failover for in-flight riders, backpressure
(Retry-After) propagation, and rolling-restart orchestration that rides
the PR-9 compile cache so a redeploy is milliseconds-cold and never
drops below (N−1)/N capacity.

jax- AND numpy-free at import by contract (NM301 pins the package,
NM331 scans its lock discipline): the router is pure stdlib
orchestration and must never pay a backend import or claim a chip.
"""

from nm03_capstone_project_tpu.fleet.manager import (
    RestartError,
    rolling_restart,
)
from nm03_capstone_project_tpu.fleet.replicas import (
    EJECTED,
    HEALTHY,
    PROBATION,
    REPLICA_STATE_VALUES,
    ReplicaStates,
    normalize_target,
    target_label,
)
from nm03_capstone_project_tpu.fleet.router import (
    FleetApp,
    make_http_server,
    serve_in_thread,
)

__all__ = [
    "EJECTED",
    "HEALTHY",
    "PROBATION",
    "REPLICA_STATE_VALUES",
    "FleetApp",
    "ReplicaStates",
    "RestartError",
    "make_http_server",
    "normalize_target",
    "rolling_restart",
    "serve_in_thread",
    "target_label",
]
