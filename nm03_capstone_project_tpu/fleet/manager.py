"""Rolling-restart orchestration: redeploy a fleet at (N−1)/N capacity.

``nm03-fleet restart`` walks the replica list ONE AT A TIME: SIGTERM the
replica (the PR-4 graceful drain — admissions stop, admitted batches
finish, telemetry flushes), wait for its listener to close, relaunch it
from the command line its own ``/readyz`` identity block published, and
wait for the new process's ``/readyz`` to go 200 before touching the
next replica. The fleet front-end's health loop ejects the draining
replica within one poll and probation reinstates the fresh one, so the
routed capacity never drops below (N−1)/N — and with a shared
``--compile-cache-dir`` (PR 9) the warm-wait is seconds, not
compile-minutes: the restarted replica deserializes its per-lane
executables instead of compiling them (the OpenCLIPER
amortize-the-overhead thesis applied to redeploys), verifiable in the
report's ``builds``/``cache_hits`` columns (``builds == 0`` is the
cache-hit proof).

Same-host by construction: the SIGTERM and the relaunch both happen on
the machine this runs on (the replica block's ``pid``/``cwd`` are local
facts). Cross-host orchestration belongs to a real supervisor
(systemd/k8s); this module is the one-host story the rest of the repo
serves.

jax-/numpy-free at import by contract (NM301 pins the package).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import time
import urllib.error
import urllib.request
from typing import List, Optional, Sequence

from nm03_capstone_project_tpu.fleet.replicas import (
    normalize_target,
    target_label,
)
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("fleet")

SCHEMA_RESTART = "nm03.fleetrestart.v1"


class RestartError(RuntimeError):
    """One replica failed a restart step; the rolling walk stops there
    (continuing would risk a second replica down at the same time)."""


def _get_json(url: str, timeout_s: float = 5.0):
    """(status, parsed body) for a GET; raises on transport failure."""
    req = urllib.request.Request(url, method="GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:  # 503 still carries the payload
        try:
            return e.code, json.loads(e.read() or b"{}")
        except json.JSONDecodeError:
            return e.code, {}


def _wait_listener_closed(target: str, timeout_s: float, poll_s: float) -> float:
    """Block until ``target`` refuses connections; returns the wait.

    ``nm03-serve`` closes its listener only after the graceful drain
    completes (admitted batches finished, metrics flushed), so
    connection-refused IS the drain-done signal — no pid polling, which
    would hang on an unreaped zombie.
    """
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    while time.monotonic() < deadline:
        try:
            req = urllib.request.Request(f"{target}/healthz", method="GET")
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                resp.read()
        except urllib.error.HTTPError:
            pass  # still answering HTTP — still draining
        except Exception as e:  # noqa: BLE001 — classified below
            # a TIMEOUT means the listener is still up but slow (a loaded
            # host finishing admitted batches) — keep waiting; relaunching
            # now would EADDRINUSE-crash the replacement while the old
            # process still holds the port. Only refused/reset means the
            # listener really closed.
            if "timed out" not in str(e).lower():
                return time.monotonic() - t0
        time.sleep(poll_s)
    raise RestartError(
        f"{target_label(target)} still listening after {timeout_s:.0f}s "
        "drain wait"
    )


def _wait_ready(
    target: str, timeout_s: float, poll_s: float, old_pid: Optional[int]
) -> dict:
    """Block until ``/readyz`` answers 200 from a NEW pid; returns it."""
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    last = "no response yet"
    while time.monotonic() < deadline:
        try:
            status, st = _get_json(f"{target}/readyz", timeout_s=5.0)
        except Exception as e:  # noqa: BLE001 — not up yet
            last = str(e)
            time.sleep(poll_s)
            continue
        pid = (st.get("replica") or {}).get("pid")
        if status == 200 and (old_pid is None or pid != old_pid):
            st["_warm_wait_s"] = round(time.monotonic() - t0, 3)
            return st
        last = f"status {status}, pid {pid}"
        time.sleep(poll_s)
    raise RestartError(
        f"{target_label(target)} not ready after {timeout_s:.0f}s ({last})"
    )


def _wait_fleet_sees(
    fleet_url: str, target: str, timeout_s: float, poll_s: float
) -> None:
    """Block until the fleet front-end reports ``target`` HEALTHY again.

    Without this, the orchestrator would move to the next replica while
    the front-end's probation canary is still pending — two replicas out
    of rotation at once, which is exactly the (N−1)/N floor this module
    promises to hold.
    """
    label = target_label(target)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            _, st = _get_json(f"{fleet_url}/readyz", timeout_s=5.0)
            per = (st.get("replicas") or {}).get("per_replica") or []
            if any(
                r.get("replica") == label and r.get("state") == "healthy"
                for r in per
            ):
                return
        except Exception:  # noqa: BLE001 — keep waiting
            pass
        time.sleep(poll_s)
    raise RestartError(
        f"fleet at {fleet_url} never reinstated {label} inside {timeout_s:.0f}s"
    )


def _relaunch_argv(argv: Sequence[str], compile_cache_dir: Optional[str]):
    """The replica's published relaunch argv, with the cache dir ensured."""
    out: List[str] = list(argv)
    if compile_cache_dir:
        if "--compile-cache-dir" in out:
            i = out.index("--compile-cache-dir")
            if i + 1 < len(out):
                out[i + 1] = compile_cache_dir
        else:
            out += ["--compile-cache-dir", compile_cache_dir]
    return out


def rolling_restart(
    targets: Sequence[str],
    compile_cache_dir: Optional[str] = None,
    drain_timeout_s: float = 120.0,
    warm_timeout_s: float = 600.0,
    poll_s: float = 0.25,
    fleet_url: Optional[str] = None,
    spawn=subprocess.Popen,
    env: Optional[dict] = None,
    emit=None,
) -> dict:
    """Restart every replica in ``targets``, one at a time; the report.

    Per replica: read the ``/readyz`` identity block (pid + the
    ``relaunch_argv``/``cwd`` the server published for exactly this
    purpose), SIGTERM, wait for the listener to close (= drain done),
    relaunch — appending/overriding ``--compile-cache-dir`` when given —
    and wait for the NEW pid's ``/readyz`` 200. With ``fleet_url``, also
    wait for the front-end to reinstate the replica before moving on, so
    at most one replica is ever out of rotation.

    ``spawn`` is injectable (tests capture the relaunched processes);
    the default detaches into a new session with /dev/null stdio — the
    replicas must outlive this orchestrator. A step failure raises
    :class:`RestartError` after recording the partial report on the
    exception (``.report``); the walk never continues past a replica it
    could not bring back.
    """
    say = emit if emit is not None else (lambda msg: log.warning("%s", msg))
    urls = [normalize_target(t) for t in targets]
    entries: List[dict] = []
    report = {"schema": SCHEMA_RESTART, "ok": False, "replicas": entries}
    for target in urls:
        label = target_label(target)
        entry: dict = {"replica": label, "target": target}
        entries.append(entry)
        try:
            _, st = _get_json(f"{target}/readyz", timeout_s=10.0)
        except Exception as e:  # noqa: BLE001
            err = RestartError(f"{label}: /readyz unreachable before restart: {e}")
            err.report = report
            raise err from e
        rep = st.get("replica") or {}
        old_pid, argv, cwd = rep.get("pid"), rep.get("relaunch_argv"), rep.get("cwd")
        if not old_pid or not argv:
            err = RestartError(
                f"{label}: /readyz carries no replica identity block "
                "(pid/relaunch_argv) — is this an nm03-serve CLI process?"
            )
            err.report = report
            raise err
        entry["old_pid"] = old_pid
        entry["old_id"] = rep.get("id")
        say(f"fleet restart: draining {label} (pid {old_pid}, id {rep.get('id')})")
        try:
            os.kill(int(old_pid), signal.SIGTERM)
        except ProcessLookupError:
            say(f"fleet restart: {label} pid {old_pid} already gone")
        except OSError as e:
            err = RestartError(f"{label}: SIGTERM pid {old_pid} failed: {e}")
            err.report = report
            raise err from e
        try:
            entry["drain_s"] = round(
                _wait_listener_closed(target, drain_timeout_s, poll_s), 3
            )
            say(f"fleet restart: {label} drained in {entry['drain_s']}s; relaunching")
            launch = _relaunch_argv(argv, compile_cache_dir)
            entry["argv"] = launch
            proc = spawn(
                launch,
                cwd=cwd or None,
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True,
            )
            entry["spawned_pid"] = getattr(proc, "pid", None)
            ready = _wait_ready(target, warm_timeout_s, poll_s, old_pid)
        except RestartError as e:
            entry["error"] = str(e)
            e.report = report
            raise
        except Exception as e:  # noqa: BLE001 — relaunch itself failed
            entry["error"] = str(e)
            err = RestartError(f"{label}: relaunch failed: {e}")
            err.report = report
            raise err from e
        new_rep = ready.get("replica") or {}
        hub = ready.get("compile_hub") or {}
        entry["new_pid"] = new_rep.get("pid")
        entry["new_id"] = new_rep.get("id")
        entry["warm_s"] = ready.get("_warm_wait_s")
        # the cache-hit proof (PR 9): a warm restart deserializes every
        # executable — builds stays 0 and the hits equal the spec count
        entry["builds"] = hub.get("builds")
        entry["cache_hits"] = hub.get("cache_hits")
        entry["cache_misses"] = hub.get("cache_misses")
        entry["compile_cache_hits"] = new_rep.get("compile_cache_hits")
        say(
            f"fleet restart: {label} ready in {entry['warm_s']}s "
            f"(pid {entry['new_pid']}, builds={entry['builds']}, "
            f"cache_hits={entry['cache_hits']})"
        )
        if fleet_url:
            try:
                _wait_fleet_sees(fleet_url, target, warm_timeout_s, poll_s)
            except RestartError as e:
                entry["error"] = str(e)
                e.report = report
                raise
            say(f"fleet restart: front-end reinstated {label}")
        entry["ok"] = True
    report["ok"] = True
    return report
