"""``nm03-fleet``: the replica-fleet front-end and its orchestration.

Four subcommands (docs/OPERATIONS.md, "Running a fleet"):

* ``nm03-fleet serve --replicas URL,URL,...`` — the routing front-end:
  proxies ``POST /v1/segment`` across the replicas with capacity-weighted
  routing, outlier ejection, failover and backpressure propagation, and
  serves its own ``/healthz`` / ``/readyz`` / ``/metrics`` /
  ``/metrics.json`` (the ``fleet_*`` series; ``--slo-*`` flags add the
  SLO plane's burn-rate gauges, ISSUE 14);
* ``nm03-fleet restart --replicas URL,URL,...`` — rolling-restart
  orchestration: drain → relaunch → warm-wait, one replica at a time, so
  a redeploy never drops the fleet below (N−1)/N capacity (pass a shared
  ``--compile-cache-dir`` to make every warm-wait a PR-9 cache hit);
* ``nm03-fleet flightrec --replicas URL,URL,...`` — remote debug pull
  (ISSUE 14): fetch every replica's ``GET /debug/flightrec`` (the PR-7
  flight rings) into one dump per replica — the wedged-fleet post-mortem
  without SIGUSR2 shell access;
* ``nm03-fleet profile --replicas URL,URL,... --ms N`` — fan an
  on-demand ``jax.profiler`` capture (``GET /debug/profile?ms=N``)
  across the replicas, writing each returned trace archive to disk.

jax-/numpy-free at import by contract (NM301 pins the package): a fleet
front-end must start in milliseconds and never claim a chip.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-fleet", description=__doc__.strip().splitlines()[0]
    )
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser(
        "serve", help="run the fleet routing front-end",
        description="Proxy POST /v1/segment across N nm03-serve replicas "
        "with capacity-weighted routing, ejection/probation, failover and "
        "Retry-After propagation (docs/OPERATIONS.md, 'Running a fleet').",
    )
    s.add_argument(
        "--replicas", required=True, metavar="URL[,URL...]",
        help="comma list of replica base URLs (host:port accepted)",
    )
    s.add_argument("--host", default="127.0.0.1", help="bind address")
    s.add_argument(
        "--port", type=int, default=8070, help="bind port (0 = ephemeral)"
    )
    s.add_argument(
        "--port-file", default=None, metavar="PATH",
        help="write the bound port here once listening (written atomically)",
    )
    s.add_argument(
        "--health-interval-s", type=float, default=1.0,
        help="replica /readyz poll cadence — the ejection detection latency",
    )
    s.add_argument(
        "--probe-interval-s", type=float, default=5.0,
        help="probation canary cadence for ejected replicas (an off-path "
        "POST /v1/segment on a synthetic slice; success reinstates)",
    )
    s.add_argument(
        "--health-timeout-s", type=float, default=2.0,
        help="per-poll HTTP timeout; a poll past this ejects (cause timeout)",
    )
    s.add_argument(
        "--proxy-timeout-s", type=float, default=90.0,
        help="per-hop proxied-request timeout; expiry ejects the replica "
        "and fails the request over",
    )
    s.add_argument(
        "--canary-hw", type=int, default=32, metavar="N",
        help="probation canary slice is NxN zeros, auto-clamped into the "
        "replica's published min-dim..canvas window (this flag is the "
        "floor when the replica publishes neither)",
    )
    s.add_argument(
        "--result-cache-bytes", default="0", metavar="BYTES",
        help="router-side content-addressed result tier budget (k/m/g "
        "suffixes; 0 disables) — a repeated study is answered at the "
        "front-end without spending a replica pick (docs/OPERATIONS.md, "
        "'Running the result tier')",
    )
    s.add_argument(
        "--fault-plan", default=None, metavar="SPEC",
        help="chaos plan (site 'fleet': replica_unreachable / "
        "proxy_io_error; docs/RESILIENCE.md). Default: $NM03_FAULT_PLAN",
    )
    s.add_argument(
        "--metrics-out", default=None, metavar="PATH",
        help="write the fleet_* metrics snapshot here at drain",
    )
    s.add_argument(
        "--log-json", default=None, metavar="PATH",
        help="append fleet events (replica_ejected/reinstated, fleet_drain) "
        "as nm03.events.v1 JSONL here",
    )
    s.add_argument("--verbose", action="store_true", help="enable INFO logging")
    from nm03_capstone_project_tpu.obs.slo import add_slo_args

    add_slo_args(s)  # the fleet-level SLO plane (ISSUE 14)

    r = sub.add_parser(
        "restart", help="rolling-restart the replicas, one at a time",
        description="SIGTERM -> drain-wait -> relaunch (from each "
        "replica's own /readyz relaunch_argv) -> /readyz warm-wait, one "
        "replica at a time; same-host by construction.",
    )
    r.add_argument(
        "--replicas", required=True, metavar="URL[,URL...]",
        help="comma list of replica base URLs, restarted in order",
    )
    r.add_argument(
        "--compile-cache-dir", default=None, metavar="DIR",
        help="ensure every relaunch carries this persistent AOT cache dir "
        "(PR 9) so the warm-wait is a deserialization, not a compile",
    )
    r.add_argument(
        "--fleet-url", default=None, metavar="URL",
        help="an nm03-fleet front-end to consult: wait until it reinstates "
        "each restarted replica before draining the next (guarantees at "
        "most one replica out of rotation)",
    )
    r.add_argument(
        "--drain-timeout-s", type=float, default=120.0,
        help="max wait for a SIGTERMed replica's listener to close",
    )
    r.add_argument(
        "--warm-timeout-s", type=float, default=600.0,
        help="max wait for a relaunched replica's /readyz 200",
    )
    r.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="report format (json = the machine/CI interface)",
    )

    for name, desc in (
        ("flightrec",
         "pull every replica's flight-recorder rings (GET /debug/flightrec) "
         "— the wedged-fleet post-mortem without SIGUSR2 shell access"),
        ("profile",
         "fan an on-demand jax.profiler capture (GET /debug/profile?ms=N) "
         "across every replica and write each trace archive to disk"),
    ):
        d = sub.add_parser(name, help=desc.split(" — ")[0], description=desc)
        d.add_argument(
            "--replicas", required=True, metavar="URL[,URL...]",
            help="comma list of replica base URLs to pull from",
        )
        d.add_argument(
            "--out-dir", default=".", metavar="DIR",
            help="where the per-replica dumps land (created if missing)",
        )
        d.add_argument(
            "--timeout-s", type=float, default=30.0,
            help="per-replica HTTP timeout (profile pulls add the capture "
            "duration on top)",
        )
        if name == "profile":
            d.add_argument(
                "--ms", type=int, default=500, metavar="N",
                help="capture duration per replica in milliseconds "
                "(the server rejects values outside [10, 10000])",
            )
    return p


def _split_targets(spec: str):
    targets = [t.strip() for t in str(spec).split(",") if t.strip()]
    if not targets:
        raise SystemExit("nm03-fleet: --replicas needs at least one URL")
    return targets


def _serve(args) -> int:
    from nm03_capstone_project_tpu.cache import parse_bytes
    from nm03_capstone_project_tpu.fleet.router import (
        FleetApp,
        make_http_server,
    )
    from nm03_capstone_project_tpu.obs import RunContext
    from nm03_capstone_project_tpu.obs.slo import objective_from_args
    from nm03_capstone_project_tpu.resilience import FaultPlan
    from nm03_capstone_project_tpu.utils.reporter import configure_reporting

    configure_reporting(verbose=args.verbose)
    plan = (
        FaultPlan.from_spec(args.fault_plan)
        if args.fault_plan else FaultPlan.from_env()
    )
    obs = RunContext.create(
        "fleet", metrics_out=args.metrics_out, log_json=args.log_json,
        argv=sys.argv[1:],
    )
    app = FleetApp(
        _split_targets(args.replicas),
        obs=obs,
        health_interval_s=args.health_interval_s,
        probe_interval_s=args.probe_interval_s,
        health_timeout_s=args.health_timeout_s,
        proxy_timeout_s=args.proxy_timeout_s,
        canary_hw=args.canary_hw,
        fault_plan=plan,
        slo=objective_from_args(args),
        result_cache_bytes=parse_bytes(
            getattr(args, "result_cache_bytes", "0") or "0"
        ),
    )
    httpd = make_http_server(app, args.host, args.port)
    port = httpd.server_address[1]
    app.start()
    if args.port_file:
        from nm03_capstone_project_tpu.utils.atomicio import atomic_write_text

        atomic_write_text(args.port_file, f"{port}\n")
    print(
        f"nm03-fleet: listening on {args.host}:{port} "
        f"({app.replicas.healthy_count()}/{len(app.replicas)} replicas "
        "healthy)",
        flush=True,
    )

    def _drain_and_stop(signum, frame):
        def work():
            app.begin_drain(reason=signal.Signals(signum).name.lower())
            httpd.shutdown()

        threading.Thread(target=work, name="nm03-fleet-drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _drain_and_stop)
    signal.signal(signal.SIGINT, _drain_and_stop)
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        app.begin_drain(reason="exit")  # idempotent after a signal drain
        app.close(status="ok")
    print("nm03-fleet: drained and stopped", flush=True)
    return 0


def _restart(args) -> int:
    from nm03_capstone_project_tpu.fleet.manager import (
        RestartError,
        rolling_restart,
    )

    def emit(msg: str) -> None:
        print(msg, file=sys.stderr, flush=True)

    try:
        report = rolling_restart(
            _split_targets(args.replicas),
            compile_cache_dir=args.compile_cache_dir,
            drain_timeout_s=args.drain_timeout_s,
            warm_timeout_s=args.warm_timeout_s,
            fleet_url=args.fleet_url,
            emit=emit,
        )
    except RestartError as e:
        report = getattr(e, "report", {"ok": False, "replicas": []})
        print(json.dumps(report, indent=2))
        print(f"nm03-fleet restart: FAILED: {e}", file=sys.stderr, flush=True)
        return 1
    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        for r in report["replicas"]:
            print(
                f"{r['replica']:<22} pid {r['old_pid']} -> {r['new_pid']}  "
                f"drain {r['drain_s']}s  warm {r['warm_s']}s  "
                f"builds {r['builds']}  cache_hits {r['cache_hits']}"
            )
        done = sum(1 for r in report["replicas"] if r.get("ok"))
        print(
            f"nm03-fleet restart: {done}/{len(report['replicas'])} replicas "
            "restarted",
            flush=True,
        )
    return 0


def _debug_pull(args, command: str) -> int:
    """Fan one ``/debug/*`` pull across every replica, concurrently.

    One thread per target (a profile pull BLOCKS for the capture
    duration server-side — serial pulls would stretch an N-replica
    post-mortem N×); every reachable replica's evidence is written even
    when others are wedged — exit 1 reports the partial pull, it never
    discards it.
    """
    import os
    import threading
    import urllib.request

    from nm03_capstone_project_tpu.fleet.replicas import (
        normalize_target,
        target_label,
    )
    from nm03_capstone_project_tpu.utils.atomicio import atomic_write_text

    targets = [normalize_target(t) for t in _split_targets(args.replicas)]
    os.makedirs(args.out_dir, exist_ok=True)
    if command == "profile":
        path, timeout = f"/debug/profile?ms={args.ms}", (
            args.timeout_s + args.ms / 1e3
        )
    else:
        path, timeout = "/debug/flightrec", args.timeout_s
    results = {}
    lock = threading.Lock()

    def pull(target: str) -> None:
        label = target_label(target)
        safe = label.replace(":", "_")
        try:
            with urllib.request.urlopen(
                f"{target}{path}", timeout=timeout
            ) as resp:
                payload = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 — a dead replica is a row
            with lock:
                results[label] = {"ok": False, "error": str(e)}
            return
        out = {"ok": True}
        if command == "profile":
            zip_b64 = payload.pop("zip_b64", None)
            json_path = os.path.join(args.out_dir, f"profile_{safe}.json")
            atomic_write_text(json_path, json.dumps(payload, indent=1) + "\n")
            out["json"] = json_path
            out["files"] = len(payload.get("files") or [])
            if zip_b64 is not None:
                import base64

                from nm03_capstone_project_tpu.utils.atomicio import (
                    atomic_write_bytes,
                )

                zip_path = os.path.join(args.out_dir, f"profile_{safe}.zip")
                atomic_write_bytes(zip_path, base64.b64decode(zip_b64))
                out["zip"] = zip_path
            elif payload.get("zip_dropped"):
                # archive over the wire cap: it survives ON the replica —
                # the row names where to fetch it out of band
                out["zip"] = None
                out["remote_zip"] = payload.get("zip_path")
        else:
            dump_path = os.path.join(args.out_dir, f"flightrec_{safe}.json")
            atomic_write_text(dump_path, json.dumps(payload, indent=1) + "\n")
            out["json"] = dump_path
            out["threads"] = len(payload.get("threads") or {})
            out["records"] = payload.get("records_total")
        with lock:
            results[label] = out

    threads = [
        threading.Thread(target=pull, args=(t,), daemon=True) for t in targets
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 30)
    failed = 0
    for target in targets:
        label = target_label(target)
        r = results.get(label, {"ok": False, "error": "pull thread hung"})
        if r.get("ok"):
            detail = " ".join(
                f"{k}={v}" for k, v in sorted(r.items()) if k != "ok"
            )
            print(f"{label:<22} ok  {detail}")
        else:
            failed += 1
            print(f"{label:<22} FAILED  {r.get('error')}", file=sys.stderr)
    print(
        f"nm03-fleet {command}: {len(targets) - failed}/{len(targets)} "
        f"replica(s) pulled -> {args.out_dir}",
        flush=True,
    )
    return 1 if failed else 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command == "serve":
        from nm03_capstone_project_tpu.obs.slo import objective_from_args

        try:
            objective_from_args(args)  # a bad --slo-* is a usage error,
        except ValueError as e:        # not a traceback mid-startup
            parser.error(str(e))
        return _serve(args)
    if args.command in ("flightrec", "profile"):
        return _debug_pull(args, args.command)
    return _restart(args)


if __name__ == "__main__":
    raise SystemExit(main())
