"""Typed configuration covering every constant the reference hard-codes.

The reference inlines all pipeline hyper-parameters at call sites (see
SURVEY.md section 5 "Config / flag system"); this module lifts each one into a
frozen dataclass so drivers, tests and benchmarks share a single source of
truth. Each field cites where the reference pins the value.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Hyper-parameters of the 5-stage segmentation pipeline.

    Defaults reproduce the reference's behavioral contract exactly.
    """

    # -- Intensity normalization -------------------------------------------
    # reference: IntensityNormalization::create(0.5f, 2.5f, 0.0f, 10000.0f)
    # (src/test/test_pipeline.cpp:55, src/sequential/main_sequential.cpp:195-196)
    norm_low: float = 0.5
    norm_high: float = 2.5
    norm_intensity_min: float = 0.0
    norm_intensity_max: float = 10000.0

    # -- Intensity clipping -------------------------------------------------
    # reference: IntensityClipping::create(0.68f, 4000.0f)
    # (src/test/test_pipeline.cpp:60, main_sequential.cpp:200)
    clip_low: float = 0.68
    clip_high: float = 4000.0

    # -- Vector median filter -----------------------------------------------
    # reference: VectorMedianFilter::create(7) (test_pipeline.cpp:65-66)
    median_window: int = 7

    # -- Unsharp sharpening --------------------------------------------------
    # reference: ImageSharpening::create(2.0f, 0.5f, 9) (test_pipeline.cpp:71)
    sharpen_gain: float = 2.0
    sharpen_sigma: float = 0.5
    sharpen_kernel: int = 9

    # -- Seeded region growing ----------------------------------------------
    # reference: SeededRegionGrowing::create(0.74f, 0.91f, seeds)
    # (test_pipeline.cpp:98, main_sequential.cpp:232-233)
    grow_low: float = 0.74
    grow_high: float = 0.91

    # -- Morphology -----------------------------------------------------------
    # reference: Dilation::create(3) / Erosion::create(3)
    # (test_pipeline.cpp:119-125, main_sequential.cpp:250)
    morph_size: int = 3

    # -- Guards ---------------------------------------------------------------
    # reference: width/height < 100 -> exception (main_sequential.cpp:189-192)
    min_dim: int = 100

    # -- Render / export -------------------------------------------------------
    # reference: RenderToImage::create(Color::Black(), 512, 512)
    # (test_pipeline.cpp:164, main_sequential.cpp:258); SegmentationRenderer
    # (labelColors={1: White}, opacity 0.6, borderOpacity 1.0, borderRadius 2)
    # (test_pipeline.cpp:136-146)
    render_size: int = 512
    overlay_opacity: float = 0.6
    overlay_border_opacity: float = 1.0
    overlay_border_radius: int = 2

    # -- Compute policy (TPU-native; no reference equivalent) ------------------
    # Static canvas the variable-size DICOM slices are padded to so that one
    # compiled program serves the whole cohort (jit demands static shapes).
    canvas: int = 256
    # Region-growing fixpoint: dilations per convergence check and a hard cap.
    grow_block_iters: int = 16
    grow_max_iters: int = 1024
    # Convergence schedule for the 2D fill: "dilate" = one-ring-per-step
    # fixpoint (sequential depth = region diameter, truncated at
    # grow_max_iters); "jump" = pointer-jumping label merge, O(log diameter)
    # rounds (ops.region_growing.region_grow_jump) — for latency-bound
    # accelerators. Identical masks whenever the dilate path converges within
    # its cap (always, for clinical-shaped regions; a >grow_max_iters
    # serpentine path truncates dilate but not jump). Honored by the 2D
    # drivers and single-device volumes (region_grow_jump_3d); the z-sharded
    # volume path implements only the halo-exchange fixpoint. Mutually
    # exclusive with use_pallas (the Pallas grow kernel is dilate-schedule).
    grow_algorithm: str = "dilate"
    # Route the hot ops through the Pallas TPU kernels (ops.pallas_median,
    # ops.pallas_region_growing) instead of the portable XLA implementations.
    # Defaults False until the caller knows it's on a TPU backend.
    use_pallas: bool = False
    # XLA median implementation: 'pruned' (the liveness-pruned selection
    # network, the fast default — ops.selection_network), 'merge' (the full
    # odd-even merge baseline it is counted/benchmarked against), or 'sort'
    # (the materialize-and-sort oracle). All bit-identical on real data.
    median_impl: str = "pruned"
    # Fuse normalize->clip->median->sharpen into one VMEM-resident Pallas
    # kernel when running on TPU with use_pallas (one HBM read of the image
    # instead of four stage round trips); off-TPU the stages compose in XLA
    # (which fuses them itself) regardless of this flag.
    fuse_preprocess: bool = True
    # Fused device render: one jitted pass sharing the letterbox geometry
    # between the grayscale and segmentation renders, with the mask leg in
    # uint8 (render.render_pair_fused — pixel-identical to the unfused
    # pair; False restores the two independent render calls).
    render_fused: bool = True

    def __post_init__(self):
        # Fail at construction (CLI parse time), not deep inside a traced op.
        if self.median_window < 1 or self.median_window % 2 == 0:
            raise ValueError(
                f"median_window must be odd and >= 1, got {self.median_window}"
            )
        if self.sharpen_kernel < 1 or self.sharpen_kernel % 2 == 0:
            raise ValueError(
                f"sharpen_kernel must be odd and >= 1, got {self.sharpen_kernel}"
            )
        if self.morph_size < 1 or self.morph_size % 2 == 0:
            raise ValueError(
                f"morph_size must be odd and >= 1, got {self.morph_size}"
            )
        if not self.grow_low <= self.grow_high:
            raise ValueError(
                f"grow band is empty: [{self.grow_low}, {self.grow_high}]"
            )
        if self.canvas < 1:
            raise ValueError(f"canvas must be positive, got {self.canvas}")
        if self.grow_block_iters < 1 or self.grow_max_iters < 1:
            raise ValueError("grow iteration counts must be positive")
        if self.grow_algorithm not in ("dilate", "jump"):
            raise ValueError(
                f"grow_algorithm must be 'dilate' or 'jump', got "
                f"{self.grow_algorithm!r}"
            )
        if self.grow_algorithm == "jump" and self.use_pallas:
            raise ValueError(
                "grow_algorithm='jump' and use_pallas are mutually exclusive: "
                "the Pallas grow kernel implements the dilate schedule, so the "
                "jump request would be silently ignored on TPU — pick one"
            )
        if self.median_impl not in ("pruned", "merge", "sort"):
            raise ValueError(
                f"median_impl must be 'pruned', 'merge' or 'sort', got "
                f"{self.median_impl!r}"
            )

    @property
    def canvas_hw(self) -> Tuple[int, int]:
        return (self.canvas, self.canvas)


@dataclasses.dataclass(frozen=True)
class BatchConfig:
    """Batch-orchestration knobs.

    The reference fixes DEFAULT_BATCH_SIZE = 25 ("maximum number of slices per
    patient", src/parallel/main_parallel.cpp:31-33) and 16 OpenMP threads
    (main_parallel.cpp:401). On TPU the batch is a vmapped leading axis; the
    size is a padding granularity rather than a thread count.
    """

    batch_size: int = 25
    prefetch_depth: int = 2  # staged (device-side) lookahead: double buffering
    io_workers: int = 8  # DICOM decode thread pool
    # streaming ingest (ingest/, docs/OPERATIONS.md "Feeding the chip"):
    # ring capacity in host batches decoded ahead of the chip — the
    # backpressure bound (decode can never outrun HBM by more than
    # ingest_depth + in-flight decodes + prefetch_depth batches)
    ingest_depth: int = 3
    # decode pool size for the ingest pipeline; 0 = use io_workers
    ingest_decode_workers: int = 0
    use_native: bool = True  # C++ batch decoder (csrc/) when buildable
    # 'host': device returns only the mask (65 KB/slice) and the 512x512
    # export renders are computed host-side in the IO pool — the default,
    # since shipping two rendered canvases (~1.5 MB/slice) back through the
    # host<->device link dominated cohort wall-clock on the tunneled chip.
    # 'device': render inside the jit (render.render_pair), the v1 behavior.
    render_stage: str = "host"

    def __post_init__(self):
        if self.render_stage not in ("host", "device"):
            raise ValueError(
                f"render_stage must be 'host' or 'device', got {self.render_stage!r}"
            )
        if self.ingest_depth < 1:
            raise ValueError(
                f"ingest_depth must be >= 1, got {self.ingest_depth}"
            )
        if self.ingest_decode_workers < 0:
            raise ValueError(
                f"ingest_decode_workers must be >= 0 (0 = io_workers), "
                f"got {self.ingest_decode_workers}"
            )


DEFAULT_CONFIG = PipelineConfig()
DEFAULT_BATCH = BatchConfig()
