"""Model checkpointing via orbax.

The reference has no checkpoint/resume at all — a rerun wipes its outputs
(``rm -rf`` in setupOutputDirectory, main_sequential.cpp:35-37; SURVEY.md
section 5). The batch drivers got a resumable manifest (utils.manifest);
this module is the same story for the learned model family: parameters and
training metadata survive restarts, and a fine-tune can restore and
continue. Orbax handles sharded arrays natively, so a checkpoint written
from a ('data', 'model') mesh restores onto a different topology (with
replication) or the same one (preserving layouts when a target is given).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

from nm03_capstone_project_tpu.utils.atomicio import atomic_write_text

Params = Dict[str, Any]


def save_params(
    path: str | Path, params: Params, meta: Optional[dict] = None
) -> None:
    """Write ``params`` (any pytree of arrays) plus a JSON metadata sidecar.

    ``meta`` should carry what's needed to rebuild the model skeleton
    (base channels, levels, training step count...).
    """
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    # force: a fine-tune run saves back into the checkpoint it restored from
    ocp.PyTreeCheckpointer().save(path, params, force=True)
    if meta is not None:
        # atomic (NM351): load_params treats meta.json as truth about the
        # weights next to it; a torn sidecar must never deploy
        atomic_write_text(path / "meta.json", json.dumps(meta, indent=1) + "\n")


def load_params(
    path: str | Path, target: Optional[Params] = None
) -> Tuple[Params, Optional[dict]]:
    """Restore (params, meta). ``target`` (a matching pytree, e.g. a fresh
    ``init_unet`` result) pins dtypes/shardings; without it orbax restores
    from the recorded layout."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    if not path.exists():
        raise FileNotFoundError(f"no checkpoint at {path}")
    if target is not None:
        params = ocp.PyTreeCheckpointer().restore(path, item=target)
    else:
        params = ocp.PyTreeCheckpointer().restore(path)
    meta_file = path / "meta.json"
    meta = json.loads(meta_file.read_text()) if meta_file.exists() else None
    return params, meta
