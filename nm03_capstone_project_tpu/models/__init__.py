"""Learned model family: pipeline-distilled segmentation networks.

The reference has no trainable models — its whole compute is the classical
operator chain. This package is the framework's learned-capability analog:
a U-Net student distilled from that chain (the teacher), with single-chip
and mesh-sharded (data x tensor parallel) training steps.
"""

from nm03_capstone_project_tpu.models.checkpoint import (  # noqa: F401
    load_params,
    save_params,
)
from nm03_capstone_project_tpu.models.train import (  # noqa: F401
    distill_batch,
    fit,
    fit_distributed,
    fit_sharded,
    pad_local_shard,
    make_optimizer,
    make_sharded_train_step,
    prepare_student_inputs,
    segmentation_loss,
    train_step,
)
from nm03_capstone_project_tpu.models.unet import (  # noqa: F401
    apply_unet,
    init_unet,
    param_shardings,
    predict_mask,
)
from nm03_capstone_project_tpu.models.unet3d import (  # noqa: F401
    apply_unet3d,
    distill_volume,
    init_unet3d,
    predict_mask3d,
)
