"""Distillation training for the segmentation U-Net.

The teacher is the classical pipeline (pipeline.slice_pipeline.process_batch
— the reference's exact operator chain); the student is models.unet. Labels
therefore cost nothing: any cohort, synthetic or real, self-labels by
running the teacher once, which is the TPU-native answer to "the reference
has no training data pipeline".

The train step is one fused jit program: forward (MXU convs), loss
(BCE-with-logits + soft Dice, both mask-weighted to the slice's true
extent), backward, and an Adam update via optax. Sharded training runs the
same step over a ('data', 'model') mesh: batches split on 'data' (the
reference's OpenMP axis), parameters split on output channels over 'model'
(tensor parallelism); GSPMD inserts the gradient psums over ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from nm03_capstone_project_tpu.compilehub import hub_jit
from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.core.image import valid_mask
from nm03_capstone_project_tpu.models.unet import apply_unet, param_shardings

Params = Dict[str, Any]


@functools.lru_cache(maxsize=16)
def make_optimizer(
    lr: float = 1e-3, weight_decay: float = 1e-4, total_steps: Optional[int] = None
):
    """Clipped AdamW; with ``total_steps`` the lr follows warmup->cosine.

    Distillation on small batches oscillates under constant lr (the loss was
    observed bouncing 0.5 <-> 1.3 at 3e-3); the 5% linear warmup + cosine
    decay stabilizes the endgame where the mask threshold (logit 0) lives.

    Cached per hyper-parameter tuple: ``train_step`` jits with the
    GradientTransformation as a static argument (hashed by identity), so
    identical-hyperparameter ``fit`` calls must receive the SAME instance
    or every call retraces the whole fused step. optax transformations are
    stateless (all state lives in the ``init``-returned pytree), so
    sharing the instance is safe.
    """
    if total_steps:
        warmup = max(1, total_steps // 20)
        # optax requires a positive cosine phase (decay_steps > warmup);
        # 1-2 step runs (smoke tests) would otherwise hit decay_steps=0
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, lr, warmup, max(total_steps, warmup + 1), end_value=lr * 0.01
        )
    else:
        schedule = lr
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, weight_decay=weight_decay),
    )


def segmentation_loss(
    logits: jax.Array, labels: jax.Array, dims: jax.Array
) -> jax.Array:
    """BCE + soft-Dice, restricted to each slice's valid region.

    ``labels`` is the teacher's uint8 mask, ``dims`` the (B, 2) true extents;
    canvas padding must not teach the student anything, so both terms are
    weighted by the validity mask. Works for slice batches (B, H, W) and
    volume batches (B, D, H, W) — every plane of a volume shares its series'
    in-plane extent, so the 2D validity mask broadcasts over depth.
    """
    canvas_hw = (logits.shape[-2], logits.shape[-1])
    w = valid_mask(dims, canvas_hw).astype(jnp.float32)
    if logits.ndim == w.ndim + 1:  # (B, D, H, W) logits, (B, H, W) mask
        # materialize the depth axis: w.sum() must count every valid voxel
        # or the BCE normalizer is off by a factor of D
        w = jnp.broadcast_to(w[..., None, :, :], logits.shape)
    y = labels.astype(jnp.float32)
    bce = optax.sigmoid_binary_cross_entropy(logits, y)
    bce = (bce * w).sum() / jnp.maximum(w.sum(), 1.0)
    p = jax.nn.sigmoid(logits) * w
    inter = (p * y).sum(axis=(-2, -1))
    denom = p.sum(axis=(-2, -1)) + (y * w).sum(axis=(-2, -1))
    dice = 1.0 - (2.0 * inter + 1.0) / (denom + 1.0)
    return bce + dice.mean()


@functools.partial(hub_jit, static_argnames=("tx", "compute_dtype", "apply_fn"))
def train_step(
    params: Params,
    opt_state,
    pixels: jax.Array,
    labels: jax.Array,
    dims: jax.Array,
    *,
    tx,
    compute_dtype=jnp.float32,
    apply_fn=None,
) -> Tuple[Params, Any, jax.Array]:
    """One SGD step; returns (params, opt_state, loss). jit-compiled.

    ``apply_fn`` selects the model family (default: the 2D U-Net; pass
    ``unet3d.apply_unet3d`` for volume batches).
    """
    apply_fn = apply_fn or apply_unet

    def loss_fn(p):
        logits = apply_fn(p, pixels, compute_dtype)
        return segmentation_loss(logits, labels, dims)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def make_sharded_train_step(mesh, params: Params, tx, compute_dtype=jnp.bfloat16):
    """jit the train step over a ('data', 'model') mesh.

    Returns (step_fn, place_params) where ``place_params`` device_puts a host
    param pytree into its tensor-parallel layout. Batch arrays shard on
    'data'; optimizer state follows the parameters' shardings (optax states
    mirror the param pytree structure leaf-for-leaf).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    p_shard = param_shardings(params, mesh)
    batch_shard = NamedSharding(mesh, P("data"))

    # structure-only trace: no host compute, just the opt-state pytree shape.
    # param_shardings works on ShapeDtypeStruct leaves too, so adamw's mu/nu
    # (which copy the param pytree leaf-for-leaf) land on the same devices as
    # their params by construction.
    opt_template = jax.eval_shape(tx.init, params)
    o_shard = param_shardings(opt_template, mesh)

    def step(params, opt_state, pixels, labels, dims):
        def loss_fn(p):
            logits = apply_unet(p, pixels, compute_dtype)
            return segmentation_loss(logits, labels, dims)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step_fn = hub_jit(
        step,
        in_shardings=(p_shard, o_shard, batch_shard, batch_shard, batch_shard),
        out_shardings=(p_shard, o_shard, NamedSharding(mesh, P())),
    )

    def place_params(host_params):
        return jax.device_put(host_params, p_shard)  # nm03-lint: disable=NM401 one-time model-weight placement, not the batch data path the ingest pipeline owns

    return step_fn, place_params


def prepare_student_inputs(
    pixels: jax.Array, cfg: Optional[PipelineConfig] = None
) -> jax.Array:
    """Normalize + clip raw DICOM-scale intensities for the student.

    The pipeline's two cheap elementwise front stages (the reference's
    IntensityNormalization + IntensityClipping contract) map intensities
    into ~[0.68, 2.5] — O(1) activations for the network. At deployment the
    student consumes this and replaces everything downstream of it (the
    7x7 median, sharpening, region-growing fixpoint and morphology — all
    the expensive stages).
    """
    from nm03_capstone_project_tpu.ops.elementwise import clip_intensity, normalize

    cfg = cfg or PipelineConfig()
    x = normalize(
        pixels, cfg.norm_low, cfg.norm_high, cfg.norm_intensity_min, cfg.norm_intensity_max
    )
    return clip_intensity(x, cfg.clip_low, cfg.clip_high)


def distill_batch(
    pixels: jax.Array, dims: jax.Array, cfg: Optional[PipelineConfig] = None
) -> jax.Array:
    """Teacher labels: run the classical pipeline, return its uint8 masks."""
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    cfg = cfg or PipelineConfig()
    return process_batch(pixels, dims, cfg)["mask"]


def fit(
    params: Params,
    pixels,
    labels,
    dims,
    steps: int = 50,
    lr: float = 1e-3,
    compute_dtype=jnp.float32,
    apply_fn=None,
):
    """Small in-memory training loop (tests / single-chip fine-tuning).

    Returns (params, list of losses). Multi-chip training drives
    :func:`make_sharded_train_step` directly.
    """
    tx = make_optimizer(lr, total_steps=steps)
    opt_state = tx.init(params)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = train_step(
            params,
            opt_state,
            pixels,
            labels,
            dims,
            tx=tx,
            compute_dtype=compute_dtype,
            apply_fn=apply_fn,
        )
        # keep the loss on device: a float() here would sync every step and
        # serialize dispatch (per-step round trip on a remote chip)
        losses.append(loss)
    return params, [float(l) for l in losses]


def fit_sharded(
    params: Params,
    pixels,
    labels,
    dims,
    mesh,
    steps: int = 50,
    lr: float = 1e-3,
    compute_dtype=jnp.float32,
):
    """Multi-device dp x tp training loop (2D student).

    Same contract as :func:`fit` but the batch shards over the mesh's
    ``data`` axis and parameters split over ``model``
    (:func:`make_sharded_train_step`). The batch is padded to a multiple of
    the data-axis size by WRAPPING real slices — repeats only reweight the
    mean loss slightly, where degenerate filler slices would add spurious
    dice terms (segmentation_loss averages dice over batch rows). Returns
    host-resident params so checkpointing is layout-independent.
    """
    dp = mesh.shape["data"]
    b = pixels.shape[0]
    if b % dp:
        target = ((b + dp - 1) // dp) * dp
        pixels, labels, dims = pad_local_shard(pixels, labels, dims, target)
    tx = make_optimizer(lr, total_steps=steps)
    step_fn, place_params = make_sharded_train_step(
        mesh, params, tx, compute_dtype=compute_dtype
    )
    params = place_params(params)
    opt_state = tx.init(params)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, pixels, labels, dims)
        losses.append(loss)  # device-resident; one sync after the loop
    return jax.device_get(params), [float(l) for l in losses]


def fit_distributed(
    params: Params,
    local_pixels,
    local_labels,
    local_dims,
    steps: int = 50,
    lr: float = 1e-3,
    compute_dtype=jnp.float32,
):
    """Multi-host data-parallel training loop (2D student).

    Each process passes its LOCAL slice shard (already distilled locally —
    teacher labeling scales linearly with hosts); the shards concatenate
    into one global batch over a ('data', 'model') mesh spanning every
    device of the job, model axis 1 (pure dp: tensor parallelism across DCN
    would put an all-reduce on the slow links for no win at this model
    size). Gradients psum over the global data axis, so every host steps
    identically; params return host-resident and replicated.

    All processes must call this together (every step is a collective).
    Local shards must have identical shapes across processes — pad with
    :func:`pad_local_shard` first.
    """
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from nm03_capstone_project_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(axis_names=("data", "model"))  # all devices on 'data'
    gx = multihost_utils.host_local_array_to_global_array(
        np.asarray(local_pixels), mesh, P("data")
    )
    gl = multihost_utils.host_local_array_to_global_array(
        np.asarray(local_labels), mesh, P("data")
    )
    gd = multihost_utils.host_local_array_to_global_array(
        np.asarray(local_dims), mesh, P("data")
    )
    tx = make_optimizer(lr, total_steps=steps)
    step_fn, place_params = make_sharded_train_step(
        mesh, params, tx, compute_dtype=compute_dtype
    )
    params = place_params(params)
    opt_state = tx.init(params)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, gx, gl, gd)
        # loss is replicated (P()) so every host can read its local copy;
        # kept on device until after the loop so steps enqueue back-to-back
        losses.append(loss)
    losses = [float(np.asarray(jax.device_get(l))) for l in losses]
    host_params = multihost_utils.global_array_to_host_local_array(
        params, mesh, jax.tree_util.tree_map(lambda _: P(), params)
    )
    return jax.device_get(host_params), losses


def pad_local_shard(pixels, labels, dims, target: int):
    """Wrap-pad a local batch to exactly ``target`` rows (a size every host
    agreed on), so the per-host shards concatenate into an evenly-sharded
    global batch. Repeating real slices only reweights the mean loss
    slightly; degenerate filler would add spurious dice terms.
    """
    import numpy as np

    b = pixels.shape[0]
    if target < b:
        raise ValueError(f"target {target} < local batch {b}")
    if target == b:
        return np.asarray(pixels), np.asarray(labels), np.asarray(dims)
    idx = np.arange(target) % b
    return (
        np.asarray(pixels)[idx],
        np.asarray(labels)[idx],
        np.asarray(dims)[idx],
    )
