"""Segmentation U-Net — the framework's learned model family.

The reference is a purely classical pipeline (no training anywhere in-tree);
this model is the TPU-native capability analog: a small encoder-decoder
segmentation network *distilled from* the classical pipeline
(models.train.distill_batch generates (phantom, pipeline-mask) pairs), so a
user can trade the iterative region-growing fixpoint for one fused
MXU-friendly forward pass at deployment.

Design notes (TPU-first):
* NHWC layout with 3x3 convs via ``lax.conv_general_dilated`` — the FLOPs
  land on the MXU; channel counts are multiples of 8 so the lanes tile.
* Compute dtype is a parameter (bfloat16 on TPU, float32 in tests); the
  parameters stay float32 and are cast per call (standard mixed precision).
* Parameters are a plain nested-dict pytree: trivial to shard with
  ``NamedSharding`` over a ('data', 'model') mesh — kernels split on the
  output-channel axis (tensor parallelism), activations on batch (data
  parallelism); XLA/GSPMD inserts the collectives.
* Down/up-sampling are reduce-window max-pool and nearest-neighbor resize —
  static shapes, no dynamic control flow, one ``jit``-traceable graph.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def _conv_init(key, kh, kw, cin, cout) -> Dict[str, jax.Array]:
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
    w = w * jnp.sqrt(2.0 / fan_in)  # He init for the ReLU blocks
    return {"w": w, "b": jnp.zeros((cout,), jnp.float32)}


def _conv(x: jax.Array, p: Dict[str, jax.Array], dtype) -> jax.Array:
    out = jax.lax.conv_general_dilated(
        x.astype(dtype),
        p["w"].astype(dtype),
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + p["b"].astype(dtype)


def _block(x, p, dtype):
    x = jax.nn.relu(_conv(x, p["c1"], dtype))
    return jax.nn.relu(_conv(x, p["c2"], dtype))


def _pool(x):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def _upsample(x):
    n, h, w, c = x.shape
    return jnp.broadcast_to(
        x[:, :, None, :, None, :], (n, h, 2, w, 2, c)
    ).reshape(n, 2 * h, 2 * w, c)


def init_unet(
    key: jax.Array, base: int = 16, levels: int = 2, in_ch: int = 1
) -> Params:
    """Initialize parameters: ``levels`` encoder/decoder stages + bottleneck.

    Channel widths are base * 2**level; with the default base=16 the largest
    kernels are (3, 3, 32, 64) — small enough for CI, wide enough that every
    conv is an MXU matmul rather than a VPU dribble.
    """
    if base % 8:
        raise ValueError(f"base channels must be a multiple of 8, got {base}")
    params: Params = {"enc": [], "dec": []}
    cin = in_ch
    for lv in range(levels):
        key, k1, k2 = jax.random.split(key, 3)
        cout = base * (2**lv)
        params["enc"].append(
            {"c1": _conv_init(k1, 3, 3, cin, cout), "c2": _conv_init(k2, 3, 3, cout, cout)}
        )
        cin = cout
    key, k1, k2 = jax.random.split(key, 3)
    cmid = base * (2**levels)
    params["mid"] = {
        "c1": _conv_init(k1, 3, 3, cin, cmid),
        "c2": _conv_init(k2, 3, 3, cmid, cmid),
    }
    cin = cmid
    for lv in reversed(range(levels)):
        key, k1, k2 = jax.random.split(key, 3)
        cout = base * (2**lv)
        params["dec"].append(
            {
                # input = upsampled decoder features + the skip connection
                "c1": _conv_init(k1, 3, 3, cin + cout, cout),
                "c2": _conv_init(k2, 3, 3, cout, cout),
            }
        )
        cin = cout
    key, kh = jax.random.split(key)
    params["head"] = _conv_init(kh, 1, 1, cin, 8)  # 8 not 1: lane-aligned
    return params


def apply_unet(
    params: Params, pixels: jax.Array, compute_dtype=jnp.bfloat16
) -> jax.Array:
    """Forward pass: (B, H, W) float pixels -> (B, H, W) float32 logits.

    H and W must be divisible by 2**levels (the pipeline canvas, a power of
    two, always is). The 8-channel head is summed into the single logit map
    (cheap, keeps the last matmul lane-aligned).
    """
    x = pixels[..., None]  # NHWC
    skips = []
    for p in params["enc"]:
        x = _block(x, p, compute_dtype)
        skips.append(x)
        x = _pool(x)
    x = _block(x, params["mid"], compute_dtype)
    for p, skip in zip(params["dec"], reversed(skips)):
        x = _upsample(x)
        x = jnp.concatenate([x, skip.astype(x.dtype)], axis=-1)
        x = _block(x, p, compute_dtype)
    logits8 = _conv(x, params["head"], compute_dtype)
    return logits8.sum(axis=-1).astype(jnp.float32)


def predict_mask(params: Params, pixels: jax.Array, compute_dtype=jnp.bfloat16) -> jax.Array:
    """uint8 mask like the classical pipeline's output contract."""
    return (apply_unet(params, pixels, compute_dtype) > 0).astype(jnp.uint8)


def param_shardings(params: Params, mesh) -> Params:
    """NamedSharding pytree: kernels split on the output-channel axis over the
    mesh's 'model' axis (tensor parallelism) when divisible, else replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = mesh.shape.get("model", 1)

    def shard_leaf(leaf):
        if leaf.ndim >= 1 and leaf.shape[-1] % tp == 0 and tp > 1:
            spec = [None] * (leaf.ndim - 1) + ["model"]
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(shard_leaf, params)
