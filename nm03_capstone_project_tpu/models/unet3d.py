"""3D segmentation U-Net — the volumetric member of the learned family.

Student counterpart of the volumetric pipeline
(:mod:`pipeline.volume_pipeline`): where the 2D student distills the
per-slice chain, this one distills the 3D teacher (6-connected growing +
3D morphology), learning through-plane context the 2D model cannot see.

Same TPU-first construction as :mod:`models.unet`: NDHWC layout, 3x3x3
convs via ``lax.conv_general_dilated`` (MXU), lane-aligned channel widths,
float32 parameters with a caller-chosen compute dtype, plain nested-dict
pytrees that :func:`models.unet.param_shardings` shards on output channels
unchanged. Pooling/upsampling act on (D, H, W) jointly (2x2x2), so the
volume must have D, H, W divisible by 2**levels.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def _conv_init(key, k, cin, cout) -> Dict[str, jax.Array]:
    fan_in = k * k * k * cin
    w = jax.random.normal(key, (k, k, k, cin, cout), jnp.float32)
    return {"w": w * jnp.sqrt(2.0 / fan_in), "b": jnp.zeros((cout,), jnp.float32)}


def _conv(x, p, dtype):
    out = jax.lax.conv_general_dilated(
        x.astype(dtype),
        p["w"].astype(dtype),
        window_strides=(1, 1, 1),
        padding="SAME",
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )
    return out + p["b"].astype(dtype)


def _block(x, p, dtype):
    x = jax.nn.relu(_conv(x, p["c1"], dtype))
    return jax.nn.relu(_conv(x, p["c2"], dtype))


def _pool(x):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 2, 1), (1, 2, 2, 2, 1), "VALID"
    )


def _upsample(x):
    n, d, h, w, c = x.shape
    return jnp.broadcast_to(
        x[:, :, None, :, None, :, None, :], (n, d, 2, h, 2, w, 2, c)
    ).reshape(n, 2 * d, 2 * h, 2 * w, c)


def init_unet3d(
    key: jax.Array, base: int = 8, levels: int = 2, in_ch: int = 1
) -> Params:
    """Same skeleton as the 2D family; 3x3x3 kernels, base * 2**level widths."""
    if base % 8:
        raise ValueError(f"base channels must be a multiple of 8, got {base}")
    params: Params = {"enc": [], "dec": []}
    cin = in_ch
    for lv in range(levels):
        key, k1, k2 = jax.random.split(key, 3)
        cout = base * (2**lv)
        params["enc"].append(
            {"c1": _conv_init(k1, 3, cin, cout), "c2": _conv_init(k2, 3, cout, cout)}
        )
        cin = cout
    key, k1, k2 = jax.random.split(key, 3)
    cmid = base * (2**levels)
    params["mid"] = {
        "c1": _conv_init(k1, 3, cin, cmid),
        "c2": _conv_init(k2, 3, cmid, cmid),
    }
    cin = cmid
    for lv in reversed(range(levels)):
        key, k1, k2 = jax.random.split(key, 3)
        cout = base * (2**lv)
        params["dec"].append(
            {
                "c1": _conv_init(k1, 3, cin + cout, cout),
                "c2": _conv_init(k2, 3, cout, cout),
            }
        )
        cin = cout
    key, kh = jax.random.split(key)
    params["head"] = _conv_init(kh, 1, cin, 8)  # lane-aligned head, summed
    return params


def apply_unet3d(
    params: Params, volume: jax.Array, compute_dtype=jnp.bfloat16
) -> jax.Array:
    """(B, D, H, W) float volumes -> (B, D, H, W) float32 logits.

    D, H, W must each be divisible by 2**levels.
    """
    x = volume[..., None]  # NDHWC
    skips = []
    for p in params["enc"]:
        x = _block(x, p, compute_dtype)
        skips.append(x)
        x = _pool(x)
    x = _block(x, params["mid"], compute_dtype)
    for p, skip in zip(params["dec"], reversed(skips)):
        x = _upsample(x)
        x = jnp.concatenate([x, skip.astype(x.dtype)], axis=-1)
        x = _block(x, p, compute_dtype)
    logits8 = _conv(x, params["head"], compute_dtype)
    return logits8.sum(axis=-1).astype(jnp.float32)


def predict_mask3d(
    params: Params, volume: jax.Array, compute_dtype=jnp.bfloat16
) -> jax.Array:
    """uint8 3D mask matching the volumetric pipeline's output contract."""
    return (apply_unet3d(params, volume, compute_dtype) > 0).astype(jnp.uint8)


def distill_volume(volume: jax.Array, dims: jax.Array, cfg=None) -> jax.Array:
    """Teacher labels from the classical 3D pipeline for one (D, H, W) volume."""
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume

    cfg = cfg or PipelineConfig()
    return process_volume(volume, dims, cfg)["mask"]
