"""NM30x — import-contract enforcement (the jax-free/numpy-free registry).

Several modules declare, in prose, that importing them must never import a
backend: the resilience package (bench.py's orchestrator imports it while
holding the never-imports-jax invariant, docs/OPERATIONS.md), the obs
event/metric modules (stdlib-only by contract so telemetry is importable
from any process), ``ops.selection_network`` (the median planner is a
compile-time artifact consumed by jax-free processes), the serving queue
(unit-testable without a backend), and bench.py itself. Until this rule,
those contracts lived only in docstrings — one convenience import away from
silently charging a multi-second jax init (or a chip claim) to a process
that must never pay it.

The rule walks *module-level* imports only: a lazy ``import jax`` inside a
function is the sanctioned pattern (obs.spans, the CLI drivers) and is not
an import-time cost. ``if TYPE_CHECKING:`` blocks are exempt for the same
reason. Transitivity is enforced over project-internal edges: a contract
module importing a sibling that imports jax is the same violation one hop
later.

Rules:
  NM301  contract module (transitively) imports a banned package at
         import time
  NM302  registry drift: a registered module/package no longer exists in
         the scanned tree (the contract would silently stop being checked)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

# module-or-package-prefix -> banned top-level packages. A key matches
# itself and (for packages) every submodule under it.
CONTRACT_REGISTRY: Dict[str, Tuple[str, ...]] = {
    "nm03_capstone_project_tpu.resilience": ("jax", "numpy"),
    "nm03_capstone_project_tpu.obs": ("jax", "numpy"),
    # the trace/flight-recorder pair is pinned EXPLICITLY on top of the
    # obs package entry (ISSUE 7 / NM371 contract): a rename or move out
    # of obs/ must trip NM302 rather than silently shedding the contract —
    # these two must stay importable (and dump-capable) from wedged or
    # crashing processes that never paid a backend import
    "nm03_capstone_project_tpu.obs.trace": ("jax", "numpy"),
    "nm03_capstone_project_tpu.obs.flightrec": ("jax", "numpy"),
    "nm03_capstone_project_tpu.ops.selection_network": ("jax", "numpy"),
    "nm03_capstone_project_tpu.serving.queue": ("jax",),
    "nm03_capstone_project_tpu.serving.metrics": ("jax",),
    # the lane fault-domain state machine (ISSUE 8): unit-testable — and
    # its quarantine transitions flight-dumpable — without a backend.
    # jax-only like its queue/metrics siblings: the serving package
    # __init__ (an ancestor on every import path) legitimately imports
    # numpy for the batcher/server exports
    "nm03_capstone_project_tpu.serving.lanes": ("jax",),
    "nm03_capstone_project_tpu.utils.reporter": ("jax", "numpy"),
    # the streaming-ingest orchestration layer (ISSUE 11): ring,
    # pipeline and telemetry must be unit-testable backend-free — jax
    # enters only through the staging callables at call time (the
    # device_put sites in ingest/staging.py import jax lazily)
    "nm03_capstone_project_tpu.ingest": ("jax", "numpy"),
    # the replica-fleet front-end (ISSUE 13): routing, ejection/probation
    # and rolling-restart orchestration are pure stdlib byte-shuffling —
    # the router must start in milliseconds and never claim a chip, so
    # the whole package is jax- AND numpy-banned (it is not under the
    # serving package precisely so no numpy-importing ancestor __init__
    # weakens the contract the way serving.queue's does)
    "nm03_capstone_project_tpu.fleet": ("jax", "numpy"),
    # the content-addressed result tier (ISSUE 19): keys, the LRU store
    # and the in-flight coalescing index are pure hashing over bytes —
    # the router embeds a ResultStore in a process that must never pay a
    # jax import, so the package is jax- AND numpy-banned like fleet/
    # (the program-version key half crosses from compilehub over the wire)
    "nm03_capstone_project_tpu.cache": ("jax", "numpy"),
    # the linter itself runs in pre-backend CI processes; the gate gates
    # itself so a convenience import can never make the gate cost a backend
    "nm03_capstone_project_tpu.analysis": ("jax", "numpy"),
    # bench.py's orchestrator must never import jax (tunnel discipline:
    # holding a chip claim in the parent wedges every child measurement)
    "bench": ("jax",),
}

PROJECT_PREFIX = "nm03_capstone_project_tpu"


class _ImportEdge:
    __slots__ = ("target", "line", "source_line")

    def __init__(self, target: str, line: int, source_line: str):
        self.target = target
        self.line = line
        self.source_line = source_line


def _module_level_imports(src: SourceFile) -> List[_ImportEdge]:
    """Imports executed when the module is imported.

    Walks the top level plus import-time bodies (if/try at module scope,
    class bodies); skips function bodies and TYPE_CHECKING guards.
    """
    edges: List[_ImportEdge] = []
    if src.tree is None:
        return edges

    def is_type_checking(test: ast.expr) -> bool:
        return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or (
            isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING"
        )

    def relative_base(level: int) -> str:
        """The package a level-N relative import resolves against.

        For pkg/mod.py (module 'pkg.mod') level 1 is 'pkg' — strip one
        component; for pkg/__init__.py the module name 'pkg' already IS
        the package, so level 1 strips zero components (stripping one
        would resolve 'from .events import X' against pkg's PARENT and
        silently drop the edge from the contract graph).
        """
        strip = level - 1 if src.is_package else level
        name = src.module_name
        for _ in range(strip):
            name = name.rsplit(".", 1)[0] if "." in name else ""
        return name

    def walk(body: Iterable[ast.stmt]) -> None:
        for node in body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    edges.append(
                        _ImportEdge(alias.name, node.lineno, src.line_text(node.lineno))
                    )
            elif isinstance(node, ast.ImportFrom):
                if node.level and node.module is None:
                    # `from . import x` — resolve against the package
                    pkg = relative_base(node.level)
                    for alias in node.names:
                        edges.append(
                            _ImportEdge(
                                f"{pkg}.{alias.name}" if pkg else alias.name,
                                node.lineno,
                                src.line_text(node.lineno),
                            )
                        )
                elif node.module:
                    mod = node.module
                    if node.level:
                        base = relative_base(node.level)
                        mod = f"{base}.{mod}" if base else mod
                    edges.append(
                        _ImportEdge(mod, node.lineno, src.line_text(node.lineno))
                    )
            elif isinstance(node, ast.If):
                if not is_type_checking(node.test):
                    walk(node.body)
                walk(node.orelse)
            elif isinstance(node, (ast.Try, ast.With)):
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, ast.stmt):
                        walk([sub])
                if isinstance(node, ast.Try):
                    for h in node.handlers:
                        walk(h.body)
            elif isinstance(node, ast.ClassDef):
                walk(node.body)
            # FunctionDef / AsyncFunctionDef bodies are lazy: not walked
    walk(src.tree.body)
    return edges


def _registered_bans(module: str) -> Tuple[str, ...]:
    bans: Set[str] = set()
    for prefix, banned in CONTRACT_REGISTRY.items():
        if module == prefix or module.startswith(prefix + "."):
            bans.update(banned)
    return tuple(sorted(bans))


def check_import_contracts(files: Sequence[SourceFile]) -> List[Finding]:
    by_module: Dict[str, SourceFile] = {f.module_name: f for f in files}
    imports: Dict[str, List[_ImportEdge]] = {
        name: _module_level_imports(f) for name, f in by_module.items()
    }

    def resolve_internal(target: str) -> List[str]:
        """Project-internal modules a dotted import EXECUTES ([] if external).

        ``from pkg.mod import name`` may name either pkg.mod.name (a module)
        or an attribute of pkg.mod; importing either executes pkg.mod — and
        Python also executes every ancestor package ``__init__`` on the way
        down, so the whole chain joins the contract graph (a banned import
        hidden in an ancestor ``__init__`` is the same import-time cost).
        """
        hits: List[str] = []
        candidates = [target]
        if "." in target:
            candidates.append(target.rsplit(".", 1)[0])
        for cand in candidates:
            while cand:
                if cand in by_module and cand not in hits:
                    hits.append(cand)
                cand = cand.rsplit(".", 1)[0] if "." in cand else ""
        return hits

    findings: List[Finding] = []
    seen_keys: Set[Tuple[str, str, int]] = set()

    for prefix in CONTRACT_REGISTRY:
        if prefix not in by_module and not any(
            m == prefix or m.startswith(prefix + ".") for m in by_module
        ):
            # only report drift when the scan plausibly covers the tree the
            # registry describes (a fixture dir with its own modules should
            # not fail for missing THIS repo's files)
            if any(m.startswith(PROJECT_PREFIX) for m in by_module):
                anchor = next(iter(files), None)
                findings.append(
                    Finding(
                        rule="NM302",
                        path=anchor.relpath if anchor else "<registry>",
                        line=1,
                        message=(
                            f"import-contract registry names {prefix!r} but no "
                            "such module is in the scanned tree — update "
                            "analysis.contracts.CONTRACT_REGISTRY"
                        ),
                    )
                )

    for module, src in by_module.items():
        bans = _registered_bans(module)
        if not bans:
            continue
        # BFS over project-internal import-time edges from this module
        stack: List[Tuple[str, List[str]]] = [(module, [])]
        visited: Set[str] = set()
        while stack:
            cur, chain = stack.pop()
            if cur in visited:
                continue
            visited.add(cur)
            for edge in imports.get(cur, ()):
                top = edge.target.split(".")[0]
                if top in bans:
                    # report at the root module's matching import when the
                    # violation is direct; otherwise at the offending hop
                    where = by_module[cur]
                    via = " -> ".join(chain + [cur]) if chain else None
                    msg = (
                        f"{module} is declared {'/'.join(bans)}-free at import "
                        f"time but imports {edge.target!r}"
                    )
                    if via:
                        msg += f" (via {via})"
                    key = (module, edge.target, edge.line)
                    if key not in seen_keys:
                        seen_keys.add(key)
                        findings.append(
                            Finding(
                                rule="NM301",
                                path=where.relpath,
                                line=edge.line,
                                message=msg,
                                source_line=edge.source_line,
                            )
                        )
                    continue
                for internal in resolve_internal(edge.target):
                    if internal not in visited:
                        stack.append((internal, chain + [cur]))
    return findings
