"""NM361 — compile-home discipline: jit/pjit/shard_map live in compilehub.

The compile hub exists because the scattered alternative already failed in
this repo's history: ``parallel/`` referenced the promoted
``jax.shard_map`` while the installed jaxlib only shipped
``jax.experimental.shard_map``, and 8 tier-1 tests failed from the seed
until PR 6 hoisted the reference into one compat shim. A second scattered
call site is one upgrade away from the same AttributeError — and, more
quietly, from a compile cache the hub cannot see (warmup, AOT policy and
the ``/readyz`` executable accounting only cover what the hub builds).

The rule therefore flags any *reference* to jax's compilation entry
points outside ``nm03_capstone_project_tpu/compilehub/``:

* ``from jax... import jit/pjit/shard_map`` (any jax module) — the
  binding itself is the violation; suppressing it sanctions the uses;
* dotted references — ``jax.jit``, ``jax.experimental.pjit.pjit``, an
  aliased ``sm.shard_map`` where ``sm`` was imported from jax;

in decorators, ``functools.partial`` arguments and plain calls alike
(AST attribute/name references, so strings and docstrings never trip it).

Sanctioned escapes: the hub's own ``hub_jit``/``compat.shard_map``
(different names — no finding), and the Pallas kernel wrappers in
``ops/pallas_*.py``, which carry reasoned suppressions: their ``jax.jit``
is the kernel's dispatch envelope whose static_argnames pin the
pallas_call grid, not a pipeline compile the hub should own.

Rule:
  NM361  jit/pjit/shard_map referenced outside compilehub/
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

_FORBIDDEN = {"jit", "pjit", "shard_map"}
_HOME_PREFIX = "nm03_capstone_project_tpu/compilehub/"


def _dotted(node: ast.expr) -> Optional[str]:
    """'jax.experimental.pjit' for a Name/Attribute chain; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _jax_module_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local names bound to jax modules: {alias: real dotted module}.

    ``import jax`` -> {'jax': 'jax'}; ``import jax.experimental.shard_map
    as sm`` -> {'sm': ...}; ``from jax.experimental import shard_map`` ->
    {'shard_map': 'jax.experimental.shard_map'} (that one ALSO trips the
    import check itself — the alias map just catches attribute uses if
    the import line was suppressed but a dotted use appears elsewhere).
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "jax" or node.module.startswith("jax."):
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def check_compile_home(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        if src.tree is None or src.relpath.startswith(_HOME_PREFIX):
            continue
        aliases = _jax_module_aliases(src.tree)
        seen: Set[Tuple[int, str]] = set()

        def emit(line: int, what: str) -> None:
            if (line, what) in seen:
                return
            seen.add((line, what))
            findings.append(
                Finding(
                    rule="NM361",
                    path=src.relpath,
                    line=line,
                    message=(
                        f"{what} referenced outside compilehub/ — lowering "
                        "and compilation belong to the compile hub (use "
                        "compilehub.hub_jit / compilehub.shard_map, or a "
                        "hub program); Pallas kernel wrappers suppress "
                        "with a reason (docs/STATIC_ANALYSIS.md)"
                    ),
                    source_line=src.line_text(line),
                )
            )

        for node in ast.walk(src.tree):
            # the binding: from jax[...] import jit/pjit/shard_map
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax" or node.module.startswith("jax.")
            ):
                for a in node.names:
                    if a.name in _FORBIDDEN:
                        emit(node.lineno, f"{node.module}.{a.name}")
            # the reference: <jax-ish>.jit / .pjit / .shard_map
            elif isinstance(node, ast.Attribute) and node.attr in _FORBIDDEN:
                base = _dotted(node.value)
                if base is None:
                    continue
                head = base.split(".")[0]
                resolved = aliases.get(head)
                if resolved is not None:
                    base = base.replace(head, resolved, 1)
                if base == "jax" or base.startswith("jax."):
                    emit(node.lineno, f"{base}.{node.attr}")
    return findings
