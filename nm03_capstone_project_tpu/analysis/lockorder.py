"""NM42x — static lock-order/deadlock analysis for the threaded serving stack.

NM331 makes *unguarded writes* checkable; nothing checked lock **ordering**.
The serving tier holds locks across long device dispatches by design (the
gang lane parks the batcher for an entire mesh program — the
OpenCLIPER-style amortization argument), which makes acquisition order the
one invariant that keeps the whole thread topology — handler threads,
batcher, gang lane, health poller, drain threads — deadlock-free. One
inverted pair between any two of the 40+ Lock/RLock/Condition sites and a
replica wedges silently: alive process, no answers.

The analysis builds a **may-hold graph**: every ``with self._lock:`` /
bare ``acquire()`` is an acquisition; while one is held, every further
acquisition reachable through same-tree calls (methods on annotated
attributes, module functions through their imports, ``@contextmanager``
helpers like the gang's ``gang_parked``) adds a directed edge
``held -> acquired``. Cross-thread boundaries (``pool.submit``,
``Thread(target=...)``) deliberately do NOT propagate the held set — the
callee runs on another thread with an empty stack.

Rules:
  NM421  lock-order cycle: two call paths acquire the same pair of locks in
         opposite order (or a non-reentrant lock may be re-acquired while
         held) — the static deadlock;
  NM422  blocking call while holding a lock: device dispatch, HTTP/socket
         I/O, ``time.sleep``, ``subprocess``, unbounded ``.result()`` /
         ``.join()`` / ``.wait()``, blocking ``Queue.get/put`` — outside
         sanctioned homes (the gang's park-the-batcher hold is the
         canonical reasoned suppression);
  NM423  a bare ``acquire()`` whose ``release()`` is not in a
         ``try/finally`` in the same function.

The runtime twin is :mod:`nm03_capstone_project_tpu.utils.lockdep`: an
instrumented-lock wrapper that records the *observed* acquisition graph and
dumps ``lockdep_witness.json``; :func:`explain_witness` is the gate
``scripts/check_static.py --lockdep-witness`` runs — zero observed cycles
or inversions, and every observed edge either present in this module's
static graph or targeting an ``obs/`` leaf lock (telemetry locks are
verified leaves: they never acquire outward, so they cannot participate in
a cycle).

jax-free and numpy-free like the rest of analysis/ (the gate gates itself).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

PKG = "nm03_capstone_project_tpu"
# telemetry locks are sanctioned leaves: counter bumps under a data lock
# are by design (cheap, bounded) and the leaf property — verified below —
# means they can never close a cycle
LEAF_PREFIX = f"{PKG}/obs/"

_FACTORY_KINDS = {"Lock", "RLock", "Condition"}

# (class, method) pairs that ARE a device dispatch: holding any lock across
# them serializes the fleet behind one mesh program
_DISPATCH_METHODS = {
    "WarmExecutor": {"run_batch"},
    "DispatchSupervisor": {"run"},
}

# attribute calls that block on the network regardless of receiver type
_NET_ATTRS = {"urlopen", "getresponse", "create_connection"}

_MAX_DEPTH = 10


def _lockish(name: str) -> bool:
    low = name.lower()
    return "lock" in low or "cond" in low


def _is_property(fn) -> bool:
    """True for ``@property``/``@cached_property`` getters (not setters)."""
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Name) and dec.id in ("property", "cached_property"):
            return True
        if isinstance(dec, ast.Attribute) and dec.attr == "cached_property":
            return True
    return False


# -- graph -------------------------------------------------------------------


class LockNode:
    """One lock identity: a creation site (class attr / module var / local)."""

    __slots__ = ("key", "path", "line", "kind")

    def __init__(self, key: str, path: str, line: int, kind: str):
        self.key = key
        self.path = path
        self.line = line
        self.kind = kind

    @property
    def is_rlock(self) -> bool:
        return self.kind == "RLock"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"LockNode({self.key}, {self.kind})"


class LockGraph:
    """The static may-hold graph over every lock creation site in the tree.

    ``edges[(a, b)]`` holds the acquisition sites ``(path, line)`` where
    ``b`` may be acquired while ``a`` is held. ``by_site`` maps a creation
    site ``(path, line)`` — exactly what the runtime witness records — back
    to its node, including Condition-alias lines.
    """

    def __init__(self) -> None:
        self.nodes: Dict[str, LockNode] = {}
        self.by_site: Dict[Tuple[str, int], LockNode] = {}
        self.edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
        self.leaf_violations: List[str] = []

    @property
    def leaf_ok(self) -> bool:
        """True when no obs/ lock ever acquires a non-obs lock — the
        property that makes 'target is an obs/ leaf' a valid witness-edge
        explanation."""
        return not self.leaf_violations

    def add_edge(self, src: LockNode, dst: LockNode, site: Tuple[str, int]) -> None:
        sites = self.edges.setdefault((src.key, dst.key), [])
        if site not in sites:
            sites.append(site)
        if src.path.startswith(LEAF_PREFIX) and not dst.path.startswith(LEAF_PREFIX):
            self.leaf_violations.append(
                f"obs/ lock {src.key} acquires non-leaf {dst.key} at "
                f"{site[0]}:{site[1]}"
            )


# -- tree indexing -----------------------------------------------------------


class _Class:
    def __init__(self, mod: "_Module", node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.lock_attrs: Dict[str, LockNode] = {}
        self.attr_types: Dict[str, str] = {}
        self.contextmanagers: Set[str] = set()
        for st in node.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[st.name] = st
                for dec in st.decorator_list:
                    dn = dec.id if isinstance(dec, ast.Name) else (
                        dec.attr if isinstance(dec, ast.Attribute) else None
                    )
                    if dn == "contextmanager":
                        self.contextmanagers.add(st.name)


class _Module:
    def __init__(self, src: SourceFile):
        self.src = src
        self.path = src.relpath
        self.name = src.module_name
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.classes: Dict[str, _Class] = {}
        self.module_locks: Dict[str, LockNode] = {}
        tree = src.tree
        if tree is None:
            return
        pkg_parts = self.name.split(".")
        if not src.is_package:
            pkg_parts = pkg_parts[:-1]
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name if alias.asname else alias.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    mod_dots = ".".join(base + ([node.module] if node.module else []))
                else:
                    mod_dots = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.imports[alias.asname or alias.name] = (
                        f"{mod_dots}.{alias.name}" if mod_dots else alias.name
                    )
        for st in tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[st.name] = st
            elif isinstance(st, ast.ClassDef):
                self.classes[st.name] = _Class(self, st)

    def is_factory(self, call: ast.Call) -> Optional[str]:
        """'Lock'/'RLock'/'Condition' when ``call`` creates a sync object."""
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "threading"
            and f.attr in _FACTORY_KINDS
        ):
            return f.attr
        if isinstance(f, ast.Name) and self.imports.get(f.id) == f"threading.{f.id}":
            if f.id in _FACTORY_KINDS:
                return f.id
        return None


def _ann_name(node: Optional[ast.expr]) -> Optional[str]:
    """Terminal class name of an annotation, unwrapping Optional/List/etc."""
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        head = node.value.strip().split("[")[0].split(".")[-1]
        return head or None
    if isinstance(node, ast.Subscript):
        outer = _ann_name(node.value)
        if outer in ("Optional", "List", "Sequence", "Tuple", "Dict", "Type"):
            inner = node.slice
            if isinstance(inner, ast.Tuple) and inner.elts:
                inner = inner.elts[-1] if outer == "Dict" else inner.elts[0]
            return _ann_name(inner)
        return outer
    return None


class _Index:
    """Cross-file resolution: modules by dotted name, classes by name, every
    function (at any nesting) with its enclosing class and local locks."""

    def __init__(self, files: Sequence[SourceFile]):
        self.graph = LockGraph()
        self.modules: Dict[str, _Module] = {}
        self.class_by_name: Dict[str, _Class] = {}
        self.roots: List[Tuple[_Module, Optional[_Class], ast.FunctionDef, str]] = []
        self.fn_local_locks: Dict[int, Dict[str, LockNode]] = {}
        self.fn_class: Dict[int, Optional[_Class]] = {}
        self.by_path: Dict[str, SourceFile] = {}
        for src in files:
            if src.tree is None or not src.relpath.endswith(".py"):
                continue
            mod = _Module(src)
            self.modules[mod.name] = mod
            self.by_path[src.relpath] = src
            for cname, cls in mod.classes.items():
                self.class_by_name.setdefault(cname, cls)
        for mod in self.modules.values():
            self._collect(mod)

    # -- lock registry --------------------------------------------------

    def _register(self, mod: _Module, key: str, call: ast.Call, kind: str) -> LockNode:
        node = self.graph.nodes.get(key)
        if node is None:
            node = LockNode(key, mod.path, call.lineno, kind)
            self.graph.nodes[key] = node
        self.graph.by_site.setdefault((mod.path, call.lineno), node)
        return node

    def _collect(self, mod: _Module) -> None:
        registered: Set[int] = set()

        def handle_assign(st: ast.stmt, cls: Optional[_Class], qual: str,
                          locals_map: Dict[str, LockNode], in_init: bool) -> None:
            if isinstance(st, ast.AnnAssign):
                targets, value = [st.target], st.value
            elif isinstance(st, ast.Assign):
                targets, value = st.targets, st.value
            else:
                return
            if not isinstance(value, ast.Call):
                # Condition alias of an alias / plain rebinds: ignore
                return
            kind = mod.is_factory(value)
            if kind is None:
                return
            tgt = targets[0]
            node: Optional[LockNode] = None
            if kind == "Condition" and value.args:
                arg = value.args[0]
                aliased: Optional[LockNode] = None
                if (
                    isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"
                    and cls is not None
                ):
                    aliased = cls.lock_attrs.get(arg.attr)
                elif isinstance(arg, ast.Name):
                    aliased = locals_map.get(arg.id) or mod.module_locks.get(arg.id)
                if aliased is not None:
                    # the Condition IS the lock: same node, extra site/name
                    node = aliased
                    self.graph.by_site.setdefault((mod.path, value.lineno), node)
            if node is None:
                if (
                    in_init
                    and cls is not None
                    and isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    node = self._register(
                        mod, f"{mod.path}:{cls.name}.{tgt.attr}", value, kind
                    )
                elif isinstance(tgt, ast.Name) and not qual:
                    node = self._register(mod, f"{mod.path}:{tgt.id}", value, kind)
                elif isinstance(tgt, ast.Name):
                    node = self._register(
                        mod, f"{mod.path}:{qual}.{tgt.id}", value, kind
                    )
                else:
                    node = self._register(
                        mod, f"{mod.path}:{value.lineno}", value, kind
                    )
            registered.add(value.lineno)
            if (
                in_init
                and cls is not None
                and isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                cls.lock_attrs[tgt.attr] = node
            elif isinstance(tgt, ast.Name):
                if qual:
                    locals_map[tgt.id] = node
                else:
                    mod.module_locks[tgt.id] = node

        def visit(stmts: Iterable[ast.stmt], cls: Optional[_Class], qual: str,
                  locals_map: Dict[str, LockNode], in_init: bool) -> None:
            for st in stmts:
                if isinstance(st, ast.ClassDef):
                    c = mod.classes.get(st.name) if not qual else _Class(mod, st)
                    visit(st.body, c, "", {}, False)
                    continue
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fq = f"{qual}.{st.name}" if qual else (
                        f"{cls.name}.{st.name}" if cls else st.name
                    )
                    fl: Dict[str, LockNode] = {}
                    self.fn_local_locks[id(st)] = fl
                    self.fn_class[id(st)] = cls
                    self.roots.append((mod, cls, st, fq))
                    visit(
                        st.body, cls, fq, fl,
                        in_init=(cls is not None and st.name == "__init__"),
                    )
                    continue
                handle_assign(st, cls, qual, locals_map, in_init)
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.stmt):
                        visit([child], cls, qual, locals_map, in_init)
        if mod.src.tree is not None:
            visit(mod.src.tree.body, None, "", {}, False)
            # attr types AFTER lock registry (annotated __init__ params etc.)
            for cls in mod.classes.values():
                self._class_attr_types(mod, cls)
            # mop-up: factory calls not in a simple assignment still need a
            # node — the runtime witness maps every package creation site
            for node in ast.walk(mod.src.tree):
                if isinstance(node, ast.Call) and node.lineno not in registered:
                    kind = mod.is_factory(node)
                    if kind is not None:
                        self._register(mod, f"{mod.path}:{node.lineno}", node, kind)

    def _class_attr_types(self, mod: _Module, cls: _Class) -> None:
        init = cls.methods.get("__init__")
        if init is None:
            return
        param_types: Dict[str, str] = {}
        for arg in list(init.args.args) + list(init.args.kwonlyargs):
            t = _ann_name(arg.annotation)
            if t:
                param_types[arg.arg] = t
        for st in ast.walk(init):
            tgt = None
            value = None
            ann = None
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                tgt, value = st.targets[0], st.value
            elif isinstance(st, ast.AnnAssign):
                tgt, value, ann = st.target, st.value, st.annotation
            if (
                tgt is None
                or not isinstance(tgt, ast.Attribute)
                or not isinstance(tgt.value, ast.Name)
                or tgt.value.id != "self"
            ):
                continue
            t = _ann_name(ann) if ann is not None else None
            if t is None and isinstance(value, ast.Name):
                t = param_types.get(value.id)
            if t is None and isinstance(value, ast.Call):
                t = self._ctor_name(mod, value)
            if t is None and isinstance(value, ast.BoolOp):
                for v in value.values:
                    if isinstance(v, ast.Name) and v.id in param_types:
                        t = param_types[v.id]
                        break
                    if isinstance(v, ast.Call):
                        t = self._ctor_name(mod, v)
                        if t:
                            break
            if t:
                cls.attr_types.setdefault(tgt.attr, t)

    def _ctor_name(self, mod: _Module, call: ast.Call) -> Optional[str]:
        f = call.func
        name = None
        if isinstance(f, ast.Name):
            name = f.id
        elif isinstance(f, ast.Attribute):
            name = f.attr
        if name and name in self.class_by_name:
            return name
        if isinstance(f, ast.Name):
            dotted = mod.imports.get(f.id)
            if dotted and dotted.split(".")[-1] in self.class_by_name:
                return dotted.split(".")[-1]
        # method call with a return annotation (factory methods)
        if isinstance(f, ast.Attribute):
            target = None
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                pass  # resolved at simulation time with a class context
            if target is None:
                for cls in self.class_by_name.values():
                    m = cls.methods.get(f.attr)
                    if m is not None and _ann_name(m.returns):
                        # ambiguous across classes; only accept unique names
                        candidates = {
                            _ann_name(c.methods[f.attr].returns)
                            for c in self.class_by_name.values()
                            if f.attr in c.methods
                        }
                        if len(candidates) == 1:
                            return candidates.pop()
                        break
        return None

    def resolve_dotted(self, dotted: str):
        """('fn', mod, cls, fndef) | ('module', mod) | ('class', cls) | None."""
        for _ in range(3):
            mod = self.modules.get(dotted)
            if mod is not None:
                return ("module", mod)
            head, _, tail = dotted.rpartition(".")
            if not head:
                return None
            parent = self.modules.get(head)
            if parent is None:
                return None
            if tail in parent.functions:
                return ("fn", parent, None, parent.functions[tail])
            if tail in parent.classes:
                return ("class", parent.classes[tail])
            re_export = parent.imports.get(tail)
            if re_export is None:
                return None
            dotted = re_export
        return None


# -- simulation --------------------------------------------------------------


class _Ctx:
    __slots__ = ("mod", "cls", "fn", "locals_types", "report", "depth")

    def __init__(self, mod, cls, fn, report, depth):
        self.mod = mod
        self.cls = cls
        self.fn = fn
        self.report = report
        self.depth = depth
        self.locals_types: Dict[str, str] = {}
        for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
            t = _ann_name(arg.annotation)
            if t:
                self.locals_types[arg.arg] = t


class _Sim:
    def __init__(self, index: _Index):
        self.index = index
        self.graph = index.graph
        self.findings: List[Finding] = []
        self._reported: Set[Tuple[str, str, int]] = set()
        self._visited: Set[Tuple[int, Tuple[str, ...], bool]] = set()
        self._cm_memo: Dict[int, List[LockNode]] = {}

    # -- entry points ---------------------------------------------------

    def run_all_roots(self) -> None:
        for mod, cls, fn, _qual in self.index.roots:
            self.visit_fn(mod, cls, fn, held=[], report=True, depth=0)

    def visit_fn(self, mod, cls, fn, held: List[LockNode], report: bool,
                 depth: int) -> None:
        if depth > _MAX_DEPTH:
            return
        key = (id(fn), tuple(sorted({h.key for h in held})), report)
        if key in self._visited:
            return
        self._visited.add(key)
        ctx = _Ctx(mod, cls, fn, report, depth)
        extra: List[LockNode] = []  # bare-acquire stack, popped at exit
        self._walk_body(ctx, fn.body, held, extra)
        for _ in extra:
            held.pop()

    # -- statements -----------------------------------------------------

    def _walk_body(self, ctx, stmts, held, extra) -> None:
        for st in stmts:
            self._walk_stmt(ctx, st, held, extra)

    def _walk_stmt(self, ctx, st, held, extra) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs do not execute here (closure != call)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired: List[LockNode] = []
            for item in st.items:
                self._walk_expr(ctx, item.context_expr, held)
                for node in self._with_locks(ctx, item.context_expr):
                    self._record_acquire(ctx, node, item.context_expr.lineno, held)
                    held.append(node)
                    acquired.append(node)
            self._walk_body(ctx, st.body, held, extra)
            for _ in acquired:
                held.pop()
            return
        if isinstance(st, ast.Try):
            self._walk_body(ctx, st.body, held, extra)
            for h in st.handlers:
                self._walk_body(ctx, h.body, held, extra)
            self._walk_body(ctx, st.orelse, held, extra)
            self._walk_body(ctx, st.finalbody, held, extra)
            return
        if isinstance(st, ast.Assign):
            self._walk_expr(ctx, st.value, held)
            self._infer_assign(ctx, st)
            # bare acquire/release tracked through _walk_expr; nothing else
            return
        if isinstance(st, (ast.Expr, ast.Return, ast.Raise, ast.Assert,
                           ast.AnnAssign, ast.AugAssign, ast.Delete)):
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._walk_expr(ctx, child, held, extra)
            return
        # control flow: tests/iters are expressions, bodies are statements
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._walk_expr(ctx, child, held, extra)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(ctx, child, held, extra)

    def _infer_assign(self, ctx, st: ast.Assign) -> None:
        if len(st.targets) != 1 or not isinstance(st.targets[0], ast.Name):
            return
        t = self._expr_type(ctx, st.value)
        if t:
            ctx.locals_types[st.targets[0].id] = t

    # -- expressions ----------------------------------------------------

    def _walk_expr(self, ctx, expr, held, extra=None) -> None:
        # all calls in the expression, same-execution only (no lambdas)
        stack = [expr]
        calls: List[ast.Call] = []
        attrs: List[ast.Attribute] = []
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                calls.append(node)
            elif isinstance(node, ast.Attribute):
                attrs.append(node)
            stack.extend(ast.iter_child_nodes(node))
        for call in sorted(calls, key=lambda c: (c.lineno, c.col_offset)):
            self._handle_call(ctx, call, held, extra)
        if held:
            # @property getters execute on attribute ACCESS — the
            # lane_count-under-the-pool-lock edge is invisible to a
            # calls-only walk (the runtime witness caught exactly that)
            call_funcs = {id(c.func) for c in calls}
            for a in sorted(attrs, key=lambda a: (a.lineno, a.col_offset)):
                if id(a) not in call_funcs:
                    self._handle_property(ctx, a, held)

    def _handle_property(self, ctx, attr: ast.Attribute, held) -> None:
        bt = self._expr_type(ctx, attr.value)
        if not bt:
            return
        cls = self.index.class_by_name.get(bt)
        if cls is None:
            return
        fn = cls.methods.get(attr.attr)
        if fn is None or not _is_property(fn):
            return
        self.visit_fn(cls.mod, cls, fn, held, report=ctx.report,
                      depth=ctx.depth + 1)

    def _handle_call(self, ctx, call: ast.Call, held, extra) -> None:
        func = call.func
        # bare acquire/release on a lock-like receiver
        if isinstance(func, ast.Attribute) and func.attr in ("acquire", "release"):
            node = self._resolve_lock_expr(ctx, func.value)
            if node is None and _terminal_name(func.value) and _lockish(
                _terminal_name(func.value)
            ):
                node = None  # lockish but unresolved: NM423 still covers it
            if node is not None:
                if func.attr == "acquire":
                    self._record_acquire(ctx, node, call.lineno, held)
                    held.append(node)
                    if extra is not None:
                        extra.append(node)
                else:
                    for i in range(len(held) - 1, -1, -1):
                        if held[i].key == node.key:
                            held.pop(i)
                            if extra is not None and node in extra:
                                extra.remove(node)
                            break
                return
        blocking = self._blocking_reason(ctx, call)
        if blocking is not None:
            if held and ctx.report:
                self._emit_nm422(ctx, call, blocking, held)
            target = self._resolve_call(ctx, call)
            if target is not None and held:
                # keep walking for graph completeness, but the finding at
                # THIS site already covers everything the callee blocks on
                self.visit_fn(target[1], target[2], target[3], held,
                              report=False, depth=ctx.depth + 1)
            return
        if not held:
            return  # the callee is simulated as its own root anyway
        target = self._resolve_call(ctx, call)
        if target is not None:
            self.visit_fn(target[1], target[2], target[3], held,
                          report=ctx.report, depth=ctx.depth + 1)

    # -- lock resolution ------------------------------------------------

    def _resolve_lock_expr(self, ctx, expr) -> Optional[LockNode]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and ctx.cls is not None
        ):
            return ctx.cls.lock_attrs.get(expr.attr)
        if isinstance(expr, ast.Name):
            local = self.index.fn_local_locks.get(id(ctx.fn), {})
            node = local.get(expr.id)
            if node is not None:
                return node
            return ctx.mod.module_locks.get(expr.id)
        return None

    def _with_locks(self, ctx, expr) -> List[LockNode]:
        node = self._resolve_lock_expr(ctx, expr)
        if node is not None:
            return [node]
        if isinstance(expr, ast.Call):
            target = self._resolve_call(ctx, expr)
            if target is not None:
                _, mod, cls, fn = target
                if cls is not None and fn.name in cls.contextmanagers:
                    return self._cm_yield_locks(mod, cls, fn)
                if cls is None:
                    # module-level @contextmanager helpers
                    for dec in fn.decorator_list:
                        dn = dec.id if isinstance(dec, ast.Name) else (
                            dec.attr if isinstance(dec, ast.Attribute) else None
                        )
                        if dn == "contextmanager":
                            return self._cm_yield_locks(mod, cls, fn)
        return []

    def _cm_yield_locks(self, mod, cls, fn) -> List[LockNode]:
        """Locks held at the (first) ``yield`` of a @contextmanager — those
        stay held for the caller's entire with-body (gang_parked)."""
        memo = self._cm_memo.get(id(fn))
        if memo is not None:
            return memo
        ctx = _Ctx(mod, cls, fn, report=False, depth=_MAX_DEPTH)
        out: List[LockNode] = []

        def find(stmts, stack: List[LockNode]) -> bool:
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    acquired = []
                    for item in st.items:
                        for node in self._with_locks(ctx, item.context_expr):
                            stack.append(node)
                            acquired.append(node)
                    hit = find(st.body, stack)
                    for _ in acquired:
                        stack.pop()
                    if hit:
                        return True
                    continue
                for sub in ast.walk(st):
                    if isinstance(sub, (ast.Yield, ast.YieldFrom)):
                        out.extend(stack)
                        return True
                if isinstance(st, (ast.Try, ast.If, ast.For, ast.While)):
                    pass  # ast.walk above already searched the subtree
            return False

        find(fn.body, [])
        self._cm_memo[id(fn)] = out
        return out

    # -- call resolution ------------------------------------------------

    def _expr_type(self, ctx, expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id == "self" and ctx.cls is not None:
                return ctx.cls.name
            return ctx.locals_types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            bt = self._expr_type(ctx, expr.value)
            if bt:
                cls = self.index.class_by_name.get(bt)
                if cls is not None:
                    return cls.attr_types.get(expr.attr)
            return None
        if isinstance(expr, ast.Subscript):
            return self._expr_type(ctx, expr.value)
        if isinstance(expr, ast.Call):
            target = self._resolve_call(ctx, expr)
            if target is None:
                return None
            _, _mod, tcls, fn = target
            if fn.name == "__init__" and tcls is not None:
                return tcls.name
            return _ann_name(fn.returns)
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                t = self._expr_type(ctx, v)
                if t:
                    return t
        return None

    def _resolve_call(self, ctx, call: ast.Call):
        """('fn', mod, cls_or_None, fndef) for same-tree callables."""
        func = call.func
        if isinstance(func, ast.Name):
            fn = ctx.mod.functions.get(func.id)
            if fn is not None:
                return ("fn", ctx.mod, None, fn)
            cls = ctx.mod.classes.get(func.id)
            if cls is None:
                dotted = ctx.mod.imports.get(func.id)
                if dotted:
                    resolved = self.index.resolve_dotted(dotted)
                    if resolved is None:
                        return None
                    if resolved[0] == "fn":
                        return resolved
                    if resolved[0] == "class":
                        cls = resolved[1]
            if cls is not None:
                init = cls.methods.get("__init__")
                if init is not None:
                    return ("fn", cls.mod, cls, init)
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base, mname = func.value, func.attr
        # module.function()
        if isinstance(base, ast.Name):
            dotted = ctx.mod.imports.get(base.id)
            if dotted:
                resolved = self.index.resolve_dotted(f"{dotted}.{mname}")
                if resolved is not None and resolved[0] == "fn":
                    return resolved
                if resolved is not None and resolved[0] == "class":
                    cls = resolved[1]
                    init = cls.methods.get("__init__")
                    if init is not None:
                        return ("fn", cls.mod, cls, init)
        bt = self._expr_type(ctx, base)
        if bt:
            cls = self.index.class_by_name.get(bt)
            if cls is not None:
                m = cls.methods.get(mname)
                if m is not None:
                    return ("fn", cls.mod, cls, m)
        return None

    # -- blocking table --------------------------------------------------

    def _blocking_reason(self, ctx, call: ast.Call) -> Optional[str]:
        func = call.func
        noargs = not call.args and not call.keywords
        if isinstance(func, ast.Name):
            if func.id == "sleep" and ctx.mod.imports.get("sleep") == "time.sleep":
                return "time.sleep()"
            if func.id == "urlopen":
                return "urlopen() network I/O"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base, m = func.value, func.attr
        if isinstance(base, ast.Name):
            if base.id == "time" and m == "sleep":
                return "time.sleep()"
            if base.id == "subprocess":
                return f"subprocess.{m}()"
        if m in _NET_ATTRS:
            return f".{m}() network I/O"
        if m == "result" and noargs:
            return ".result() with no timeout"
        if m == "join" and noargs:
            return ".join() with no timeout"
        if m == "wait" and noargs:
            return ".wait() with no timeout"
        if m in ("get", "put"):
            bt = self._expr_type(ctx, base)
            if bt in ("Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"):
                for kw in call.keywords:
                    if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                            and kw.value.value is False:
                        return None
                    if kw.arg == "timeout":
                        return None
                return f"blocking Queue.{m}()"
            return None
        bt = self._expr_type(ctx, base)
        if bt and m in _DISPATCH_METHODS.get(bt, ()):
            return f"device dispatch {bt}.{m}()"
        return None

    # -- recording -------------------------------------------------------

    def _record_acquire(self, ctx, node: LockNode, line: int, held) -> None:
        site = (ctx.mod.path, line)
        for h in held:
            if h.key == node.key:
                if node.is_rlock:
                    continue  # reentrant by construction
                self._emit(
                    "NM421", ctx, line,
                    f"non-reentrant lock {node.key} may be re-acquired while "
                    "already held (self-deadlock); use an RLock or drop the "
                    "nested acquisition",
                )
                continue
            self.graph.add_edge(h, node, site)

    def _emit_nm422(self, ctx, call: ast.Call, desc: str, held) -> None:
        inner = held[-1]
        more = f" (+{len(held) - 1} more)" if len(held) > 1 else ""
        self._emit(
            "NM422", ctx, call.lineno,
            f"{desc} while holding {inner.key}{more} — blocking under a lock "
            "stalls every thread behind it; move it outside the critical "
            "section (or suppress with the reason the hold is by design)",
        )

    def _emit(self, rule: str, ctx, line: int, message: str) -> None:
        key = (rule, ctx.mod.path, line)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(
            Finding(
                rule=rule,
                path=ctx.mod.path,
                line=line,
                message=message,
                source_line=ctx.mod.src.line_text(line),
            )
        )


def _terminal_name(expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


# -- NM421: cycles over the finished graph ------------------------------------


def _find_cycle(adj: Dict[str, Set[str]]) -> Optional[List[str]]:
    """One directed cycle (as a node path, first node repeated last)."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    path: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        path.append(n)
        for nxt in sorted(adj.get(n, ())):
            if color.get(nxt, WHITE) == GRAY:
                i = path.index(nxt)
                return path[i:] + [nxt]
            if color.get(nxt, WHITE) == WHITE:
                found = dfs(nxt)
                if found:
                    return found
        path.pop()
        color[n] = BLACK
        return None

    for n in sorted(adj):
        if color[n] == WHITE:
            found = dfs(n)
            if found:
                return found
    return None


def _cycle_findings(index: _Index) -> List[Finding]:
    graph = index.graph
    adj: Dict[str, Set[str]] = {}
    for (a, b) in graph.edges:
        if a != b:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
    out: List[Finding] = []
    seen: Set[frozenset] = set()
    while True:
        cycle = _find_cycle(adj)
        if cycle is None:
            break
        nodes = cycle[:-1]
        key = frozenset(nodes)
        # break the cycle so the search can surface any OTHER cycle
        adj[nodes[-1]].discard(cycle[-1] if len(nodes) == 1 else nodes[0])
        if key in seen:
            continue
        seen.add(key)
        legs = []
        sites: List[Tuple[str, int]] = []
        for a, b in zip(cycle, cycle[1:]):
            at = graph.edges.get((a, b), [("?", 0)])[0]
            legs.append(f"{a} -> {b} (at {at[0]}:{at[1]})")
            sites.append(at)
        real = [s for s in sites if s[1]]
        anchor = min(real) if real else (legs and sites[0]) or ("?", 1)
        src = index.by_path.get(anchor[0])
        out.append(
            Finding(
                rule="NM421",
                path=anchor[0],
                line=anchor[1],
                message=(
                    "lock-order cycle — two paths acquire the same locks in "
                    "opposite order: " + "; ".join(legs)
                ),
                source_line=src.line_text(anchor[1]) if src else "",
            )
        )
    return out


# -- NM423: unbalanced bare acquire -------------------------------------------


def _balance_findings(files: Sequence[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            acquires: List[Tuple[ast.Call, str]] = []
            released: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node is not fn:
                    continue
                if isinstance(node, ast.Try):
                    for f_st in node.finalbody:
                        for sub in ast.walk(f_st):
                            if (
                                isinstance(sub, ast.Call)
                                and isinstance(sub.func, ast.Attribute)
                                and sub.func.attr == "release"
                            ):
                                released.add(ast.dump(sub.func.value))
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    recv = _terminal_name(node.func.value)
                    if recv and _lockish(recv):
                        acquires.append((node, ast.dump(node.func.value)))
            for call, dump in acquires:
                if dump in released:
                    continue
                out.append(
                    Finding(
                        rule="NM423",
                        path=src.relpath,
                        line=call.lineno,
                        message=(
                            "bare acquire() without a release() in a "
                            "try/finally in the same function — an exception "
                            "between them wedges every later acquirer; use "
                            "'with' or a try/finally"
                        ),
                        source_line=src.line_text(call.lineno),
                    )
                )
    return out


# -- public API ---------------------------------------------------------------


def build_lock_graph(files: Sequence[SourceFile]) -> LockGraph:
    """The static may-hold graph alone (the witness gate's reference)."""
    index = _Index(files)
    sim = _Sim(index)
    sim.run_all_roots()
    return index.graph


def check_lock_order(files: Sequence[SourceFile]) -> List[Finding]:
    """NM421 + NM422 + NM423 over the whole file set."""
    files = [f for f in files if f.tree is not None]
    index = _Index(files)
    sim = _Sim(index)
    sim.run_all_roots()
    findings = list(sim.findings)
    findings.extend(_cycle_findings(index))
    findings.extend(_balance_findings(files))
    return findings


# -- the witness gate ---------------------------------------------------------


def explain_witness(witness: dict, graph: LockGraph) -> List[str]:
    """Problems that fail ``check_static --lockdep-witness`` (empty = pass).

    A witness passes when it has zero recorded inversions, its observed
    acquisition-order graph is acyclic, every package lock site it saw is
    in the static registry, and every observed edge is *explained*: present
    in the static may-hold graph, or targeting an ``obs/`` leaf lock while
    the leaf discipline holds statically (obs/ locks never acquire outward,
    so a leaf edge cannot close a cycle).
    """
    problems: List[str] = []
    sitemap: Dict[str, Optional[str]] = {}
    for s in witness.get("sites", []):
        sid = s.get("id", f"{s.get('path')}:{s.get('line')}")
        node = graph.by_site.get((s.get("path"), int(s.get("line", 0))))
        if node is not None:
            sitemap[sid] = node.key
        elif str(s.get("path", "")).startswith(f"{PKG}/"):
            sitemap[sid] = None
            problems.append(
                f"witness lock site {s.get('path')}:{s.get('line')} is not in "
                "the static lock registry (analysis/lockorder.py cannot see "
                "this creation site — fix the registry, not the witness)"
            )
        else:
            sitemap[sid] = sid  # non-package site (fixtures): identity-mapped
    for inv in witness.get("inversions", []):
        problems.append(
            "observed lock-order inversion: "
            f"{inv.get('first')} -> {inv.get('second')} after the opposite "
            f"order was seen; stacks: {inv.get('stack')} vs "
            f"{inv.get('prior_stack')}"
        )
    adj: Dict[str, Set[str]] = {}
    observed: List[Tuple[str, str, dict]] = []
    for e in witness.get("edges", []):
        a = sitemap.get(e.get("src"))
        b = sitemap.get(e.get("dst"))
        if a is None or b is None:
            continue  # unregistered package site: already a problem above
        observed.append((a, b, e))
        if a != b:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
    cycle = _find_cycle(adj)
    if cycle is not None:
        problems.append(
            "observed acquisition-order graph has a cycle: "
            + " -> ".join(cycle)
        )
    static_edges = set(graph.edges)
    for a, b, e in observed:
        if (a, b) in static_edges:
            continue
        na, nb = graph.nodes.get(a), graph.nodes.get(b)
        if na is None or nb is None:
            continue  # fixture locks have no static story to check
        if (
            nb.path.startswith(LEAF_PREFIX)
            and not na.path.startswith(LEAF_PREFIX)
            and graph.leaf_ok
        ):
            continue
        problems.append(
            f"observed edge {a} -> {b} (count {e.get('count', 1)}) is not "
            "explained by the static may-hold graph — either the static "
            "analysis is blind to this path (add the type annotation it "
            "needs) or the runtime took an unvetted lock order"
        )
    problems.extend(
        f"static leaf violation: {v}" for v in graph.leaf_violations
    )
    return problems
