"""``nm03-lint`` — the project's own static-analysis gate.

Runs every NM3xx rule family over the package (plus bench.py and scripts/)
and reports findings *relative to the checked-in baseline*: exit 0 when
nothing new, exit 1 per new finding class, exit 2 on usage errors. The
baseline makes adoption monotonic — the gate is green the day it lands and
every finding after that is a regression, never archaeology.

Usage:
    nm03-lint                      # default paths, text output
    nm03-lint --format json        # machine-readable (scripts/check_static)
    nm03-lint --select NM301,NM331 serving/   # narrow a run
    nm03-lint --update-baseline    # absorb current findings (review the diff!)
    nm03-lint --list-rules         # the catalog (docs/STATIC_ANALYSIS.md)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from nm03_capstone_project_tpu.analysis.atomicio import (
    check_atomic_io,
    check_obs_dump_io,
)
from nm03_capstone_project_tpu.analysis.cachekey import check_cache_key
from nm03_capstone_project_tpu.analysis.compilehome import check_compile_home
from nm03_capstone_project_tpu.analysis.contracts import check_import_contracts
from nm03_capstone_project_tpu.analysis.core import (
    DEFAULT_BASELINE_NAME,
    Finding,
    apply_baseline,
    collect_files,
    find_repo_root,
    load_baseline,
    prune_baseline,
    run_rules,
    write_baseline,
)
from nm03_capstone_project_tpu.analysis.dtypes import check_dtype_discipline
from nm03_capstone_project_tpu.analysis.hostsync import check_host_sync
from nm03_capstone_project_tpu.analysis.lockorder import check_lock_order
from nm03_capstone_project_tpu.analysis.metricsdocs import check_metrics_docs
from nm03_capstone_project_tpu.analysis.retrace import check_retrace
from nm03_capstone_project_tpu.analysis.staginghome import check_staging_home
from nm03_capstone_project_tpu.analysis.threads import check_thread_shared_state

ALL_RULES = (
    check_import_contracts,
    check_retrace,
    check_host_sync,
    check_thread_shared_state,
    check_dtype_discipline,
    check_atomic_io,
    check_obs_dump_io,
    check_compile_home,
    check_cache_key,
    check_metrics_docs,
    check_staging_home,
    check_lock_order,
)

RULE_CATALOG = {
    "NM301": "import-contract: jax/numpy imported at import time by a contract module",
    "NM302": "import-contract: registry names a module missing from the tree",
    "NM311": "retrace: array construction inside a jitted body",
    "NM312": "retrace: jitted callable invoked with a non-static Python scalar",
    "NM321": "host-sync: implicit device->host transfer inside an obs span",
    "NM322": "host-sync: implicit transfer in a serving dispatch-path function",
    "NM331": "threads: unguarded attribute write in a cross-thread class",
    "NM341": "dtype: float64 introduction in the f32 ops pipeline",
    "NM342": "dtype: uint8-cast comparison against an out-of-range literal",
    "NM351": "atomic-io: truncating artifact write without tmp+rename",
    "NM361": "compile-home: jit/pjit/shard_map referenced outside compilehub/",
    "NM371": "obs-io: flight-recorder/trace module writes without atomic_write_*",
    "NM381": "cache-key: CompileSpec field not consumed by the persist cache key",
    "NM392": "metrics-docs: metric name and docs/OBSERVABILITY.md table drifted",
    "NM401": "staging-home: device_put referenced outside ingest/",
    "NM421": "lock-order: cycle in the may-hold graph (static deadlock)",
    "NM422": "lock-order: blocking call (dispatch/IO/sleep/join) under a lock",
    "NM423": "lock-order: bare acquire() without release() in a try/finally",
    "NM390": "meta: suppression without a reason",
    "NM399": "meta: file does not parse",
}


def default_paths(root: Path) -> List[Path]:
    paths = [root / "nm03_capstone_project_tpu"]
    for extra in ("bench.py", "scripts"):
        p = root / extra
        if p.exists():
            paths.append(p)
    return paths


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-lint", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: the package, bench.py, "
        "scripts/)",
    )
    p.add_argument(
        "--root",
        default=None,
        help="repo root for relative paths and the default baseline "
        "(default: nearest ancestor of the first path with a "
        "pyproject.toml)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        metavar="JSON",
        help=f"baseline file (default: <root>/{DEFAULT_BASELINE_NAME}; "
        "missing file = empty baseline)",
    )
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="write the current findings as the new baseline and exit 0 "
        "(the diff is the review artifact)",
    )
    p.add_argument(
        "--prune-baseline",
        action="store_true",
        help="drop baseline entries whose finding no longer reproduces, "
        "then exit 0 (the baseline must only ever shrink without review; "
        "growth goes through --update-baseline and its diff)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    p.add_argument(
        "--select",
        default=None,
        metavar="RULES",
        help="comma list of rule-id prefixes to run (e.g. NM30,NM331)",
    )
    p.add_argument(
        "--format",
        choices=["text", "json"],
        default="text",
        help="output format (json is the scripts/check_static.py interface)",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rid, desc in sorted(RULE_CATALOG.items()):
            print(f"{rid}  {desc}")
        return 0

    if args.root:
        root = Path(args.root).resolve()
    else:
        anchor = Path(args.paths[0]) if args.paths else Path.cwd()
        anchor = anchor if anchor.is_dir() else anchor.parent
        root = find_repo_root(anchor)
    paths = [Path(p) for p in args.paths] or default_paths(root)
    for p in paths:
        if not p.exists():
            print(f"nm03-lint: no such path: {p}", file=sys.stderr)
            return 2

    try:
        files = collect_files(paths, root)
    except ValueError as e:
        print(f"nm03-lint: {e} (is --root an ancestor of every path?)", file=sys.stderr)
        return 2
    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select
        else None
    )
    findings = run_rules(files, ALL_RULES, select=select)

    baseline_path = (
        Path(args.baseline) if args.baseline else root / DEFAULT_BASELINE_NAME
    )
    if args.update_baseline:
        if (args.select or args.paths) and not args.baseline:
            # the default baseline is whole-tree truth: rewriting it from a
            # --select/path-narrowed run would silently DELETE every entry
            # the narrowed run didn't reproduce, and the next full gate
            # run would fail on previously-accepted findings. An explicit
            # --baseline opts out (fixture trees, scratch files).
            print(
                "nm03-lint: refusing --update-baseline on a narrowed run "
                "(--select/path arguments present); rerun with the default "
                "scope, or pass an explicit --baseline",
                file=sys.stderr,
            )
            return 2
        write_baseline(baseline_path, findings)
        print(
            f"nm03-lint: baseline updated with {len(findings)} finding(s) "
            f"at {baseline_path}"
        )
        return 0

    if args.prune_baseline:
        if (args.select or args.paths) and not args.baseline:
            # same whole-tree-truth rule as --update-baseline: a narrowed
            # run reproduces only a slice of the findings and would prune
            # every entry outside that slice
            print(
                "nm03-lint: refusing --prune-baseline on a narrowed run "
                "(--select/path arguments present); rerun with the default "
                "scope, or pass an explicit --baseline",
                file=sys.stderr,
            )
            return 2
        kept, dropped = prune_baseline(baseline_path, findings)
        print(
            f"nm03-lint: baseline pruned: {dropped} stale entr"
            f"{'y' if dropped == 1 else 'ies'} dropped, {kept} kept"
        )
        return 0

    if args.no_baseline:
        new, matched = list(findings), 0
    else:
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"nm03-lint: bad baseline: {e}", file=sys.stderr)
            return 2
        new, matched = apply_baseline(findings, baseline)

    if args.format == "json":
        print(
            json.dumps(
                {
                    "files_scanned": len(files),
                    "findings": [f.to_json() for f in new],
                    "baselined": matched,
                },
                indent=1,
            )
        )
    else:
        for f in new:
            print(f.render())
        suffix = f" ({matched} baselined)" if matched else ""
        print(
            f"nm03-lint: {len(new)} new finding(s) across "
            f"{len(files)} file(s){suffix}"
        )
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
