"""NM32x — implicit device->host transfers where they corrupt telemetry.

OpenCLIPER's (arxiv 1807.11830) core overhead argument applies directly
here: host<->device movement must be *explicit and auditable*, because an
implicit sync in the wrong place serializes the whole pipeline and — worse
for this codebase — silently poisons the numbers we use to detect exactly
that. Two scopes carry the hazard:

* **obs span bodies** (``with spans.span(...)``): a ``.item()`` /
  ``np.asarray`` / ``float(...)`` on a device value inside a span blocks on
  the device stream, so the span's histogram stops measuring the stage and
  starts measuring the backlog — latency attribution lies exactly when it
  matters. The sanctioned idiom is the span's own ``tree=`` argument, which
  syncs deliberately and documents it;
* **serving dispatch paths** (the batcher loop and the warm executor's
  ``run_batch``): one stray sync in the single dispatch thread stalls every
  queued request behind it. Fetches belong inside the supervised primary
  (where the deadline covers them) and nowhere else.

Both scopes have legitimate, deliberate syncs today — those carry inline
suppressions with reasons, which is the point: the rule converts "knows
where the syncs are" from tribal knowledge into grep-able annotations.

Rules:
  NM321  implicit device->host transfer inside an obs span body
  NM322  implicit device->host transfer in a serving dispatch-path function
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

# functions forming the serving dispatch path: relpath -> qualified names
DISPATCH_PATHS: Dict[str, Tuple[str, ...]] = {
    "nm03_capstone_project_tpu/serving/batcher.py": (
        "DynamicBatcher._run",
        "DynamicBatcher.execute",
        # the per-lane chunk path (PR 6): runs on the lane worker pool,
        # where a stray sync stalls that lane's whole chunk
        "DynamicBatcher._execute_chunk",
    ),
    "nm03_capstone_project_tpu/serving/executor.py": (
        "WarmExecutor.run_batch",
    ),
}

_TRANSFER_ATTRS = {"item", "tolist", "block_until_ready"}
_TRANSFER_CALLS = {
    ("np", "asarray"), ("np", "array"),
    ("numpy", "asarray"), ("numpy", "array"),
    ("jax", "device_get"),
}


def _attr_pair(func: ast.expr) -> Optional[Tuple[str, str]]:
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (func.value.id, func.attr)
    return None


def _sync_description(node: ast.Call, rule: str) -> Optional[str]:
    """Human name of the sync this call performs, or None."""
    if isinstance(node.func, ast.Attribute) and node.func.attr in _TRANSFER_ATTRS:
        return f".{node.func.attr}()"
    pair = _attr_pair(node.func)
    if pair in _TRANSFER_CALLS:
        return f"{pair[0]}.{pair[1]}()"
    if isinstance(node.func, ast.Name):
        # print() is only a hazard on the dispatch thread (NM322): driver
        # spans print host strings; the batcher thread must never block on
        # console IO (or format a device array) between batches
        if rule == "NM322" and node.func.id == "print" and node.args and not all(
            isinstance(a, ast.Constant) for a in node.args
        ):
            return "print() of a runtime value"
        if node.func.id in ("float", "int") and node.args and isinstance(
            node.args[0], (ast.Call, ast.Subscript)
        ):
            if _is_shape_access(node.args[0]):
                return None  # shapes are host metadata, never a transfer
            return f"{node.func.id}() of an expression"
    return None


def _is_shape_access(node: ast.expr) -> bool:
    """x.shape[i] / len-like metadata reads that never touch the device."""
    if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Attribute):
        return node.value.attr in ("shape", "dims")
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id == "len"
    return False


def _walk_same_execution(body: List[ast.stmt]):
    """Walk statements WITHOUT descending into nested defs/lambdas: a
    closure defined in a span body does not execute in it (the supervised
    ``primary()`` is the sanctioned home for fetches)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _findings_in(
    src: SourceFile, body: List[ast.stmt], rule: str, where: str
) -> List[Finding]:
    out: List[Finding] = []
    for sub in _walk_same_execution(body):
        if not isinstance(sub, ast.Call):
            continue
        desc = _sync_description(sub, rule)
        if desc is None:
            continue
        hint = (
            "use the span's tree= argument for a deliberate sync"
            if rule == "NM321"
            else "fetch inside the supervised primary, not on the "
            "dispatch thread"
        )
        out.append(
            Finding(
                rule=rule,
                path=src.relpath,
                line=sub.lineno,
                message=(
                    f"{desc} inside {where} forces a device sync — {hint}"
                ),
                source_line=src.line_text(sub.lineno),
            )
        )
    return out


def _is_span_with(node: ast.With) -> bool:
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Call) and isinstance(ctx.func, ast.Attribute):
            if ctx.func.attr in ("span", "section"):
                return True
    return False


def check_host_sync(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        if src.tree is None:
            continue

        # NM321 — span bodies anywhere in the tree
        for node in ast.walk(src.tree):
            if isinstance(node, ast.With) and _is_span_with(node):
                findings.extend(
                    _findings_in(src, node.body, "NM321", "an obs span body")
                )

        # NM322 — the registered serving dispatch-path functions
        wanted = DISPATCH_PATHS.get(src.relpath)
        if not wanted:
            continue
        for cls in src.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                qual = f"{cls.name}.{fn.name}"
                if qual in wanted:
                    findings.extend(
                        _findings_in(
                            src, fn.body, "NM322", f"dispatch path {qual}"
                        )
                    )
    # span-body findings can double-report a call that is ALSO in a
    # dispatch function; keep the more specific NM322 in that case
    nm322_sites = {(f.path, f.line) for f in findings if f.rule == "NM322"}
    return [
        f
        for f in findings
        if not (f.rule == "NM321" and (f.path, f.line) in nm322_sites)
    ]
