"""NM392 — metrics↔docs drift: every registered metric name is documented,
every documented metric name exists.

The telemetry contract is three-sided: producers register series, the
docs/OBSERVABILITY.md tables tell operators (and the capacity-planning
runbook) what each series means, and ``check_telemetry.py`` gates the
schema. The weakest side is the docs — nothing ever *failed* when a new
gauge shipped undocumented, or when a renamed counter left its old row
behind pointing at a series that no longer exists. This rule closes that
gap statically (ISSUE 10), leaning on a convention the metric-name
modules already follow: **every module-level UPPERCASE string constant in
``serving/metrics.py`` and ``obs/metrics.py`` whose value is a
Prometheus-legal lowercase name IS a metric name** (those modules exist
precisely to own the names; schema strings like ``nm03.metrics.v1``
self-exclude via the dots).

The docs side is every table row of docs/OBSERVABILITY.md whose second
cell is a metric type::

    | `serving_mfu` | gauge | — | ... |

Both directions are findings:

* a constant with no docs row anchors at the constant's declaration —
  the series shipped undocumented;
* a docs row with no constant anchors at the docs line — the table
  documents a series no module registers (a rename left a stale row).

Fixture trees work the same way: any ``serving/metrics.py`` /
``obs/metrics.py`` under a scanned root is checked against THAT root's
``docs/OBSERVABILITY.md`` (red/green battery in tests/test_analysis.py).

Rules:
  NM392  metric name registered-but-undocumented / documented-but-unregistered
"""

from __future__ import annotations

import ast
import posixpath
import re
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

DOC_RELPATH = "docs/OBSERVABILITY.md"

# the name-owning modules: <anything>/serving/metrics.py, <anything>/obs/metrics.py
_NAME_MODULE_DIRS = ("serving", "obs")

# a metric name as this codebase writes them: lowercase Prometheus-legal.
# Deliberately excludes dotted schema ids ("nm03.metrics.v1") and anything
# with uppercase (label-value enums etc. are not plain string constants).
_METRIC_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
_METRIC_TYPES = ("counter", "gauge", "histogram")


def _is_name_module(relpath: str) -> bool:
    parts = relpath.split("/")
    return (
        len(parts) >= 2
        and parts[-1] == "metrics.py"
        and parts[-2] in _NAME_MODULE_DIRS
    )


def _module_constants(src: SourceFile) -> Dict[str, Tuple[int, str]]:
    """{metric name: (line, constant identifier)} of one name module.

    Only module-level ``UPPER_CASE = "literal"`` assignments count; a
    re-export (``from obs.metrics import X``) deliberately does not — the
    DEFINITION site is the single owner the rule binds to docs.
    """
    out: Dict[str, Tuple[int, str]] = {}
    if src.tree is None:
        return out
    for node in src.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not (
            isinstance(target, ast.Name)
            and target.id.isupper()
            and not target.id.startswith("_")  # module-private: not a contract
        ):
            continue
        if not (
            isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            continue
        value = node.value.value
        if _METRIC_NAME_RE.match(value):
            out[value] = (node.lineno, target.id)
    return out


def _doc_metric_rows(doc_path: Path) -> Dict[str, Tuple[int, str]]:
    """{metric name: (line, raw line)} from the docs' metric tables.

    A metric row is a markdown table row whose first cell is a backticked
    Prometheus-shaped name and whose second cell is a bare metric type —
    exactly the shape every docs/OBSERVABILITY.md metric table uses, and
    nothing else in the file (endpoint tables carry paths, span tables
    carry scopes in cell two).
    """
    out: Dict[str, Tuple[int, str]] = {}
    try:
        text = doc_path.read_text(encoding="utf-8", errors="replace")
    except OSError:
        return out
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if len(cells) < 2 or cells[1] not in _METRIC_TYPES:
            continue
        name = cells[0].strip("`").strip()
        if _METRIC_NAME_RE.match(name) and name not in out:
            out[name] = (lineno, line)
    return out


def check_metrics_docs(files: Sequence[SourceFile]) -> List[Finding]:
    # group the name modules by scan root: a fixture tree is its own
    # universe with its own docs file
    by_root: Dict[Path, List[SourceFile]] = {}
    for src in files:
        if _is_name_module(src.relpath):
            by_root.setdefault(src.root, []).append(src)

    findings: List[Finding] = []
    for root, modules in sorted(by_root.items(), key=lambda kv: str(kv[0])):
        doc_path = root / DOC_RELPATH
        registered: Dict[str, Tuple[SourceFile, int, str]] = {}
        for src in sorted(modules, key=lambda s: s.relpath):
            for name, (line, ident) in _module_constants(src).items():
                registered.setdefault(name, (src, line, ident))
        if not registered:
            continue
        if not doc_path.exists():
            src = min(modules, key=lambda s: s.relpath)
            findings.append(
                Finding(
                    rule="NM392",
                    path=src.relpath,
                    line=1,
                    message=(
                        f"metric name module has no {DOC_RELPATH} to "
                        "document against — every registered series must "
                        "have a docs table row (docs/STATIC_ANALYSIS.md "
                        "NM392)"
                    ),
                    source_line=src.line_text(1),
                )
            )
            continue
        documented = _doc_metric_rows(doc_path)
        doc_rel = posixpath.join(*DOC_RELPATH.split("/"))
        for name, (src, line, ident) in sorted(registered.items()):
            if name in documented:
                continue
            findings.append(
                Finding(
                    rule="NM392",
                    path=src.relpath,
                    line=line,
                    message=(
                        f"metric {name!r} ({ident}) has no row in "
                        f"{DOC_RELPATH} — a series must ship documented "
                        "(name | type | labels | meaning) "
                        "(docs/STATIC_ANALYSIS.md NM392)"
                    ),
                    source_line=src.line_text(line),
                )
            )
        for name, (lineno, raw) in sorted(documented.items()):
            if name in registered:
                continue
            findings.append(
                Finding(
                    rule="NM392",
                    path=doc_rel,
                    line=lineno,
                    message=(
                        f"documented metric {name!r} is not registered in "
                        "any metric-name module (serving/metrics.py, "
                        "obs/metrics.py) — a rename or removal left a "
                        "stale docs row (docs/STATIC_ANALYSIS.md NM392)"
                    ),
                    source_line=raw,
                )
            )
    return findings
