"""NM381 — cache-key completeness: every CompileSpec field reaches the
persistent cache key.

The persistent executable cache (``compilehub/persist.py``) hands a
process a *compiled binary* instead of compiling one. That is only sound
while the on-disk key covers everything that makes two executables
different — :class:`CompileSpec` is the in-process identity, so the
moment someone adds a spec field (a new backend knob, a precision flag, a
sharding variant) WITHOUT folding it into ``PersistKey.from_spec``, two
genuinely different programs share one cache entry and one of them runs
the other's binary. Silently. That is the worst failure mode this
codebase can have — wrong masks with green telemetry — and it is
invisible to tests until the exact collision is constructed.

The rule therefore checks, statically, that every field declared on the
``CompileSpec`` dataclass (``compilehub/hub.py``) is *read* inside the
sibling ``compilehub/persist.py``'s **key derivation**: the
``from_spec`` function, plus any module function it (transitively)
hands the whole spec to — ``digest(spec)`` inside ``from_spec`` makes
``digest``'s reads coverage. Deliberately NOT module-wide: persist.py's
store/serialize paths legitimately read spec fields for other reasons
(``_serialize`` consults ``spec.device``/``spec.donate`` to refuse the
export fallback), and a read there must not silence the rule — only
reads that can actually reach the key count. Fixture trees work too:
any directory holding a ``hub.py`` that declares CompileSpec is matched
with ITS sibling ``persist.py`` (tests/test_analysis.py red/green
battery).

Findings anchor at the field's declaration line in hub.py — the place
the new field was added is the place the omission gets fixed.

Rules:
  NM381  CompileSpec field not consumed by the persist cache key
"""

from __future__ import annotations

import ast
import posixpath
from typing import Dict, List, Optional, Sequence, Set

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

_SPEC_CLASS = "CompileSpec"
_HUB_FILENAME = "hub.py"
_PERSIST_FILENAME = "persist.py"


def _spec_fields(tree: ast.AST) -> Dict[str, int]:
    """{field name: declaration line} of the CompileSpec dataclass, or {}."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == _SPEC_CLASS:
            fields: Dict[str, int] = {}
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    fields[stmt.target.id] = stmt.lineno
            return fields
    return {}


def _functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Module-level (and class-method) function defs by name, last wins."""
    return {
        node.name: node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _spec_param(fn: ast.FunctionDef) -> Optional[str]:
    """The spec-carrying parameter: the first arg that is not self/cls."""
    for a in fn.args.args:
        if a.arg not in ("self", "cls"):
            return a.arg
    return None


def _reads_in(fn: ast.FunctionDef, param: str) -> Set[str]:
    return {
        node.attr
        for node in ast.walk(fn)
        if isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == param
    }


def _spec_reads(tree: ast.AST) -> Set[str]:
    """Fields read along the KEY DERIVATION: inside ``from_spec`` and in
    any function it (transitively) passes the whole spec object to.

    NOT module-wide on purpose: the store path reads spec fields for
    reasons that never reach the key (``_serialize`` refusing the export
    fallback for pinned specs), and such a read silencing the rule is
    exactly the false negative the break-drill test pins.
    """
    fns = _functions(tree)
    root = fns.get("from_spec")
    if root is None:
        return set()
    reads: Set[str] = set()
    visited: Set[str] = set()
    frontier = [(root, _spec_param(root))]
    while frontier:
        fn, param = frontier.pop()
        if fn.name in visited or param is None:
            continue
        visited.add(fn.name)
        reads |= _reads_in(fn, param)
        # follow helper(spec): the whole object crossed the call, so the
        # helper's reads of its matching parameter are key coverage
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                continue
            callee = fns.get(node.func.id)
            if callee is None:
                continue
            for pos, arg in enumerate(node.args):
                if isinstance(arg, ast.Name) and arg.id == param:
                    args = [
                        a.arg for a in callee.args.args
                        if a.arg not in ("self", "cls")
                    ]
                    if pos < len(args):
                        frontier.append((callee, args[pos]))
    return reads


def check_cache_key(files: Sequence[SourceFile]) -> List[Finding]:
    by_path = {f.relpath: f for f in files}
    findings: List[Finding] = []
    for src in files:
        if src.tree is None or posixpath.basename(src.relpath) != _HUB_FILENAME:
            continue
        fields = _spec_fields(src.tree)
        if not fields:
            continue  # a hub.py without CompileSpec is not the contract file
        persist_rel = posixpath.join(
            posixpath.dirname(src.relpath), _PERSIST_FILENAME
        )
        persist: Optional[SourceFile] = by_path.get(persist_rel)
        if persist is None or persist.tree is None:
            # no persist module in this tree (fixture dirs for other rule
            # families) — the completeness contract applies only where the
            # persistent layer exists
            continue
        reads = _spec_reads(persist.tree)
        for name, line in sorted(fields.items(), key=lambda kv: kv[1]):
            if name in reads:
                continue
            findings.append(
                Finding(
                    rule="NM381",
                    path=src.relpath,
                    line=line,
                    message=(
                        f"CompileSpec field {name!r} is never read by "
                        f"{persist_rel} — the persistent cache key cannot "
                        "cover it, so two specs differing only in "
                        f"{name!r} would share one on-disk executable; "
                        "fold it into PersistKey.from_spec "
                        "(docs/STATIC_ANALYSIS.md NM381)"
                    ),
                    source_line=src.line_text(line),
                )
            )
    return findings
