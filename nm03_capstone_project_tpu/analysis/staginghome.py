"""NM401 — staging-home discipline: host→HBM staging lives in ingest/.

The streaming-ingest subsystem (ISSUE 11) exists because the scattered
alternative already cost this repo its headline: both batch drivers carried
their own ``jax.device_put`` staging loops, each serial, each invisible to
the others, and PR 10's telemetry measured the device starved for a large
fraction of wall (the pinned ``feed_stall``). A staging call outside
``ingest/`` is one refactor away from the same regression — and, more
quietly, from an upload the ingest telemetry cannot see (ring occupancy,
decode lookahead and the upload-overlap ratio only cover what the pipeline
stages) and the ``--sanitize`` transfer guard cannot attribute.

The rule mirrors NM361's compile-home contract: any *reference* to jax's
host→device placement entry points outside the sanctioned homes is a
finding —

* ``from jax... import device_put`` (any jax module) — the binding itself
  is the violation; suppressing it sanctions the uses;
* dotted references — ``jax.device_put``, an aliased ``j.device_put``
  where ``j`` was imported from jax — in calls, wrappers and
  ``functools.partial`` arguments alike (AST references, so strings and
  docstrings never trip it).

Sanctioned homes (no finding):

* ``nm03_capstone_project_tpu/ingest/`` — THE staging home;
* ``nm03_capstone_project_tpu/compilehub/`` — warmup/AOT staging is the
  hub's own job (pinning a lane executable's canary inputs is part of
  compiling for that lane, not batch feeding);
* ``nm03_capstone_project_tpu/utils/sanitize.py`` — the runtime twin that
  polices this very hazard documents the sanctioned idiom.

Everything else suppresses with a reason (docs/STATIC_ANALYSIS.md): the
CPU-degradation fallbacks (committing host arrays to the *fallback*
device is the escape from the wedged one), one-time model-parameter
placement (weights are not the data path), and bench's measurement
harness (the upload IS the thing being measured there).

Rule:
  NM401  device_put referenced outside ingest/
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set, Tuple

from nm03_capstone_project_tpu.analysis.compilehome import _dotted, _jax_module_aliases
from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

_FORBIDDEN = {"device_put", "device_put_sharded", "device_put_replicated"}
_HOME_PREFIX = "nm03_capstone_project_tpu/ingest/"
# staging the compile hub / sanitize runtime twin may do themselves
_SANCTIONED_PREFIXES = (
    _HOME_PREFIX,
    "nm03_capstone_project_tpu/compilehub/",
    "nm03_capstone_project_tpu/utils/sanitize.py",
)


def check_staging_home(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        if src.tree is None or src.relpath.startswith(_SANCTIONED_PREFIXES):
            continue
        aliases = _jax_module_aliases(src.tree)
        seen: Set[Tuple[int, str]] = set()

        def emit(line: int, what: str) -> None:
            if (line, what) in seen:
                return
            seen.add((line, what))
            findings.append(
                Finding(
                    rule="NM401",
                    path=src.relpath,
                    line=line,
                    message=(
                        f"{what} referenced outside ingest/ — host->HBM "
                        "staging belongs to the streaming-ingest subsystem "
                        "(use ingest.stage_batch / an IngestPipeline stage "
                        "callable); CPU-fallback, parameter-placement and "
                        "bench measurement sites suppress with a reason "
                        "(docs/STATIC_ANALYSIS.md)"
                    ),
                    source_line=src.line_text(line),
                )
            )

        for node in ast.walk(src.tree):
            # the binding: from jax[...] import device_put[_*]
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax" or node.module.startswith("jax.")
            ):
                for a in node.names:
                    if a.name in _FORBIDDEN:
                        emit(node.lineno, f"{node.module}.{a.name}")
            # the reference: <jax-ish>.device_put[_*]
            elif isinstance(node, ast.Attribute) and node.attr in _FORBIDDEN:
                base = _dotted(node.value)
                if base is None:
                    continue
                head = base.split(".")[0]
                resolved = aliases.get(head)
                if resolved is not None:
                    base = base.replace(head, resolved, 1)
                if base == "jax" or base.startswith("jax."):
                    emit(node.lineno, f"{base}.{node.attr}")
    return findings
