"""NM35x — artifact writes must use the PR-3 tmp+rename idiom.

A result JSON, manifest, journal snapshot, or exported JPEG is a *promise*:
``--resume`` folds it into the manifest, ``check_telemetry.py`` validates
it, a judge diffs it. PR 3 established the discipline — write to
``<path>.tmp``, then ``os.replace`` — so a SIGTERM/ENOSPC mid-write leaves
either the old artifact or a stray ``.tmp``, never a torn file that parses
as truth. This rule catches the writes that bypass it.

Heuristic: any truncating write (``open(..., "w"/"wb")``,
``Path.write_text``, ``Path.write_bytes``) is a candidate; it is exempt
when the enclosing function visibly completes the idiom (an ``os.replace``
call in the same function) or the target expression names a tmp file.
Append-mode opens are exempt by design — the journal's torn-tail-safe
append IS the other sanctioned idiom. Long-lived streaming sinks (the
JSONL event log) are real exceptions and carry inline suppressions with
the reason, which doubles as their documentation.

Rules:
  NM351  truncating artifact write without the tmp+rename idiom
  NM371  flight-recorder/trace module writes a file without atomic_write_*
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile


def _literal_mode(node: ast.Call) -> Optional[str]:
    """The mode of an open() call when statically known ('r' default)."""
    if len(node.args) >= 2:
        m = node.args[1]
        if isinstance(m, ast.Constant) and isinstance(m.value, str):
            return m.value
        return None  # dynamic mode: cannot judge
    for kw in node.keywords:
        if kw.arg == "mode":
            if isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, str
            ):
                return kw.value.value
            return None
    return "r"


def _names_tmp(node: ast.expr) -> bool:
    """True when the path expression visibly names a tmp target."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if "tmp" in sub.value.lower():
                return True
        if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "tmp" in sub.attr.lower():
            return True
    return False


def _enclosing_function(
    tree: ast.AST, lineno: int
) -> Optional[ast.AST]:
    best = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            end = getattr(node, "end_lineno", None) or node.lineno
            if node.lineno <= lineno <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    return best


def _has_replace(scope: ast.AST) -> bool:
    """os.replace/os.rename, or <tmp-ish>.replace()/.rename() — NOT a bare
    str.replace, which must not count as completing the idiom."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            base = node.func.value
            if attr in ("replace", "rename"):
                if isinstance(base, ast.Name) and base.id == "os":
                    return True
                if _names_tmp(base):
                    return True
    return False


# NM371 — the post-mortem modules' write discipline is stricter than
# NM351: a flight-recorder dump races the very crash it documents, and a
# trace export may be cut by the next SIGTERM, so BOTH must route every
# write through utils.atomicio.atomic_write_* — no hand-rolled tmp+rename
# (which NM351 would accept) and no direct write primitives at all.
OBS_DUMP_MODULES: tuple = (
    "nm03_capstone_project_tpu/obs/flightrec.py",
    "nm03_capstone_project_tpu/obs/trace.py",
)

_DIRECT_WRITE_ATTRS = ("write_text", "write_bytes")
_HAND_ROLLED = ("replace", "rename", "mkstemp", "NamedTemporaryFile")


_MODE_CHARS = set("rwaxbtU+")


def _attr_open_mode(node: ast.Call) -> Optional[str]:
    """Best-effort mode of an attribute-style open call.

    Covers BOTH calling conventions: ``Path(p).open(mode, ...)`` (mode
    first) and ``io.open(path, mode, ...)`` (path first) — any string
    literal among the first two positionals that *looks like* a mode
    string counts, so a literal path (``io.open("debug.json", "w")``)
    can never masquerade as a read mode. None = statically unjudgeable,
    which the caller flags — strictness is this rule's contract.
    """
    for kw in node.keywords:
        if kw.arg == "mode":
            if isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, str
            ):
                return kw.value.value
            return None
    candidates = []
    saw_non_literal = False
    for a in node.args[:2]:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            v = a.value
            if v and set(v) <= _MODE_CHARS and len(v) <= 4:
                candidates.append(v)
        else:
            saw_non_literal = True
    if candidates:
        for v in candidates:  # the most write-looking candidate decides
            if any(c in v for c in "wax+"):
                return v
        return candidates[0]
    if saw_non_literal:
        return None
    return "r"


def _hand_rolled_bindings(tree: ast.AST):
    """Names that reach the hand-rolled write primitives in this module.

    NM371's contract is ANY spelling: ``import os as _os`` and
    ``from os import replace as rp`` must not slip past a matcher pinned
    to the literal attribute form ``os.replace``. Returns
    (module_aliases, bare_names): local names bound to the os/tempfile
    modules, and local names bound directly to a hand-rolled primitive
    (mapped back to its canonical ``module.attr`` for the message).
    """
    module_aliases = set()
    bare_names = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname is None:
                    # `import os.path` binds the TOP-LEVEL name `os`
                    if a.name.split(".")[0] in ("os", "tempfile"):
                        module_aliases.add(a.name.split(".")[0])
                elif a.name in ("os", "tempfile"):
                    # `import os.path as p` binds p to os.path, whose
                    # attrs are not the hand-rolled primitives — only a
                    # whole-module alias counts
                    module_aliases.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module in ("os", "tempfile"):
                for a in node.names:
                    if a.name in _HAND_ROLLED:
                        bare_names[a.asname or a.name] = (
                            f"{node.module}.{a.name}"
                        )
    return module_aliases, bare_names


def check_obs_dump_io(files: Sequence[SourceFile]) -> List[Finding]:
    """NM371: obs.trace / obs.flightrec must write via atomic_write_*."""
    findings: List[Finding] = []
    for src in files:
        if src.relpath not in OBS_DUMP_MODULES or src.tree is None:
            continue
        mod_aliases, bare_hand_rolled = _hand_rolled_bindings(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            what = None
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                mode = _literal_mode(node)
                if mode is None or any(c in (mode or "") for c in "wax+"):
                    what = f'open(..., "{mode}")' if mode else "open(...) with a non-read mode"
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in bare_hand_rolled
            ):
                what = f"{bare_hand_rolled[node.func.id]}() (from-import)"
            elif isinstance(node.func, ast.Attribute):
                if node.func.attr in _DIRECT_WRITE_ATTRS:
                    what = f".{node.func.attr}()"
                elif node.func.attr == "open":
                    # Path.open / io.open are the same primitive wearing an
                    # attribute: flag any non-read (or statically unknown)
                    # mode. NOTE Path.open takes mode as its FIRST
                    # positional, unlike builtin open(path, mode).
                    mode = _attr_open_mode(node)
                    if mode is None or any(c in (mode or "") for c in "wax+"):
                        what = (
                            f'.open(..., "{mode}")' if mode
                            else ".open(...) with a non-read mode"
                        )
                elif node.func.attr in _HAND_ROLLED and isinstance(
                    node.func.value, ast.Name
                ) and node.func.value.id in mod_aliases:
                    what = f"{node.func.value.id}.{node.func.attr}()"
                elif node.func.attr == "rename":
                    # Path(...).rename(target) — receiver-agnostic: these
                    # modules have no legitimate rename of any kind
                    what = ".rename()"
                elif (
                    node.func.attr == "replace"
                    and len(node.args) == 1
                    and not node.keywords
                ):
                    # Path(...).replace(target) takes ONE positional;
                    # str.replace(old, new) takes two, so stays clean
                    what = ".replace(target) (pathlib-style rename)"
            if what is None:
                continue
            findings.append(
                Finding(
                    rule="NM371",
                    path=src.relpath,
                    line=node.lineno,
                    message=(
                        f"{what} in a flight-recorder/trace module — dumps "
                        "and exports race the crash/drain they document and "
                        "must route through utils.atomicio.atomic_write_* "
                        "(the idiom's single point of correctness), never a "
                        "direct or hand-rolled write"
                    ),
                    source_line=src.line_text(node.lineno),
                )
            )
    return findings


def check_atomic_io(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        if src.relpath.startswith("tests/"):
            continue  # test fixtures write scratch files on purpose
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            path_expr: Optional[ast.expr] = None
            what = None
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                mode = _literal_mode(node)
                if mode is None or not mode.startswith("w"):
                    continue
                path_expr = node.args[0] if node.args else None
                what = f'open(..., "{mode}")'
            elif isinstance(node.func, ast.Attribute) and node.func.attr in (
                "write_text",
                "write_bytes",
            ):
                path_expr = node.func.value
                what = f".{node.func.attr}()"
            else:
                continue
            if path_expr is not None and _names_tmp(path_expr):
                continue
            scope = _enclosing_function(src.tree, node.lineno)
            if scope is not None and _has_replace(scope):
                continue
            findings.append(
                Finding(
                    rule="NM351",
                    path=src.relpath,
                    line=node.lineno,
                    message=(
                        f"{what} truncates the target in place — a kill or "
                        "full disk mid-write leaves a torn artifact; write "
                        "to <path>.tmp and os.replace() it (docs/"
                        "RESILIENCE.md), or suppress with why tearing is "
                        "acceptable here"
                    ),
                    source_line=src.line_text(node.lineno),
                )
            )
    return findings
