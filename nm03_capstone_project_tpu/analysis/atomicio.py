"""NM35x — artifact writes must use the PR-3 tmp+rename idiom.

A result JSON, manifest, journal snapshot, or exported JPEG is a *promise*:
``--resume`` folds it into the manifest, ``check_telemetry.py`` validates
it, a judge diffs it. PR 3 established the discipline — write to
``<path>.tmp``, then ``os.replace`` — so a SIGTERM/ENOSPC mid-write leaves
either the old artifact or a stray ``.tmp``, never a torn file that parses
as truth. This rule catches the writes that bypass it.

Heuristic: any truncating write (``open(..., "w"/"wb")``,
``Path.write_text``, ``Path.write_bytes``) is a candidate; it is exempt
when the enclosing function visibly completes the idiom (an ``os.replace``
call in the same function) or the target expression names a tmp file.
Append-mode opens are exempt by design — the journal's torn-tail-safe
append IS the other sanctioned idiom. Long-lived streaming sinks (the
JSONL event log) are real exceptions and carry inline suppressions with
the reason, which doubles as their documentation.

Rules:
  NM351  truncating artifact write without the tmp+rename idiom
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile


def _literal_mode(node: ast.Call) -> Optional[str]:
    """The mode of an open() call when statically known ('r' default)."""
    if len(node.args) >= 2:
        m = node.args[1]
        if isinstance(m, ast.Constant) and isinstance(m.value, str):
            return m.value
        return None  # dynamic mode: cannot judge
    for kw in node.keywords:
        if kw.arg == "mode":
            if isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, str
            ):
                return kw.value.value
            return None
    return "r"


def _names_tmp(node: ast.expr) -> bool:
    """True when the path expression visibly names a tmp target."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if "tmp" in sub.value.lower():
                return True
        if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "tmp" in sub.attr.lower():
            return True
    return False


def _enclosing_function(
    tree: ast.AST, lineno: int
) -> Optional[ast.AST]:
    best = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            end = getattr(node, "end_lineno", None) or node.lineno
            if node.lineno <= lineno <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    return best


def _has_replace(scope: ast.AST) -> bool:
    """os.replace/os.rename, or <tmp-ish>.replace()/.rename() — NOT a bare
    str.replace, which must not count as completing the idiom."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            base = node.func.value
            if attr in ("replace", "rename"):
                if isinstance(base, ast.Name) and base.id == "os":
                    return True
                if _names_tmp(base):
                    return True
    return False


def check_atomic_io(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        if src.relpath.startswith("tests/"):
            continue  # test fixtures write scratch files on purpose
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            path_expr: Optional[ast.expr] = None
            what = None
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                mode = _literal_mode(node)
                if mode is None or not mode.startswith("w"):
                    continue
                path_expr = node.args[0] if node.args else None
                what = f'open(..., "{mode}")'
            elif isinstance(node.func, ast.Attribute) and node.func.attr in (
                "write_text",
                "write_bytes",
            ):
                path_expr = node.func.value
                what = f".{node.func.attr}()"
            else:
                continue
            if path_expr is not None and _names_tmp(path_expr):
                continue
            scope = _enclosing_function(src.tree, node.lineno)
            if scope is not None and _has_replace(scope):
                continue
            findings.append(
                Finding(
                    rule="NM351",
                    path=src.relpath,
                    line=node.lineno,
                    message=(
                        f"{what} truncates the target in place — a kill or "
                        "full disk mid-write leaves a torn artifact; write "
                        "to <path>.tmp and os.replace() it (docs/"
                        "RESILIENCE.md), or suppress with why tearing is "
                        "acceptable here"
                    ),
                    source_line=src.line_text(node.lineno),
                )
            )
    return findings
