"""NM33x — shared-state race heuristic for the threaded subsystems.

The serving stack is a deliberate thread topology: N HTTP handler threads,
one batcher thread, supervisor worker threads, a drain thread spawned from
a signal handler — all sharing objects (queue, executor, app state). The
codebase's own discipline (batcher.py's "single consumer" docstring, the
supervisor's ``_lock``) is that cross-thread attributes are lock-guarded,
Queue/Event-mediated, or explicitly annotated. This rule makes that
discipline checkable.

Heuristic, scoped to stay honest: within files registered as threaded
(serving/ + resilience/supervisor.py), a class that creates threads or owns
synchronization primitives is "concurrent"; any plain attribute it writes
*outside* ``__init__`` and outside a ``with self.<lock>:`` block is flagged.
Attributes whose initializer is itself a synchronization object (Event,
Lock, Condition, Queue, deque) are exempt — mutation happens through their
own thread-safe APIs. CPython's GIL makes most of these benign as *tearing*
goes; the hazard the rule actually guards is ordering (a reader observing
``warm = True`` before the state the flag advertises) and lost updates —
and one unguarded flag that "was fine" is how the next refactor inherits a
race.

False positives are expected and wanted as *documented suppressions*: the
single-thread-confined attribute with a ``disable=NM331 <why>`` annotation
is the cheapest possible concurrency documentation.

Rules:
  NM331  plain attribute written outside a lock in a concurrent class
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

# files whose classes participate in the cross-thread object graph
THREADED_FILES: Tuple[str, ...] = (
    "nm03_capstone_project_tpu/serving/",
    "nm03_capstone_project_tpu/resilience/supervisor.py",
    # the saturation monitor's rings are written by executor/batcher/lane
    # threads and read by scrape handlers (ISSUE 10): same discipline
    "nm03_capstone_project_tpu/obs/saturation.py",
    # the streaming-ingest pipeline (ISSUE 11): feeder/stager/decode-pool
    # threads share the ring, counters and interval rings with the
    # consumer — the package is threaded by construction
    "nm03_capstone_project_tpu/ingest/",
    # the fleet front-end (ISSUE 13): HTTP handler threads, the health
    # poller and the drain thread share the replica state table, the
    # routing weights and the signal cache — same discipline
    "nm03_capstone_project_tpu/fleet/",
    # the result tier (ISSUE 19): the store is written by handler threads
    # on fill and read/evicted by scrape + admin threads; the in-flight
    # index is shared between every handler that might coalesce — same
    # discipline
    "nm03_capstone_project_tpu/cache/",
)

_SYNC_TYPE_NAMES = {
    "Event", "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "deque", "local", "AdmissionQueue",
}


def _call_type_name(node: ast.expr) -> Optional[str]:
    """Rightmost name of a Call's constructor (threading.Lock -> 'Lock')."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _lockish(name: str) -> bool:
    return "lock" in name.lower() or "cond" in name.lower()


class _ClassFacts:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.spawns_thread = False
        self.lock_attrs: Set[str] = set()
        self.sync_attrs: Set[str] = set()  # attrs holding sync objects
        self.init_writes: Set[str] = set()
        # attr -> [(method, line, guarded, source_line)]
        self.writes: Dict[str, List[Tuple[str, int, bool, str]]] = {}


def _field_default_type(node: ast.expr) -> Optional[str]:
    """Type name behind dataclasses.field(default_factory=X) / direct calls."""
    if isinstance(node, ast.Call):
        name = _call_type_name(node)
        if name == "field":
            for kw in node.keywords:
                if kw.arg == "default_factory":
                    v = kw.value
                    if isinstance(v, ast.Attribute):
                        return v.attr
                    if isinstance(v, ast.Name):
                        return v.id
            return None
        return name
    return None


def _gather(src: SourceFile, cls: ast.ClassDef) -> _ClassFacts:
    facts = _ClassFacts(cls)
    # dataclass-style fields
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            tname = _field_default_type(stmt.value) if stmt.value is not None else None
            if tname in _SYNC_TYPE_NAMES:
                facts.sync_attrs.add(stmt.target.id)
                if tname in ("Lock", "RLock", "Condition"):
                    facts.lock_attrs.add(stmt.target.id)
            facts.init_writes.add(stmt.target.id)
            # annotation alone (e.g. `done: threading.Event`) also marks sync
            ann = stmt.annotation
            ann_name = ann.attr if isinstance(ann, ast.Attribute) else (
                ann.id if isinstance(ann, ast.Name) else None
            )
            if ann_name in _SYNC_TYPE_NAMES:
                facts.sync_attrs.add(stmt.target.id)

    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        in_init = method.name == "__init__"

        # guarded line spans: every `with self.<lockish>:` body
        guarded_ranges: List[Tuple[int, int]] = []
        for sub in ast.walk(method):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    ctx = item.context_expr
                    attr = None
                    if isinstance(ctx, ast.Attribute) and isinstance(
                        ctx.value, ast.Name
                    ) and ctx.value.id == "self":
                        attr = ctx.attr
                    if attr is not None and (
                        attr in facts.lock_attrs or _lockish(attr)
                    ):
                        end = getattr(sub, "end_lineno", None) or max(
                            (
                                getattr(n, "end_lineno", 0) or 0
                                for n in ast.walk(sub)
                                if hasattr(n, "lineno")
                            ),
                            default=sub.lineno,
                        )
                        guarded_ranges.append((sub.lineno, end))

        def is_guarded(line: int) -> bool:
            return any(a <= line <= b for a, b in guarded_ranges)

        for sub in ast.walk(method):
            if isinstance(sub, ast.Call):
                name = _call_type_name(sub)
                if name == "Thread":
                    facts.spawns_thread = True
            targets: List[ast.expr] = []
            if isinstance(sub, ast.Assign):
                targets = list(sub.targets)
                vtype = _call_type_name(sub.value)
            elif isinstance(sub, ast.AugAssign):
                targets = [sub.target]
                vtype = None
            else:
                continue
            for t in targets:
                # self.x[...] = / += mutates the container behind self.x:
                # the same shared-state write one indirection deeper
                if isinstance(t, ast.Subscript):
                    t = t.value
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                attr = t.attr
                if in_init:
                    facts.init_writes.add(attr)
                    if vtype in _SYNC_TYPE_NAMES:
                        facts.sync_attrs.add(attr)
                        if vtype in ("Lock", "RLock", "Condition"):
                            facts.lock_attrs.add(attr)
                    if _lockish(attr) and vtype in (
                        "Lock", "RLock", "Condition", None
                    ):
                        facts.lock_attrs.add(attr)
                else:
                    facts.writes.setdefault(attr, []).append(
                        (
                            method.name,
                            sub.lineno,
                            is_guarded(sub.lineno),
                            src.line_text(sub.lineno),
                        )
                    )
    return facts


def _concurrent(facts: _ClassFacts) -> bool:
    return facts.spawns_thread or bool(facts.lock_attrs) or bool(
        facts.sync_attrs
    )


def check_thread_shared_state(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        if not any(
            src.relpath == t or src.relpath.startswith(t) for t in THREADED_FILES
        ):
            continue
        for cls in src.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            facts = _gather(src, cls)
            if not _concurrent(facts):
                continue
            for attr, writes in sorted(facts.writes.items()):
                if attr in facts.sync_attrs or attr in facts.lock_attrs:
                    continue
                for method, line, guarded, source_line in writes:
                    if guarded:
                        continue
                    findings.append(
                        Finding(
                            rule="NM331",
                            path=src.relpath,
                            line=line,
                            message=(
                                f"{cls.name}.{attr} written in {method}() "
                                "without holding a lock, in a class shared "
                                "across threads — guard it, route it through "
                                "a Queue/Event, or annotate why it is "
                                "single-thread confined"
                            ),
                            source_line=source_line,
                        )
                    )
    return findings
