"""NM34x — dtype discipline at the uint8/f32 boundary in ops/.

The pipeline's numeric contract is narrow and deliberate: slices enter as
f32, the mask leaves as uint8, and every op in between stays in f32 (x64 is
never enabled; docs/PERF.md pins the median to bit-identical f32 plans).
The two statically visible ways that contract erodes:

* a float64 introduction on the host side of a jit boundary —
  ``np.arange(..., dtype=np.float64)``, ``astype(float)``,
  ``np.float64(...)`` — which either doubles the constant folded into the
  executable or (under numpy promotion) silently upcasts a whole
  expression before jax canonicalizes it back, making host and device
  paths disagree in the last ulp;
* a comparison against a literal that cannot be represented on the uint8
  side of the cast (``mask.astype(jnp.uint8) > 300``) — constant-foldable
  nonsense that reads like a real threshold.

Scope is ``ops/`` (and the render uint8 leg), where the boundary lives; a
deliberate f64 intermediate (e.g. a normalization constant computed once on
the host at full precision, then cast) is a one-line suppression with the
reason attached.

Rules:
  NM341  float64 introduction (dtype=float64 / astype(float) / np.float64)
  NM342  comparison crossing a uint8 cast against an out-of-range literal
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Tuple

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

SCOPED_DIRS: Tuple[str, ...] = (
    "nm03_capstone_project_tpu/ops/",
    "nm03_capstone_project_tpu/render/",
)


def _attr_pair(node: ast.expr) -> Optional[Tuple[str, str]]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return (node.value.id, node.attr)
    return None


def _is_f64_expr(node: ast.expr) -> bool:
    pair = _attr_pair(node)
    if pair and pair[1] in ("float64", "double"):
        return True
    if isinstance(node, ast.Constant) and node.value in ("float64", "double"):
        return True
    if isinstance(node, ast.Name) and node.id == "float":
        return True  # numpy maps the python float type to float64
    return False


def _is_u8_cast(node: ast.expr) -> bool:
    """x.astype(uint8-ish) or jnp.uint8(x) / np.uint8(x)."""
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) and node.func.attr == "astype":
            if node.args:
                a = node.args[0]
                pair = _attr_pair(a)
                if (pair and pair[1] == "uint8") or (
                    isinstance(a, ast.Constant) and a.value == "uint8"
                ):
                    return True
        pair = _attr_pair(node.func)
        if pair and pair[1] == "uint8":
            return True
    return False


def check_dtype_discipline(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        if not any(src.relpath.startswith(d) for d in SCOPED_DIRS):
            continue
        for node in ast.walk(src.tree):
            # NM341 — float64 introductions
            if isinstance(node, ast.Call):
                pair = _attr_pair(node.func)
                if pair and pair[1] == "float64":
                    findings.append(
                        Finding(
                            rule="NM341",
                            path=src.relpath,
                            line=node.lineno,
                            message=(
                                f"{pair[0]}.float64() constructs f64 in the "
                                "f32 pipeline — compute in f32, or suppress "
                                "with the precision rationale"
                            ),
                            source_line=src.line_text(node.lineno),
                        )
                    )
                    continue
                is_astype = (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                )
                dtype_args = list(node.args[:1]) if is_astype else []
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype_args.append(kw.value)
                for a in dtype_args:
                    if _is_f64_expr(a):
                        findings.append(
                            Finding(
                                rule="NM341",
                                path=src.relpath,
                                line=node.lineno,
                                message=(
                                    "float64 dtype in the f32 pipeline "
                                    "(dtype=float is float64 under numpy) — "
                                    "use np.float32/jnp.float32, or suppress "
                                    "with the precision rationale"
                                ),
                                source_line=src.line_text(node.lineno),
                            )
                        )
                        break

            # NM342 — uint8 cast compared against out-of-range literal
            if isinstance(node, ast.Compare):
                sides = [node.left, *node.comparators]
                has_u8 = any(_is_u8_cast(s) for s in sides)
                if not has_u8:
                    continue
                for s in sides:
                    if isinstance(s, ast.Constant) and isinstance(
                        s.value, (int, float)
                    ) and not isinstance(s.value, bool):
                        if not (0 <= s.value <= 255):
                            findings.append(
                                Finding(
                                    rule="NM342",
                                    path=src.relpath,
                                    line=node.lineno,
                                    message=(
                                        f"comparison of a uint8-cast value "
                                        f"against {s.value!r}, which is "
                                        "outside [0, 255] — the comparison "
                                        "is constant and the threshold is "
                                        "not doing what it reads like"
                                    ),
                                    source_line=src.line_text(node.lineno),
                                )
                            )
    return findings
