"""nm03-lint core: findings, suppressions, baselines, and the file walk.

The analyzer is a *project* linter, not a general one: every rule is pinned
to an invariant this codebase documents in prose (jax-free import contracts,
lock-guarded shared state across the serving/resilience threads, retrace and
host-transfer discipline in the jit hot paths, the PR-3 tmp+rename export
idiom). General linters cannot see those contracts; this one encodes them,
the way ImageCL (arxiv 1605.06399) encodes kernel portability hazards as
compile-time checks instead of runtime surprises.

Deliberately jax-free AND numpy-free: the linter runs in CI processes and
pre-commit hooks that must never pay a backend import, and it registers its
own modules in the import-contract registry — the gate gates itself.

Machinery shared by every rule family:

* :class:`Finding` — one diagnostic: stable rule id, path, line, message,
  plus a content-addressed fingerprint (rule + path + normalized source
  line) so baselines survive unrelated line-number drift;
* suppressions — ``# nm03-lint: disable=NM301,NM331 <reason>`` on the
  finding's line or on a comment line directly above it. A suppression
  *must* carry a reason: a bare disable is itself a finding (NM390) so the
  suppression inventory stays auditable;
* baselines — a checked-in JSON set of fingerprints; the gate fails only on
  findings *not* in the baseline, so adoption day is zero-findings by
  construction and every later finding is new signal.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*nm03-lint:\s*disable=(?P<rules>[A-Z0-9, ]+?)(?:\s+(?P<reason>\S.*))?$"
)

# directories never worth parsing (build junk, artifacts, foreign code)
SKIP_DIRS = {
    ".git", "__pycache__", ".pytest_cache", "build", "dist",
    "results", "csrc", "node_modules", ".eggs",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``fingerprint`` is the baseline identity."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    source_line: str = ""

    @property
    def fingerprint(self) -> str:
        norm = " ".join(self.source_line.split())
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{norm}".encode()
        ).hexdigest()
        return h[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


@dataclasses.dataclass
class Suppression:
    line: int
    rules: Tuple[str, ...]
    reason: str


class SourceFile:
    """One parsed file: AST + source lines + suppression table.

    Parsed once, handed to every rule family — the walk is the expensive
    part, the rules are visitors over it.
    """

    def __init__(self, path: Path, root: Path):
        self.abspath = path
        self.root = root
        self.relpath = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8", errors="replace")
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text, filename=str(path))
        except SyntaxError as e:  # surfaced as NM399 by the engine
            self.parse_error = f"{e.msg} (line {e.lineno})"
        self.suppressions: Dict[int, Suppression] = {}
        self._collect_suppressions()

    @property
    def is_package(self) -> bool:
        """True for __init__.py files (their module IS their package)."""
        return self.relpath.endswith("/__init__.py") or self.relpath == "__init__.py"

    @property
    def module_name(self) -> str:
        """Dotted module path relative to the scan root (bench.py -> bench)."""
        rel = self.relpath
        if rel.endswith("/__init__.py"):
            rel = rel[: -len("/__init__.py")]
        elif rel.endswith(".py"):
            rel = rel[:-3]
        return rel.replace("/", ".")

    def _collect_suppressions(self) -> None:
        # tokenize, not regex-over-lines: '# nm03-lint:' inside a string
        # literal must not become a suppression
        try:
            tokens = tokenize.generate_tokens(iter(self.text.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = tuple(
                    r.strip() for r in m.group("rules").split(",") if r.strip()
                )
                self.suppressions[tok.start[0]] = Suppression(
                    line=tok.start[0],
                    rules=rules,
                    reason=(m.group("reason") or "").strip(),
                )
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparseable files already carry NM399

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        """Same-line suppression, or one on the directly preceding
        comment-only line (for statements too long to annotate inline)."""
        for cand in (line, line - 1):
            s = self.suppressions.get(cand)
            if s is None:
                continue
            if cand == line - 1:
                text = self.lines[cand - 1].strip() if cand - 1 < len(self.lines) else ""
                if not text.startswith("#"):
                    continue  # trailing comment of the previous statement
            if rule in s.rules:
                return s
        return None

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


def collect_files(paths: Sequence[str | os.PathLike], root: Path) -> List[SourceFile]:
    """Expand files/directories into parsed :class:`SourceFile` objects."""
    seen: Dict[Path, None] = {}
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                if any(part in SKIP_DIRS for part in sub.parts):
                    continue
                seen.setdefault(sub.resolve(), None)
        elif p.suffix == ".py":
            seen.setdefault(p.resolve(), None)
    return [SourceFile(p, root) for p in seen]


def find_repo_root(start: Path) -> Path:
    """Nearest ancestor holding pyproject.toml (else ``start`` itself)."""
    start = start.resolve()
    for cand in (start, *start.parents):
        if (cand / "pyproject.toml").exists():
            return cand
    return start


# -- the engine --------------------------------------------------------------


def run_rules(
    files: Iterable[SourceFile],
    rules,
    select: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run every rule family over the parsed files.

    Each rule is ``callable(files) -> Iterable[Finding]`` operating on the
    whole file set (the import-contract rule needs the cross-file graph;
    per-file rules just loop). Suppressions are applied here, centrally,
    and a suppression with no reason degrades into an NM390 finding at the
    same site — suppressing is allowed, hiding *why* is not.
    """
    files = list(files)
    by_path = {f.relpath: f for f in files}
    findings: List[Finding] = []
    for f in files:
        if f.parse_error is not None:
            findings.append(
                Finding(
                    rule="NM399",
                    path=f.relpath,
                    line=1,
                    message=f"file does not parse: {f.parse_error}",
                )
            )
    for rule_fn in rules:
        findings.extend(rule_fn(files))
    out: List[Finding] = []
    for fd in findings:
        if select and not any(fd.rule.startswith(s) for s in select):
            continue
        src = by_path.get(fd.path)
        if src is not None:
            sup = src.suppression_for(fd.rule, fd.line)
            if sup is not None:
                if not sup.reason:
                    out.append(
                        Finding(
                            rule="NM390",
                            path=fd.path,
                            line=sup.line,
                            message=(
                                f"suppression of {fd.rule} has no reason; write "
                                "'# nm03-lint: disable=RULE <why this is safe>'"
                            ),
                            source_line=src.line_text(sup.line),
                        )
                    )
                continue
        out.append(fd)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# -- baseline ----------------------------------------------------------------

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = "nm03lint_baseline.json"


def load_baseline(path: Path) -> Dict[str, int]:
    """fingerprint -> allowed count. Missing file = empty baseline."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}; "
            f"this nm03-lint writes version {BASELINE_VERSION}"
        )
    counts: Dict[str, int] = {}
    for e in data.get("entries", []):
        counts[e["fingerprint"]] = counts.get(e["fingerprint"], 0) + 1
    return counts


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "fingerprint": f.fingerprint,
            # message kept for humans diffing the baseline, not for matching
            "message": f.message,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    payload = {"version": BASELINE_VERSION, "entries": entries}
    tmp = Path(f"{path}.tmp")
    tmp.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
    os.replace(tmp, path)


def prune_baseline(path: Path, findings: Sequence[Finding]) -> Tuple[int, int]:
    """Drop baseline entries whose finding no longer reproduces.

    ``findings`` must come from a FULL default-scope run (the CLI refuses
    narrowed runs for the same reason --update-baseline does): an entry is
    kept only up to the multiplicity the current tree still produces, so a
    fixed finding leaves the baseline the moment it is fixed instead of
    accreting forever. Returns ``(kept, dropped)``; the file is rewritten
    (tmp+rename) only when something was dropped.
    """
    if not path.exists():
        return (0, 0)
    data = json.loads(path.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}; "
            f"this nm03-lint writes version {BASELINE_VERSION}"
        )
    live: Dict[str, int] = {}
    for f in findings:
        live[f.fingerprint] = live.get(f.fingerprint, 0) + 1
    kept: List[dict] = []
    dropped = 0
    for e in data.get("entries", []):
        if live.get(e.get("fingerprint"), 0) > 0:
            live[e["fingerprint"]] -= 1
            kept.append(e)
        else:
            dropped += 1
    if dropped:
        payload = {"version": BASELINE_VERSION, "entries": kept}
        tmp = Path(f"{path}.tmp")
        tmp.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
        os.replace(tmp, path)
    return (len(kept), dropped)


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], int]:
    """(new findings, matched count): baseline entries absorb matching
    findings up to their recorded multiplicity."""
    remaining = dict(baseline)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        if remaining.get(f.fingerprint, 0) > 0:
            remaining[f.fingerprint] -= 1
            matched += 1
        else:
            new.append(f)
    return new, matched
