"""Project-specific static analysis (``nm03-lint``) — docs/STATIC_ANALYSIS.md.

jax-free and numpy-free at import by contract (and self-enforced: this
package registers itself in its own import-contract registry).
"""

from nm03_capstone_project_tpu.analysis.core import (  # noqa: F401
    Finding,
    SourceFile,
    apply_baseline,
    collect_files,
    find_repo_root,
    load_baseline,
    run_rules,
    write_baseline,
)
from nm03_capstone_project_tpu.analysis.cli import (  # noqa: F401
    ALL_RULES,
    RULE_CATALOG,
    main,
)
