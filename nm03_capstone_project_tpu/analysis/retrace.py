"""NM31x — retrace hazards in the jit hot paths.

The serving executor exists because recompiles are the one latency cliff an
always-warm service cannot absorb (a single retrace stalls every rider of
the batch window). The two statically visible ways this codebase can
reintroduce one:

* calling a jitted function with a Python scalar positional argument that
  was not declared static — every distinct value traces a new program
  (weak-typed scalars specialize the jaxpr), which presents as "it got
  slower after N requests", never as an error;
* constructing ``jnp.array``/``jnp.asarray`` (or ``np.*`` equivalents) from
  Python data *inside* a jitted body — at best a constant re-baked per
  trace, at worst a host->device transfer on every call.

Both have sanctioned idioms already in tree (``static_argnames`` on the
growers, host-side construction + ``device_put`` in the drivers), so the
rule points at the idiom, not just the hazard.

Analysis is module-local by design: a jit wrapper and its callee defined in
different files resolve through the import graph only at runtime, and a
project linter that guesses cross-module bindings produces noise, not
signal. The hot paths this rule exists for (runner, executor, bench worker)
all jit module-local callables.

Rules:
  NM311  jnp.array/jnp.asarray/np.asarray/np.array construction inside a
         jitted function body
  NM312  jitted callable invoked with a Python numeric literal positional
         argument and no static_argnames/static_argnums declaration
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from nm03_capstone_project_tpu.analysis.core import Finding, SourceFile

_ARRAY_CTORS = {
    ("jnp", "array"), ("jnp", "asarray"),
    ("np", "array"), ("np", "asarray"), ("np", "frombuffer"),
    ("numpy", "array"), ("numpy", "asarray"),
}
_WRAPPERS = {"vmap", "pmap", "grad", "value_and_grad", "checkify", "partial"}
# the compile hub's tracked wrappers ARE jit for this rule's purposes —
# without them the NM311/312 coverage would silently vanish the day a call
# site migrates to the hub (PR 6 migrated all of them)
_JIT_NAMES = ("jit", "pjit", "hub_jit", "_hub_jit")
_JIT_BASES = ("jax", "pjit", "", "hub", "compilehub")


def _attr_pair(func: ast.expr) -> Optional[Tuple[str, str]]:
    """('jax', 'jit') for ``jax.jit``; ('', 'jit') for bare ``jit``."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (func.value.id, func.attr)
    if isinstance(func, ast.Name):
        return ("", func.id)
    return None


def _is_jit_call(node: ast.Call) -> bool:
    pair = _attr_pair(node.func)
    return (
        pair is not None
        and pair[1] in _JIT_NAMES
        and pair[0] in _JIT_BASES
    )


def _has_static(node: ast.Call) -> bool:
    return any(
        kw.arg in ("static_argnames", "static_argnums") for kw in node.keywords
    )


def _unwrap_to_callable(node: ast.expr) -> Optional[ast.expr]:
    """Peel jax.vmap/functools.partial/... down to the jitted Name/Lambda."""
    while isinstance(node, ast.Call):
        pair = _attr_pair(node.func)
        if pair is None or pair[1] not in _WRAPPERS:
            return node  # a call producing the callable we cannot see into
        if not node.args:
            return None
        node = node.args[0]
    return node


class _JitInventory(ast.NodeVisitor):
    """Module-wide jit facts: jitted defs, jitted names, static-ness."""

    def __init__(self):
        self.defs: Dict[str, ast.AST] = {}  # every def/lambda by name
        self.jitted_defs: List[Tuple[ast.AST, bool]] = []  # (def node, has_static)
        self.jitted_names: Dict[str, bool] = {}  # name -> has_static

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.defs[node.name] = node
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and _is_jit_call(dec):
                self.jitted_defs.append((node, _has_static(dec)))
                self.jitted_names[node.name] = _has_static(dec)
            else:
                pair = _attr_pair(dec)
                if pair and pair[1] in _JIT_NAMES and pair[0] in _JIT_BASES:
                    self.jitted_defs.append((node, False))
                    self.jitted_names[node.name] = False
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call) and _is_jit_call(node.value):
            has_static = _has_static(node.value)
            target_names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            for name in target_names:
                self.jitted_names[name] = has_static
            inner = (
                _unwrap_to_callable(node.value.args[0])
                if node.value.args
                else None
            )
            if isinstance(inner, ast.Lambda):
                self.jitted_defs.append((inner, has_static))
            elif isinstance(inner, ast.Name):
                self._pending = getattr(self, "_pending", [])
                self._pending.append((inner.id, has_static))
        self.generic_visit(node)

    def resolve_pending(self) -> None:
        for name, has_static in getattr(self, "_pending", []):
            node = self.defs.get(name)
            if node is not None:
                self.jitted_defs.append((node, has_static))


def _is_number_literal(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub, ast.UAdd)):
        return _is_number_literal(node.operand)
    return False


def check_retrace(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        if src.tree is None:
            continue
        inv = _JitInventory()
        inv.visit(src.tree)
        inv.resolve_pending()

        # NM311: array construction inside jitted bodies
        seen_nodes: Set[int] = set()
        for def_node, _static in inv.jitted_defs:
            body = def_node.body if isinstance(def_node, ast.Lambda) else def_node
            for sub in ast.walk(body):
                if not isinstance(sub, ast.Call) or id(sub) in seen_nodes:
                    continue
                pair = _attr_pair(sub.func)
                if pair in _ARRAY_CTORS:
                    seen_nodes.add(id(sub))
                    findings.append(
                        Finding(
                            rule="NM311",
                            path=src.relpath,
                            line=sub.lineno,
                            message=(
                                f"{pair[0]}.{pair[1]}() inside a jitted body: "
                                "constructed per trace (and a host transfer "
                                "when data is concrete) — build the array "
                                "outside the jit and pass it in, or use "
                                "jnp.full/zeros with traced shapes"
                            ),
                            source_line=src.line_text(sub.lineno),
                        )
                    )

        # NM312: jitted name called with a Python numeric literal
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Name):
                continue
            has_static = inv.jitted_names.get(node.func.id)
            if has_static is None or has_static:
                continue
            for arg in node.args:
                if _is_number_literal(arg):
                    findings.append(
                        Finding(
                            rule="NM312",
                            path=src.relpath,
                            line=node.lineno,
                            message=(
                                f"jitted {node.func.id}() called with a Python "
                                "scalar literal and no static_argnames — every "
                                "distinct value retraces; declare the argument "
                                "static or pass a jnp array"
                            ),
                            source_line=src.line_text(node.lineno),
                        )
                    )
                    break
    return findings
