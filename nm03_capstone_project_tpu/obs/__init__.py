"""Observability subsystem: metrics, spans, and structured run telemetry.

Unifies (and supersedes) the scattered timing/profiling/logging fragments:

* :mod:`~nm03_capstone_project_tpu.obs.metrics` — a thread-safe registry of
  counters, gauges, and bucketed histograms, snapshot-able to JSON and to
  the Prometheus text exposition format;
* :mod:`~nm03_capstone_project_tpu.obs.spans` — nested named sections with
  device sync, ``jax.profiler`` trace annotations, and per-stage latency
  histograms (absorbing ``utils.timing.Timer``, which is now an alias);
* :mod:`~nm03_capstone_project_tpu.obs.events` — a JSON-lines event log
  where every record carries the run id, git SHA, sequence number, and
  wall + monotonic timestamps, plus the heartbeat thread and the bridge
  that mirrors package-logger warnings into the stream;
* :mod:`~nm03_capstone_project_tpu.obs.run` — :class:`RunContext`, the
  driver-facing facade that owns the per-patient outcome protocol;
* :mod:`~nm03_capstone_project_tpu.obs.trace` — request-scoped serving
  traces (span trees per trace id, Chrome/Perfetto export via
  ``nm03-trace``);
* :mod:`~nm03_capstone_project_tpu.obs.flightrec` — the crash flight
  recorder (per-thread rings, atomic dumps on SIGUSR2 / degradation /
  unhandled crash).

Schemas and metric names are documented in docs/OBSERVABILITY.md and
validated by scripts/check_telemetry.py.
"""

from nm03_capstone_project_tpu.obs import flightrec  # noqa: F401
from nm03_capstone_project_tpu.obs.events import (  # noqa: F401
    LEVELS,
    SCHEMA_EVENTS,
    EventLog,
    Heartbeat,
    LogBridge,
    new_run_id,
)
from nm03_capstone_project_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    SCHEMA_METRICS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from nm03_capstone_project_tpu.obs.run import (  # noqa: F401
    GROW_TRUNCATED_TOTAL,
    PATIENT_OUTCOMES_TOTAL,
    PIPELINE_DEGRADED_TOTAL,
    RESILIENCE_FAULTS_INJECTED_TOTAL,
    RESILIENCE_RETRIES_TOTAL,
    SLICES_TOTAL,
    RunContext,
)
from nm03_capstone_project_tpu.obs.saturation import (  # noqa: F401
    FEED_PHASES,
    PhaseAccountant,
    SaturationMonitor,
    peak_flops_for,
)
from nm03_capstone_project_tpu.obs.spans import (  # noqa: F401
    STAGE_LATENCY_METRIC,
    SpanRecorder,
)
from nm03_capstone_project_tpu.obs.trace import (  # noqa: F401
    NULL_TRACE,
    SERVE_TRACE_EVENT,
    ChunkTrace,
    TraceContext,
    new_trace_id,
    sanitize_trace_id,
)
