"""Crash flight recorder: a bounded in-memory ring, dumped on demand.

The wedged-tunnel probe timeouts of BENCH_r03/r05 — and any hung serving
dispatch — share one diagnostic problem: by the time anyone notices, the
process either died (nothing on disk) or is wedged (logs stop exactly at
the interesting moment). The flight recorder solves it the way avionics
do: a small, always-on, lock-guarded ring of the most recent events and
spans **per thread**, costing one dict build and one deque append per
record while the process is healthy, and dumped *atomically* (tmp+rename,
via :func:`~nm03_capstone_project_tpu.utils.atomicio.atomic_write_text` —
lint rule NM371 bans any other write primitive in this module) when
something goes wrong:

* **SIGUSR2** — the operator's post-mortem trigger against a live (or
  wedged) process: ``kill -USR2 <pid>`` and the last N records of every
  thread land in ``nm03_flight_<pid>_sigusr2_<n>.json``;
* **one-way CPU degradation** — the PR-3 supervisor auto-dumps at the
  degradation transition, capturing what every thread was doing when the
  dispatch deadline expired;
* **unhandled crash** — ``sys.excepthook`` / ``threading.excepthook``
  chains dump before the traceback prints.

jax-free AND numpy-free at import by contract (the NM301 registry pins
``obs.flightrec`` explicitly): the recorder must be importable — and must
dump — from processes that never paid a backend import, including the
bench orchestrator. Recording is process-global (:func:`note`); dumping
is inert until :func:`configure`/:func:`install` names a directory, so
library callers never spray files.

Schema (``nm03.flightrec.v1``) and the triage runbook are documented in
docs/OBSERVABILITY.md and docs/OPERATIONS.md ("post-mortem triage").
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

SCHEMA_FLIGHT = "nm03.flightrec.v1"

# per-thread ring length and the thread-ring cap: HTTP handler threads are
# transient and unboundedly named, so the ring table is LRU-bounded — a
# post-mortem cares about the threads active at the end, not every
# connection ever served
DEFAULT_RING = 256
MAX_THREADS = 64

ENV_DUMP_DIR = "NM03_FLIGHTREC_DIR"


class _Ring:
    """One thread's bounded record ring, with its own lock.

    The lock is per-ring so the only contention on a thread's hot-path
    append is a concurrent snapshot/dump — never another thread's append.
    RLock, not Lock: a SIGUSR2 dump runs on the main thread and must
    survive interrupting a main-thread ``note()`` that already holds its
    own ring's lock.
    """

    __slots__ = ("lock", "records", "last_mono")

    def __init__(self, maxlen: int):
        self.lock = threading.RLock()
        self.records: deque = deque(maxlen=maxlen)
        self.last_mono = time.monotonic()


class FlightRecorder:
    """Per-thread bounded rings of recent records, dumpable atomically.

    ``note()`` is the hot path and is deliberately tiny: build one dict,
    append to the calling thread's own ring under that ring's (otherwise
    uncontended) lock — the serving path funnels every span boundary of
    every lane and handler thread through here, so a process-wide note
    lock would serialize exactly the threads tracing exists to tell
    apart. The table lock is only taken to register a new thread's ring,
    to evict, and to snapshot. Everything else (dump, handler
    installation) is cold-path.
    """

    def __init__(self, ring: int = DEFAULT_RING, max_threads: int = MAX_THREADS):
        # RLock: a signal handler dumping on the main thread must survive
        # interrupting a main-thread note() mid-registration
        self._lock = threading.RLock()
        self._ring_len = int(ring)
        self._max_threads = int(max_threads)
        self._rings: "OrderedDict[str, _Ring]" = OrderedDict()
        self._tl = threading.local()  # caches this thread's (key, ring)
        self._dump_dir: Optional[str] = None
        self._dump_seq = itertools.count()
        self._installed = False
        self._prev_excepthook = None
        self._prev_threading_hook = None
        self._t0 = time.monotonic()

    # -- recording (the hot path) ------------------------------------------

    def note(self, kind: str, name: str, **fields) -> None:
        """Append one record to the calling thread's ring. Never raises."""
        try:
            rec = {
                "ts_unix": round(time.time(), 6),
                "mono_s": round(time.monotonic(), 6),
                "kind": str(kind),
                "name": str(name),
            }
            for k, v in fields.items():
                if k not in rec:
                    rec[k] = v
            cur = threading.current_thread()
            # name#ident, not name alone: every supervisor worker is named
            # "nm03-dispatch", and one shared ring would let healthy lanes
            # flush the wedged lane's evidence in seconds
            key = f"{cur.name}#{cur.ident}"
            ring = getattr(self._tl, "ring", None)
            # the membership probe is deliberately lock-free (dict reads
            # are atomic): it only decides whether to take the slow
            # registration path, which re-checks under the lock
            if (
                ring is None
                or self._tl.key != key
                or key not in self._rings
            ):
                with self._lock:
                    ring = self._rings.get(key)
                    if ring is None:
                        ring = _Ring(self._ring_len)
                        self._rings[key] = ring
                        while len(self._rings) > self._max_threads:
                            self._evict_one_ring()
                self._tl.key = key
                self._tl.ring = ring
            with ring.lock:
                ring.records.append(rec)
                ring.last_mono = rec["mono_s"]
        except Exception:  # noqa: BLE001 — the recorder must never cost a run
            pass

    def _evict_one_ring(self) -> None:
        """Drop one ring (caller holds the table lock; table is over cap).

        Dead threads' rings go first: a wedged thread stops calling
        ``note()`` and so stops refreshing ``last_mono``, which would make
        plain LRU evict exactly the ring a post-mortem needs ("the thread
        whose ring stops"). Only when every ring belongs to a live thread
        does the least-recently-active one go.
        """
        live = {f"{t.name}#{t.ident}" for t in threading.enumerate()}
        victim = next((k for k in self._rings if k not in live), None)
        if victim is None:
            victim = min(
                self._rings, key=lambda k: self._rings[k].last_mono
            )
        del self._rings[victim]

    # -- snapshot / dump ---------------------------------------------------

    def snapshot(self, reason: str = "snapshot") -> dict:
        with self._lock:
            entries = list(self._rings.items())
        threads = {}
        for k, ring in entries:
            with ring.lock:
                threads[k] = list(ring.records)
        return {
            "schema": SCHEMA_FLIGHT,
            "reason": str(reason),
            "pid": os.getpid(),
            "ts_unix": round(time.time(), 6),
            "mono_s": round(time.monotonic(), 6),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "threads_live": [t.name for t in threading.enumerate()],
            "records_total": sum(len(v) for v in threads.values()),
            "threads": threads,
        }

    def configure(self, dump_dir: Optional[str]) -> None:
        """Name (or clear, with None) the auto-dump directory."""
        with self._lock:
            self._dump_dir = str(dump_dir) if dump_dir is not None else None

    @property
    def configured(self) -> bool:
        with self._lock:
            return self._dump_dir is not None

    def dump(self, path: Optional[str] = None, reason: str = "manual") -> str:
        """Write the snapshot atomically; returns the dump path.

        With no ``path``, the file lands in the configured dump directory
        (or the cwd) as ``nm03_flight_<pid>_<reason>_<n>.json``. The write
        goes through ``atomic_write_text`` — a dump raced by the crash it
        documents must be complete-or-absent, never torn (NM371).
        """
        from nm03_capstone_project_tpu.utils.atomicio import atomic_write_text

        snap = self.snapshot(reason=reason)
        if path is None:
            safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in reason)
            name = f"nm03_flight_{os.getpid()}_{safe}_{next(self._dump_seq)}.json"
            with self._lock:
                base = self._dump_dir or "."
            path = os.path.join(base, name)
        atomic_write_text(path, json.dumps(snap, default=str, indent=1) + "\n")
        return path

    def auto_dump(self, reason: str) -> Optional[str]:
        """Dump iff a dump directory is configured; swallows every error.

        The hook sites (supervisor degradation, excepthooks, the SIGUSR2
        handler) call this — a failing dump must never make a bad moment
        worse.
        """
        if not self.configured:
            return None
        try:
            path = self.dump(reason=reason)
        except Exception:  # noqa: BLE001 — post-mortem capture is best-effort
            return None
        with contextlib.suppress(Exception):
            sys.stderr.write(f"nm03-flightrec: dumped {reason} -> {path}\n")
            sys.stderr.flush()
        return path

    # -- handler installation (cold path, process-lifetime) ----------------

    def install(
        self,
        dump_dir: Optional[str] = None,
        sigusr2: bool = True,
        excepthook: bool = True,
    ) -> None:
        """Arm the recorder: dump dir + SIGUSR2 handler + crash hooks.

        Idempotent (a second install only refreshes the dump dir). The
        SIGUSR2 handler can only be registered from the main thread;
        elsewhere it is skipped silently (``configure`` + ``auto_dump``
        still work — the in-process tests use exactly that).
        """
        self.configure(
            dump_dir if dump_dir is not None else os.environ.get(ENV_DUMP_DIR, ".")
        )
        with self._lock:
            if self._installed:
                return
            self._installed = True
        if sigusr2:
            with contextlib.suppress(Exception):  # non-main thread / platform
                import signal

                signal.signal(
                    signal.SIGUSR2, lambda s, f: self.auto_dump("sigusr2")
                )
        if excepthook:
            self._prev_excepthook = sys.excepthook

            def hook(exc_type, exc, tb):
                self.note(
                    "crash", exc_type.__name__, message=str(exc)[:500]
                )
                self.auto_dump(f"crash_{exc_type.__name__}")
                (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

            sys.excepthook = hook
            self._prev_threading_hook = threading.excepthook

            def thread_hook(args):
                if args.exc_type is not SystemExit:
                    self.note(
                        "crash",
                        args.exc_type.__name__,
                        message=str(args.exc_value)[:500],
                        thread=getattr(args.thread, "name", None),
                    )
                    self.auto_dump(f"thread_crash_{args.exc_type.__name__}")
                (self._prev_threading_hook or threading.__excepthook__)(args)

            threading.excepthook = thread_hook


# the process-wide recorder: one ring table per process, like the compile
# hub — a post-mortem wants every thread's tail in ONE file
_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _RECORDER


def note(kind: str, name: str, **fields) -> None:
    """Record into the process recorder (the tracer's feed)."""
    _RECORDER.note(kind, name, **fields)


def configure(dump_dir: Optional[str]) -> None:
    _RECORDER.configure(dump_dir)


def install(dump_dir: Optional[str] = None, **kwargs) -> None:
    _RECORDER.install(dump_dir=dump_dir, **kwargs)


def auto_dump(reason: str) -> Optional[str]:
    return _RECORDER.auto_dump(reason)


def dump(path: Optional[str] = None, reason: str = "manual") -> str:
    return _RECORDER.dump(path=path, reason=reason)
