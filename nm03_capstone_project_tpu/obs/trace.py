"""Request-scoped tracing for the serving path + the Perfetto exporter.

PR 1's spans are per-stage *aggregates* and PR 6's ``serving_lane_*``
series say how many batches each lane ran; neither can answer "where did
request X's 400 ms go". This module adds the missing attribution layer:

* every ``POST /v1/segment`` gets a **trace id** (an inbound
  ``X-Nm03-Request-Id`` header is honored after sanitization, else one is
  minted) that travels on the :class:`~..serving.queue.ServeRequest`
  through admission → coalescing → per-lane chunk dispatch → the
  supervised executor → response, and is echoed back as the
  ``X-Nm03-Request-Id`` response header so ``nm03-loadgen`` can correlate;
* each hop records a **span** (``queue_wait``, ``coalesce``, ``pad_stack``,
  ``device_dispatch`` per supervised attempt, ``fetch``, ``cpu_fallback``,
  ``encode``). Chunk-level spans are *shared*: one record carries every
  rider's trace id, which is exactly how a coalesced batch shows up as one
  dispatch block with N requests on the timeline;
* completed requests emit one ``serve_trace`` event (the span tree) into
  the ordinary JSONL event log, and every span begin/end also feeds the
  :mod:`~nm03_capstone_project_tpu.obs.flightrec` ring — an in-flight
  request's trace id is in the flight recorder *before* the dispatch that
  may wedge;
* ``nm03-trace`` (this module's :func:`main`) converts an event stream's
  ``serve_trace`` records into Chrome/Perfetto ``trace_event`` JSON (B/E
  pairs; request tracks + lane tracks), validated by
  ``scripts/check_telemetry.py --expect-trace``.

jax-free AND numpy-free at import by contract (NM301 registry pins
``obs.trace``); the exporter writes through ``atomic_write_text`` (NM371).
Schema (``nm03.trace.v1``) is documented in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import contextlib
import itertools
import json
import os
import re
import sys
import threading
import time
from typing import Dict, Iterable, List, Optional

from nm03_capstone_project_tpu.obs import flightrec

SCHEMA_TRACE = "nm03.trace.v1"
# the JSONL event (one per completed request) carrying the span tree
SERVE_TRACE_EVENT = "serve_trace"
# the router-side twin (ISSUE 14): one per proxied request (and one per
# probation canary, flagged probe=true) in the fleet front-end's stream,
# carrying the router's own span tree under the same schema
FLEET_TRACE_EVENT = "fleet_trace"

# the serving span vocabulary (docs/OBSERVABILITY.md trace schema). The
# exporter and validator are deliberately name-agnostic (every B event
# must carry a trace id, whatever it is called); this tuple is the
# authoritative schema list, pinned by the serving e2e test — a new span
# name on the request path must be added here AND to the docs table
SERVE_SPAN_NAMES = (
    "queue_wait",       # admission -> popped by the batcher
    "coalesce",         # popped -> the batching window closed
    "pad_stack",        # chunk padded into its bucket canvas stack
    "device_dispatch",  # one supervised execute attempt on one lane
    "fetch",            # device -> host result fetch (inside the deadline)
    "requeue",          # chunk re-dispatched off a quarantined lane
    "probe",            # probation canary on a quarantined lane (off-path)
    "cpu_fallback",     # degraded-path recompute
    "encode",           # host render + JPEG encode on the handler thread
    # whole-volume serving (ISSUE 15): the gang lane's span chain
    "volume_gang_acquire",  # waiting for the slice batcher to park
    "volume_dispatch",      # one supervised mesh-wide execute attempt
    "volume_gather",        # mesh -> host mask-volume fetch
    "volume_requeue",       # the gang re-meshed onto surviving lanes
)

# the fleet section of the span vocabulary (ISSUE 14): the router's own
# spans, riding `fleet_trace` events in the front-end's stream. Same
# lockstep contract as SERVE_SPAN_NAMES — a new router span must be
# added here AND to the docs/OBSERVABILITY.md trace table.
FLEET_SPAN_NAMES = (
    "route_pick",       # one smooth-WRR pick over the healthy set
    "proxy_hop",        # one forward attempt to one replica (`replica` field)
    "failover",         # the rider moved off a dying/shedding replica
    "canary_probe",     # one probation canary POST (off-path, probe=true)
)

# client-supplied trace ids: bounded charset/length so a hostile header
# cannot smuggle log-breaking bytes into the event stream or a filename
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._:\-]{0,63}$")

_SPAN_SEQ = itertools.count(1)


def new_trace_id() -> str:
    import uuid

    return uuid.uuid4().hex[:16]


def sanitize_trace_id(raw: Optional[str]) -> Optional[str]:
    """A usable client-supplied trace id, or None (caller mints one)."""
    if not isinstance(raw, str):
        return None
    raw = raw.strip()
    return raw if _TRACE_ID_RE.match(raw) else None


def _new_span_id() -> str:
    # pid-salted: the exporter dedupes shared chunk spans by id, and a
    # concatenated event stream (two replicas' logs, or a restarted
    # server appending with ">>") must not let a second process's s1
    # collide with the first's and be silently dropped from the export
    return f"s{os.getpid():x}.{next(_SPAN_SEQ):x}"


def make_span(
    name: str,
    t0_s: float,
    t1_s: float,
    trace_ids: List[str],
    lane: Optional[int] = None,
    **fields,
) -> dict:
    """One span record (the unit both the event log and the exporter use).

    Times are ``time.monotonic()`` seconds — one process-wide timebase so
    spans from different threads line up on one timeline. ``riders`` > 1
    marks a shared (chunk-level) span: one dispatch, many requests.
    """
    rec = {
        "id": _new_span_id(),
        "name": str(name),
        "t0_s": round(t0_s, 6),
        "dur_s": round(max(t1_s - t0_s, 0.0), 6),
        "thread": threading.current_thread().name,
        "lane": lane,
        "riders": len(trace_ids),
        "trace_ids": list(trace_ids),
    }
    for k, v in fields.items():
        if k not in rec:
            rec[k] = v
    return rec


class TraceContext:
    """One request's span collection, carried on the ServeRequest.

    Appends happen from the handler, batcher, and lane-pool threads, but
    always sequenced by the request's own lifecycle handoffs (queue put,
    chunk dispatch, done-Event); the lock makes the container safe against
    a concurrent flight-recorder snapshot mid-append anyway.
    """

    __slots__ = ("trace_id", "spans", "_lock")

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or new_trace_id()
        self.spans: List[dict] = []
        self._lock = threading.Lock()

    def add(self, rec: dict) -> None:
        with self._lock:
            self.spans.append(rec)

    def add_span(
        self, name: str, t0_s: float, t1_s: float, lane: Optional[int] = None,
        **fields,
    ) -> dict:
        """Record a retrospective span (both endpoints already measured)."""
        rec = make_span(name, t0_s, t1_s, [self.trace_id], lane=lane, **fields)
        self.add(rec)
        flightrec.note(
            "span", name, trace_id=self.trace_id,
            dur_s=rec["dur_s"], lane=lane,
        )
        return rec

    @contextlib.contextmanager
    def span(self, name: str, lane: Optional[int] = None, **fields):
        """Time a section on this request's trace (e.g. ``encode``)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_span(name, t0, time.monotonic(), lane=lane, **fields)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self.spans)


class ChunkTrace:
    """Shared spans for one dispatched chunk: many riders, one lane.

    The batcher builds one per chunk; ``span()`` records ONE span carrying
    every rider's trace id and appends it to every rider's context — the
    exporter then shows a coalesced batch as a single dispatch block with
    ``riders`` requests on the lane's track.
    """

    __slots__ = (
        "contexts", "lane", "trace_ids", "served_by_fallback",
        "device_busy_s",
    )

    def __init__(self, contexts: Iterable, lane: Optional[int] = None):
        self.contexts = [c for c in contexts if c is not None]
        self.lane = lane
        self.trace_ids = [c.trace_id for c in self.contexts]
        # set True by WarmExecutor._run_degraded: the chunk was answered
        # by the process-wide CPU fallback, on no lane — the batcher's
        # per-lane accounting must skip it
        self.served_by_fallback = False
        # accumulated device-busy seconds across every dispatch ATTEMPT of
        # this chunk (requeues included): WarmExecutor.run_batch adds each
        # interval; the batcher's success path prorates the total across
        # the chunk's riders into the device-time ledger (ISSUE 16)
        self.device_busy_s = 0.0

    def mark(self, name: str, **fields) -> None:
        """Flight-recorder-only marker (no span): the in-flight evidence a
        wedged dispatch leaves behind even when its span never closes."""
        flightrec.note(
            "mark", name, trace_ids=self.trace_ids, lane=self.lane, **fields
        )

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        if not self.contexts:
            yield
            return
        t0 = time.monotonic()
        flightrec.note(
            "span_begin", name, trace_ids=self.trace_ids, lane=self.lane,
            **fields,
        )
        try:
            yield
        finally:
            rec = make_span(
                name, t0, time.monotonic(), self.trace_ids, lane=self.lane,
                **fields,
            )
            for c in self.contexts:
                c.add(rec)
            flightrec.note(
                "span", name, trace_ids=self.trace_ids,
                dur_s=rec["dur_s"], lane=self.lane,
            )


class _NullTrace:
    """No-op stand-in so un-traced call paths cost nothing."""

    lane = None
    trace_ids: List[str] = []

    def mark(self, name: str, **fields) -> None:
        pass

    def span(self, name: str, **fields):
        return contextlib.nullcontext()


NULL_TRACE = _NullTrace()


# -- Chrome/Perfetto trace_event export --------------------------------------


def chrome_trace_events(serve_traces: Iterable[dict]) -> List[dict]:
    """``serve_trace`` records -> Chrome ``trace_event`` B/E pairs.

    Track layout: request-scoped spans (lane is null) ride a per-request
    track named by trace id; chunk-scoped spans ride ``lane N`` tracks —
    the view where ≥2 requests sharing one dispatch span on distinct lanes
    is visible at a glance. Shared spans are deduplicated by span id (they
    appear in every rider's record). Metadata (``ph: "M"``) events name
    the process and tracks; B/E events are globally ts-sorted.
    """
    meta, be = _process_events(list(serve_traces), 1, "nm03-serve")
    be.sort(key=lambda e: (e["ts"], 0 if e["ph"] == "E" else 1))
    return meta + be


def _process_events(
    recs: List[dict], pid: int, process_name: str, shift_s: float = 0.0
) -> tuple:
    """One process's trace records -> (metadata events, unsorted B/E list).

    The single-process exporter and the multi-log merge share this body:
    ``pid`` scopes the track table, ``shift_s`` is added to every span
    time BEFORE the µs conversion (the merge passes each stream's
    monotonic→merged-timeline offset; adding after the conversion would
    put the values past float's 0.1 µs resolution).
    """
    # trace ids are client-controlled and nothing enforces uniqueness: a
    # client retrying with the same X-Nm03-Request-Id while the original
    # is in flight yields two span trees under one id. Disambiguate those
    # request tracks by the server-side request_id so the serializing
    # cursor below never rewrites one request's times to fit another's.
    id_counts: Dict[str, int] = {}
    for rec in recs:
        tid_ = rec.get("trace_id")
        if tid_:
            id_counts[tid_] = id_counts.get(tid_, 0) + 1

    spans: List[tuple] = []  # (span, request-track override)
    seen: set = set()
    for rec in recs:
        tid_ = rec.get("trace_id")
        req_track = f"req {tid_}" if tid_ else None
        if tid_ and id_counts.get(tid_, 0) > 1 and rec.get("request_id"):
            req_track = f"req {tid_} ({rec['request_id']})"
        for sp in rec.get("spans") or []:
            sid = sp.get("id")
            if sid is None or sid in seen:
                continue
            seen.add(sid)
            spans.append((sp, req_track))

    tids: Dict[str, int] = {}
    meta: List[dict] = [
        {
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": process_name},
        }
    ]

    def tid_for(track: str) -> int:
        if track not in tids:
            tids[track] = len(tids) + 1
            meta.append(
                {
                    "ph": "M", "pid": pid, "tid": tids[track],
                    "name": "thread_name", "args": {"name": track},
                }
            )
        return tids[track]

    # group by track: within one track (one request's lifecycle spans, or
    # one lane's sequential chunk work) spans never truly overlap, but the
    # independent 0.1 µs roundings of t0 and dur can make an adjacent
    # pair's E land a hair after the next B — a serializing cursor per
    # track clamps that away so B/E stacks balance at every prefix
    by_track: Dict[str, List[dict]] = {}
    for sp, req_track in spans:
        lane = sp.get("lane")
        if lane is not None:
            track = f"lane {lane}"
        else:
            # `or`, not a .get default: a present-but-empty trace_ids list
            # (schema drift, hand-edited stream) must not crash the export
            track = req_track or f"req {(sp.get('trace_ids') or ['?'])[0]}"
        by_track.setdefault(track, []).append(sp)

    be: List[dict] = []
    # rounding tears are <= 0.2 µs (two independent 0.1 µs roundings);
    # anything past this is a genuine overlap, not an artifact
    _TEAR_EPS_US = 1.0
    for track, track_spans in by_track.items():
        # greedy interval partitioning: spans that GENUINELY overlap on one
        # track — a PR-3 retry ladder's abandoned device_dispatch attempt
        # returning late while attempt 2 runs on the same lane — keep their
        # true times on an "(overlap)" sibling track instead of being
        # cursor-clamped into a wrong start and a zero width; the cursor
        # only ever absorbs sub-µs rounding tears
        subtracks: List[list] = []  # [tid, cursor_ts] per sibling track
        for sp in sorted(track_spans, key=lambda s: float(s.get("t0_s", 0.0))):
            lane = sp.get("lane")
            t0 = float(sp.get("t0_s", 0.0)) + shift_s
            b_ts = round(t0 * 1e6, 1)
            e_ts = round((t0 + float(sp.get("dur_s", 0.0))) * 1e6, 1)
            slot = next(
                (s for s in subtracks if b_ts >= s[1] - _TEAR_EPS_US), None
            )
            if slot is None:
                n = len(subtracks)
                name = track if n == 0 else (
                    f"{track} (overlap)" if n == 1 else f"{track} (overlap {n})"
                )
                slot = [tid_for(name), b_ts]
                subtracks.append(slot)
            if b_ts < slot[1]:
                b_ts = slot[1]  # sub-µs tear
            if e_ts <= b_ts:
                e_ts = round(b_ts + 0.1, 1)  # strictly-positive width
            slot[1] = e_ts
            args = {
                "trace_ids": sp.get("trace_ids", []),
                "riders": sp.get("riders", len(sp.get("trace_ids", []))),
            }
            if lane is not None:
                args["lane"] = lane
            if "attempt" in sp:
                args["attempt"] = sp["attempt"]
            # fleet-span attribution (ISSUE 14): which replica a proxy_hop
            # went to and how it ended — the fields --expect-fleet-trace
            # joins on — plus failover causes and the probe flag
            for k in ("replica", "outcome", "cause", "probe"):
                if k in sp:
                    args[k] = sp[k]
            common = {"name": sp.get("name", "?"), "pid": pid, "tid": slot[0],
                      "cat": "serving"}
            be.append({**common, "ph": "B", "ts": b_ts, "args": args})
            be.append({**common, "ph": "E", "ts": e_ts})
    # the caller sorts B/E globally (an E at the same ts as its track's
    # next B must come first so every per-track stack prefix balances)
    return meta, be


def load_serve_traces(events_path: str) -> List[dict]:
    """The ``serve_trace`` records of one JSONL event stream (in order)."""
    return load_stream(events_path)["serve"]


def load_stream(events_path: str) -> dict:
    """Parse one JSONL event stream for the exporter.

    Returns ``{path, serve, fleet, offset_s, run_id}``: the
    ``serve_trace`` and ``fleet_trace`` records in order, plus the
    stream's monotonic→wall clock offset. Span times are
    ``time.monotonic()`` seconds of the WRITING process — meaningless
    across processes — but every event record carries both ``ts_unix``
    and ``mono_s``, so ``median(ts_unix - mono_s)`` recovers the
    process's monotonic epoch on the shared wall clock: the offset the
    multi-log merge aligns each process's spans with. (The replica
    ``/readyz`` handshake echoes the same clock pair live, so the router
    can publish per-replica offsets for skew triage; the merge derives
    its offsets from each log itself and needs no side channel.)
    Unparsable lines are skipped — a SIGKILLed replica's torn tail is
    exactly the post-mortem input this tool exists for.
    """
    serve: List[dict] = []
    fleet: List[dict] = []
    offsets: List[float] = []
    run_id = None
    with open(events_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail: a crash mid-write is exactly our use case
            if not isinstance(rec, dict):
                continue
            ts, mono = rec.get("ts_unix"), rec.get("mono_s")
            if isinstance(ts, (int, float)) and isinstance(mono, (int, float)):
                offsets.append(float(ts) - float(mono))
            if run_id is None and rec.get("run_id"):
                run_id = rec["run_id"]
            if rec.get("event") == SERVE_TRACE_EVENT:
                serve.append(rec)
            elif rec.get("event") == FLEET_TRACE_EVENT:
                fleet.append(rec)
    offsets.sort()
    offset_s = offsets[len(offsets) // 2] if offsets else 0.0
    return {
        "path": str(events_path),
        "serve": serve,
        "fleet": fleet,
        "offset_s": offset_s,
        "run_id": run_id,
    }


def _replica_process_name(stream: dict, trace_to_replica: Dict[str, str]) -> str:
    """Name one replica stream's process track.

    The replica's own log does not know its host:port — the ROUTER does
    (every ``fleet_trace`` names the answering replica) — so the join is
    by trace id: the label that answered the majority of this stream's
    trace ids names the process. Streams the router never routed to
    (direct traffic, or a replica that died before completing anything)
    fall back to the run id.
    """
    votes: Dict[str, int] = {}
    for rec in stream["serve"]:
        label = trace_to_replica.get(rec.get("trace_id"))
        if label:
            votes[label] = votes.get(label, 0) + 1
    if votes:
        return f"replica {max(votes, key=votes.get)}"
    suffix = stream["run_id"] or os.path.basename(stream["path"])
    return f"replica {suffix}"


def merged_chrome_trace_events(streams: List[dict]) -> List[dict]:
    """N event streams -> ONE multi-process Perfetto timeline (ISSUE 14).

    Each stream becomes its own process (router streams — those carrying
    ``fleet_trace`` records — first, then replicas), with every span's
    monotonic time normalized onto one shared timeline via the stream's
    own wall-clock offset (see :func:`load_stream`). The result answers
    "where did request X's 400 ms go, across which replicas" from one
    screen: the router's ``route_pick → proxy_hop → failover →
    proxy_hop`` chain sits above each replica's full span tree under the
    same trace id.
    """
    routers = [s for s in streams if s["fleet"]]
    replicas = [s for s in streams if not s["fleet"]]
    # trace id -> answering replica label, from the router's own records
    trace_to_replica: Dict[str, str] = {}
    for s in routers:
        for rec in s["fleet"]:
            if rec.get("trace_id") and rec.get("replica"):
                trace_to_replica[rec["trace_id"]] = rec["replica"]

    # one shared zero point: the earliest wall-aligned span start across
    # every stream, so ts values stay small enough for 0.1 µs arithmetic
    base = None
    for s in streams:
        for rec in s["fleet"] + s["serve"]:
            for sp in rec.get("spans") or []:
                try:
                    t = float(sp.get("t0_s", 0.0)) + s["offset_s"]
                except (TypeError, ValueError):
                    continue
                base = t if base is None else min(base, t)
    base = base or 0.0

    events: List[dict] = []
    be_all: List[dict] = []
    pid = 0
    for i, s in enumerate(routers):
        pid += 1
        name = "nm03-fleet" if len(routers) == 1 else f"nm03-fleet {i}"
        meta, be = _process_events(
            s["fleet"] + s["serve"], pid, name, shift_s=s["offset_s"] - base
        )
        events.extend(meta)
        be_all.extend(be)
    for s in replicas:
        pid += 1
        meta, be = _process_events(
            s["serve"], pid, _replica_process_name(s, trace_to_replica),
            shift_s=s["offset_s"] - base,
        )
        events.extend(meta)
        be_all.extend(be)
    be_all.sort(key=lambda e: (e["ts"], 0 if e["ph"] == "E" else 1))
    events.extend(be_all)
    return events


def export_chrome_trace(events_paths, out_path: str) -> int:
    """Write the Perfetto-loadable export; returns the request-tree count.

    ``events_paths`` is one stream path or a list of them: a single
    replica-only stream keeps the original single-process export byte
    layout; multiple streams (or any stream carrying ``fleet_trace``
    records) produce the merged multi-process timeline.
    """
    from nm03_capstone_project_tpu.utils.atomicio import atomic_write_text

    paths = (
        [events_paths] if isinstance(events_paths, (str, os.PathLike))
        else list(events_paths)
    )
    streams = [load_stream(p) for p in paths]
    n_serve = sum(len(s["serve"]) for s in streams)
    n_fleet = sum(len(s["fleet"]) for s in streams)
    if len(streams) == 1 and not n_fleet:
        trace_events = chrome_trace_events(streams[0]["serve"])
        metadata = {"source": streams[0]["path"], "requests": n_serve}
    else:
        trace_events = merged_chrome_trace_events(streams)
        metadata = {
            "sources": [s["path"] for s in streams],
            "requests": n_serve,
            "fleet_requests": n_fleet,
            "processes": len(streams),
        }
    payload = {
        "schema": SCHEMA_TRACE,
        "displayTimeUnit": "ms",
        "traceEvents": trace_events,
        "metadata": metadata,
    }
    atomic_write_text(out_path, json.dumps(payload, indent=1) + "\n")
    return n_serve + n_fleet


def main(argv=None) -> int:
    """``nm03-trace``: events JSONL -> Chrome/Perfetto trace_event JSON.

    Load the output at https://ui.perfetto.dev (or chrome://tracing). The
    triage loop is documented in docs/OPERATIONS.md ("post-mortem triage").
    """
    p = argparse.ArgumentParser(
        prog="nm03-trace", description=main.__doc__.strip().splitlines()[0]
    )
    p.add_argument(
        "events", nargs="+",
        help="JSONL event stream(s) (--log-json output). One replica "
        "stream exports the classic single-process timeline; several — "
        "the fleet router's log plus N replica logs — are stitched into "
        "ONE multi-process timeline with per-replica tracks and "
        "clock-offset-normalized times (ISSUE 14)",
    )
    p.add_argument(
        "-o", "--out", default=None,
        help="trace JSON output path (default: <first events file>"
        ".trace.json)",
    )
    args = p.parse_args(argv)
    out = args.out or f"{args.events[0]}.trace.json"
    try:
        n = export_chrome_trace(args.events, out)
    except OSError as e:
        print(f"nm03-trace: {e}", file=sys.stderr)
        return 2
    merged = f" (merged from {len(args.events)} streams)" if len(
        args.events
    ) > 1 else ""
    print(f"nm03-trace: {n} request trace(s){merged} -> {out}")
    if n == 0:
        print(
            "nm03-trace: no serve_trace records (nor fleet_trace) found — "
            "was the stream written by nm03-serve/nm03-fleet --log-json "
            "with traffic served?",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
