"""Request-scoped tracing for the serving path + the Perfetto exporter.

PR 1's spans are per-stage *aggregates* and PR 6's ``serving_lane_*``
series say how many batches each lane ran; neither can answer "where did
request X's 400 ms go". This module adds the missing attribution layer:

* every ``POST /v1/segment`` gets a **trace id** (an inbound
  ``X-Nm03-Request-Id`` header is honored after sanitization, else one is
  minted) that travels on the :class:`~..serving.queue.ServeRequest`
  through admission → coalescing → per-lane chunk dispatch → the
  supervised executor → response, and is echoed back as the
  ``X-Nm03-Request-Id`` response header so ``nm03-loadgen`` can correlate;
* each hop records a **span** (``queue_wait``, ``coalesce``, ``pad_stack``,
  ``device_dispatch`` per supervised attempt, ``fetch``, ``cpu_fallback``,
  ``encode``). Chunk-level spans are *shared*: one record carries every
  rider's trace id, which is exactly how a coalesced batch shows up as one
  dispatch block with N requests on the timeline;
* completed requests emit one ``serve_trace`` event (the span tree) into
  the ordinary JSONL event log, and every span begin/end also feeds the
  :mod:`~nm03_capstone_project_tpu.obs.flightrec` ring — an in-flight
  request's trace id is in the flight recorder *before* the dispatch that
  may wedge;
* ``nm03-trace`` (this module's :func:`main`) converts an event stream's
  ``serve_trace`` records into Chrome/Perfetto ``trace_event`` JSON (B/E
  pairs; request tracks + lane tracks), validated by
  ``scripts/check_telemetry.py --expect-trace``.

jax-free AND numpy-free at import by contract (NM301 registry pins
``obs.trace``); the exporter writes through ``atomic_write_text`` (NM371).
Schema (``nm03.trace.v1``) is documented in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import contextlib
import itertools
import json
import os
import re
import sys
import threading
import time
from typing import Dict, Iterable, List, Optional

from nm03_capstone_project_tpu.obs import flightrec

SCHEMA_TRACE = "nm03.trace.v1"
# the JSONL event (one per completed request) carrying the span tree
SERVE_TRACE_EVENT = "serve_trace"

# the serving span vocabulary (docs/OBSERVABILITY.md trace schema). The
# exporter and validator are deliberately name-agnostic (every B event
# must carry a trace id, whatever it is called); this tuple is the
# authoritative schema list, pinned by the serving e2e test — a new span
# name on the request path must be added here AND to the docs table
SERVE_SPAN_NAMES = (
    "queue_wait",       # admission -> popped by the batcher
    "coalesce",         # popped -> the batching window closed
    "pad_stack",        # chunk padded into its bucket canvas stack
    "device_dispatch",  # one supervised execute attempt on one lane
    "fetch",            # device -> host result fetch (inside the deadline)
    "requeue",          # chunk re-dispatched off a quarantined lane
    "probe",            # probation canary on a quarantined lane (off-path)
    "cpu_fallback",     # degraded-path recompute
    "encode",           # host render + JPEG encode on the handler thread
)

# client-supplied trace ids: bounded charset/length so a hostile header
# cannot smuggle log-breaking bytes into the event stream or a filename
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._:\-]{0,63}$")

_SPAN_SEQ = itertools.count(1)


def new_trace_id() -> str:
    import uuid

    return uuid.uuid4().hex[:16]


def sanitize_trace_id(raw: Optional[str]) -> Optional[str]:
    """A usable client-supplied trace id, or None (caller mints one)."""
    if not isinstance(raw, str):
        return None
    raw = raw.strip()
    return raw if _TRACE_ID_RE.match(raw) else None


def _new_span_id() -> str:
    # pid-salted: the exporter dedupes shared chunk spans by id, and a
    # concatenated event stream (two replicas' logs, or a restarted
    # server appending with ">>") must not let a second process's s1
    # collide with the first's and be silently dropped from the export
    return f"s{os.getpid():x}.{next(_SPAN_SEQ):x}"


def make_span(
    name: str,
    t0_s: float,
    t1_s: float,
    trace_ids: List[str],
    lane: Optional[int] = None,
    **fields,
) -> dict:
    """One span record (the unit both the event log and the exporter use).

    Times are ``time.monotonic()`` seconds — one process-wide timebase so
    spans from different threads line up on one timeline. ``riders`` > 1
    marks a shared (chunk-level) span: one dispatch, many requests.
    """
    rec = {
        "id": _new_span_id(),
        "name": str(name),
        "t0_s": round(t0_s, 6),
        "dur_s": round(max(t1_s - t0_s, 0.0), 6),
        "thread": threading.current_thread().name,
        "lane": lane,
        "riders": len(trace_ids),
        "trace_ids": list(trace_ids),
    }
    for k, v in fields.items():
        if k not in rec:
            rec[k] = v
    return rec


class TraceContext:
    """One request's span collection, carried on the ServeRequest.

    Appends happen from the handler, batcher, and lane-pool threads, but
    always sequenced by the request's own lifecycle handoffs (queue put,
    chunk dispatch, done-Event); the lock makes the container safe against
    a concurrent flight-recorder snapshot mid-append anyway.
    """

    __slots__ = ("trace_id", "spans", "_lock")

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or new_trace_id()
        self.spans: List[dict] = []
        self._lock = threading.Lock()

    def add(self, rec: dict) -> None:
        with self._lock:
            self.spans.append(rec)

    def add_span(
        self, name: str, t0_s: float, t1_s: float, lane: Optional[int] = None,
        **fields,
    ) -> dict:
        """Record a retrospective span (both endpoints already measured)."""
        rec = make_span(name, t0_s, t1_s, [self.trace_id], lane=lane, **fields)
        self.add(rec)
        flightrec.note(
            "span", name, trace_id=self.trace_id,
            dur_s=rec["dur_s"], lane=lane,
        )
        return rec

    @contextlib.contextmanager
    def span(self, name: str, lane: Optional[int] = None, **fields):
        """Time a section on this request's trace (e.g. ``encode``)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_span(name, t0, time.monotonic(), lane=lane, **fields)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self.spans)


class ChunkTrace:
    """Shared spans for one dispatched chunk: many riders, one lane.

    The batcher builds one per chunk; ``span()`` records ONE span carrying
    every rider's trace id and appends it to every rider's context — the
    exporter then shows a coalesced batch as a single dispatch block with
    ``riders`` requests on the lane's track.
    """

    __slots__ = ("contexts", "lane", "trace_ids", "served_by_fallback")

    def __init__(self, contexts: Iterable, lane: Optional[int] = None):
        self.contexts = [c for c in contexts if c is not None]
        self.lane = lane
        self.trace_ids = [c.trace_id for c in self.contexts]
        # set True by WarmExecutor._run_degraded: the chunk was answered
        # by the process-wide CPU fallback, on no lane — the batcher's
        # per-lane accounting must skip it
        self.served_by_fallback = False

    def mark(self, name: str, **fields) -> None:
        """Flight-recorder-only marker (no span): the in-flight evidence a
        wedged dispatch leaves behind even when its span never closes."""
        flightrec.note(
            "mark", name, trace_ids=self.trace_ids, lane=self.lane, **fields
        )

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        if not self.contexts:
            yield
            return
        t0 = time.monotonic()
        flightrec.note(
            "span_begin", name, trace_ids=self.trace_ids, lane=self.lane,
            **fields,
        )
        try:
            yield
        finally:
            rec = make_span(
                name, t0, time.monotonic(), self.trace_ids, lane=self.lane,
                **fields,
            )
            for c in self.contexts:
                c.add(rec)
            flightrec.note(
                "span", name, trace_ids=self.trace_ids,
                dur_s=rec["dur_s"], lane=self.lane,
            )


class _NullTrace:
    """No-op stand-in so un-traced call paths cost nothing."""

    lane = None
    trace_ids: List[str] = []

    def mark(self, name: str, **fields) -> None:
        pass

    def span(self, name: str, **fields):
        return contextlib.nullcontext()


NULL_TRACE = _NullTrace()


# -- Chrome/Perfetto trace_event export --------------------------------------


def chrome_trace_events(serve_traces: Iterable[dict]) -> List[dict]:
    """``serve_trace`` records -> Chrome ``trace_event`` B/E pairs.

    Track layout: request-scoped spans (lane is null) ride a per-request
    track named by trace id; chunk-scoped spans ride ``lane N`` tracks —
    the view where ≥2 requests sharing one dispatch span on distinct lanes
    is visible at a glance. Shared spans are deduplicated by span id (they
    appear in every rider's record). Metadata (``ph: "M"``) events name
    the process and tracks; B/E events are globally ts-sorted.
    """
    recs = [r for r in serve_traces]
    # trace ids are client-controlled and nothing enforces uniqueness: a
    # client retrying with the same X-Nm03-Request-Id while the original
    # is in flight yields two span trees under one id. Disambiguate those
    # request tracks by the server-side request_id so the serializing
    # cursor below never rewrites one request's times to fit another's.
    id_counts: Dict[str, int] = {}
    for rec in recs:
        tid_ = rec.get("trace_id")
        if tid_:
            id_counts[tid_] = id_counts.get(tid_, 0) + 1

    spans: List[tuple] = []  # (span, request-track override)
    seen: set = set()
    for rec in recs:
        tid_ = rec.get("trace_id")
        req_track = f"req {tid_}" if tid_ else None
        if tid_ and id_counts.get(tid_, 0) > 1 and rec.get("request_id"):
            req_track = f"req {tid_} ({rec['request_id']})"
        for sp in rec.get("spans") or []:
            sid = sp.get("id")
            if sid is None or sid in seen:
                continue
            seen.add(sid)
            spans.append((sp, req_track))

    tids: Dict[str, int] = {}
    events: List[dict] = [
        {
            "ph": "M", "pid": 1, "tid": 0, "name": "process_name",
            "args": {"name": "nm03-serve"},
        }
    ]

    def tid_for(track: str) -> int:
        if track not in tids:
            tids[track] = len(tids) + 1
            events.append(
                {
                    "ph": "M", "pid": 1, "tid": tids[track],
                    "name": "thread_name", "args": {"name": track},
                }
            )
        return tids[track]

    # group by track: within one track (one request's lifecycle spans, or
    # one lane's sequential chunk work) spans never truly overlap, but the
    # independent 0.1 µs roundings of t0 and dur can make an adjacent
    # pair's E land a hair after the next B — a serializing cursor per
    # track clamps that away so B/E stacks balance at every prefix
    by_track: Dict[str, List[dict]] = {}
    for sp, req_track in spans:
        lane = sp.get("lane")
        if lane is not None:
            track = f"lane {lane}"
        else:
            # `or`, not a .get default: a present-but-empty trace_ids list
            # (schema drift, hand-edited stream) must not crash the export
            track = req_track or f"req {(sp.get('trace_ids') or ['?'])[0]}"
        by_track.setdefault(track, []).append(sp)

    be: List[dict] = []
    # rounding tears are <= 0.2 µs (two independent 0.1 µs roundings);
    # anything past this is a genuine overlap, not an artifact
    _TEAR_EPS_US = 1.0
    for track, track_spans in by_track.items():
        # greedy interval partitioning: spans that GENUINELY overlap on one
        # track — a PR-3 retry ladder's abandoned device_dispatch attempt
        # returning late while attempt 2 runs on the same lane — keep their
        # true times on an "(overlap)" sibling track instead of being
        # cursor-clamped into a wrong start and a zero width; the cursor
        # only ever absorbs sub-µs rounding tears
        subtracks: List[list] = []  # [tid, cursor_ts] per sibling track
        for sp in sorted(track_spans, key=lambda s: float(s.get("t0_s", 0.0))):
            lane = sp.get("lane")
            b_ts = round(float(sp.get("t0_s", 0.0)) * 1e6, 1)
            e_ts = round(
                (float(sp.get("t0_s", 0.0)) + float(sp.get("dur_s", 0.0)))
                * 1e6,
                1,
            )
            slot = next(
                (s for s in subtracks if b_ts >= s[1] - _TEAR_EPS_US), None
            )
            if slot is None:
                n = len(subtracks)
                name = track if n == 0 else (
                    f"{track} (overlap)" if n == 1 else f"{track} (overlap {n})"
                )
                slot = [tid_for(name), b_ts]
                subtracks.append(slot)
            if b_ts < slot[1]:
                b_ts = slot[1]  # sub-µs tear
            if e_ts <= b_ts:
                e_ts = round(b_ts + 0.1, 1)  # strictly-positive width
            slot[1] = e_ts
            args = {
                "trace_ids": sp.get("trace_ids", []),
                "riders": sp.get("riders", len(sp.get("trace_ids", []))),
            }
            if lane is not None:
                args["lane"] = lane
            if "attempt" in sp:
                args["attempt"] = sp["attempt"]
            common = {"name": sp.get("name", "?"), "pid": 1, "tid": slot[0],
                      "cat": "serving"}
            be.append({**common, "ph": "B", "ts": b_ts, "args": args})
            be.append({**common, "ph": "E", "ts": e_ts})
    # stable global ts order; an E at the same ts as its track's next B
    # must come first so the per-track stack stays balanced at every prefix
    be.sort(key=lambda e: (e["ts"], 0 if e["ph"] == "E" else 1))
    events.extend(be)
    return events


def load_serve_traces(events_path: str) -> List[dict]:
    """The ``serve_trace`` records of one JSONL event stream (in order)."""
    out: List[dict] = []
    with open(events_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail: a crash mid-write is exactly our use case
            if isinstance(rec, dict) and rec.get("event") == SERVE_TRACE_EVENT:
                out.append(rec)
    return out


def export_chrome_trace(events_path: str, out_path: str) -> int:
    """Write the Perfetto-loadable export; returns the request count."""
    from nm03_capstone_project_tpu.utils.atomicio import atomic_write_text

    traces = load_serve_traces(events_path)
    payload = {
        "schema": SCHEMA_TRACE,
        "displayTimeUnit": "ms",
        "traceEvents": chrome_trace_events(traces),
        "metadata": {
            "source": events_path,
            "requests": len(traces),
        },
    }
    atomic_write_text(out_path, json.dumps(payload, indent=1) + "\n")
    return len(traces)


def main(argv=None) -> int:
    """``nm03-trace``: events JSONL -> Chrome/Perfetto trace_event JSON.

    Load the output at https://ui.perfetto.dev (or chrome://tracing). The
    triage loop is documented in docs/OPERATIONS.md ("post-mortem triage").
    """
    p = argparse.ArgumentParser(
        prog="nm03-trace", description=main.__doc__.strip().splitlines()[0]
    )
    p.add_argument("events", help="JSONL event stream (--log-json output)")
    p.add_argument(
        "-o", "--out", default=None,
        help="trace JSON output path (default: <events>.trace.json)",
    )
    args = p.parse_args(argv)
    out = args.out or f"{args.events}.trace.json"
    try:
        n = export_chrome_trace(args.events, out)
    except OSError as e:
        print(f"nm03-trace: {e}", file=sys.stderr)
        return 2
    print(f"nm03-trace: {n} request trace(s) -> {out}")
    if n == 0:
        print(
            "nm03-trace: no serve_trace records found — was the stream "
            "written by nm03-serve --log-json with traffic served?",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
