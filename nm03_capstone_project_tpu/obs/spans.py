"""Span API: nested, named sections with device sync and stage histograms.

Supersedes ``utils.timing.Timer`` (which is now an alias of
:class:`SpanRecorder` for backward compatibility): the same re-entrant
wall-clock accumulation and optional pytree sync, plus

* ``jax.profiler.TraceAnnotation`` emission so every span shows on the
  TensorBoard/Perfetto timeline captured by ``--profile-dir``;
* per-stage latency **histograms** fed into a
  :class:`~nm03_capstone_project_tpu.obs.metrics.MetricsRegistry` under
  ``nm03_stage_latency_seconds{stage=...}`` — the stage-level performance
  attribution the results JSON's flat per-section sums cannot carry
  (distributions, not just totals);
* a per-thread nesting stack, so ``span("compute")`` inside
  ``span("patient")`` records the child's latency under its own stage while
  the parent keeps accumulating the enclosing wall.

Stage-label cardinality stays bounded even for per-patient section names:
the histogram label is the FIRST ``/``-component of the span name (the
volume driver times ``load/<patient>`` per patient; all of those feed one
``stage="load"`` histogram while ``report()`` keeps the per-patient keys).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

# canonical name home is obs.metrics (NM392); re-exported here because the
# span API is where every caller historically imported it from
from nm03_capstone_project_tpu.obs.metrics import (  # noqa: F401
    STAGE_LATENCY_METRIC,
)


def _annotation(name: str):
    """jax.profiler.TraceAnnotation, or a no-op where jax is not LOADED.

    Deliberately keyed on ``sys.modules``, not importability: a process
    that hasn't imported jax has no profiler to annotate, and importing it
    here would both charge jax's multi-second import to the first span and
    violate the bench orchestrator's never-imports-jax invariant.
    """
    import sys

    if "jax" not in sys.modules:
        return contextlib.nullcontext()
    try:
        from nm03_capstone_project_tpu.utils.profiling import annotate

        return annotate(name)
    except Exception:  # noqa: BLE001 — observability must never break a run
        return contextlib.nullcontext()


class SpanRecorder:
    """Named wall-clock sections; re-entrant accumulation + histograms.

    Drop-in superset of the old ``Timer``: ``section(name, tree=None)``,
    ``sections``/``counts`` dicts, and ``report()`` behave identically.
    """

    def __init__(self, registry=None, histogram_name: str = STAGE_LATENCY_METRIC):
        self.sections: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self.registry = registry
        self.histogram_name = histogram_name
        self._lock = threading.RLock()  # signal-handler reentrancy
        self._local = threading.local()

    # -- nesting introspection (per-thread) --------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @property
    def depth(self) -> int:
        """Current nesting depth on the calling thread."""
        return len(self._stack())

    def current_path(self) -> str:
        """``outer/inner`` span path on the calling thread ('' at top level)."""
        return "/".join(self._stack())

    # -- the span context ---------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, tree=None, stage: Optional[str] = None):
        """Time a named section.

        Args:
          name: section key accumulated in ``sections``/``report()``; may
            carry a ``/``-suffix for per-item detail (``load/<patient>``).
          tree: optional pytree synced (``timing.sync``) before the clock
            stops, so device work enqueued inside the span is charged to it.
          stage: histogram ``stage`` label override; defaults to the first
            ``/``-component of ``name`` (bounded cardinality).
        """
        stack = self._stack()
        stack.append(name)
        t0 = time.perf_counter()
        try:
            with _annotation(name):  # stage shows up on the profiler timeline
                yield
        finally:
            # a failing device sync must still pop the nesting stack and
            # record the section (the old Timer had no stack to corrupt;
            # this one must not leave phantom nesting behind a raise)
            try:
                if tree is not None:
                    from nm03_capstone_project_tpu.utils.timing import sync

                    sync(tree)
            finally:
                dt = time.perf_counter() - t0
                stack.pop()
                with self._lock:
                    self.sections[name] = self.sections.get(name, 0.0) + dt
                    self.counts[name] = self.counts.get(name, 0) + 1
                if self.registry is not None:
                    label = stage if stage is not None else name.split("/", 1)[0]
                    self.registry.histogram(
                        self.histogram_name,
                        help="wall-clock latency per pipeline stage "
                        "(device-synced where the span passed a tree)",
                        stage=label,
                    ).observe(dt)

    # Timer-compat alias: every existing `timer.section(...)` call site and
    # test keeps working against the span API.
    section = span

    def report(self) -> Dict[str, float]:
        with self._lock:
            return dict(sorted(self.sections.items()))
