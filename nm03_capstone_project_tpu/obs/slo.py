"""SLO plane: declared objectives, multi-window burn rates, error budget.

PR 7/10/13 built the measurement wall — request latency histograms,
outcome counters, saturation gauges, a fleet router with its own routed/
failover/shed accounting — but nobody computed whether the service is
actually MEETING an objective (ISSUE 14). This module adds the yes/no:

* an :class:`SLOObjective` declares what "meeting it" means — an
  availability percentage (the fraction of requests that must terminate
  ok) and optionally a latency target at a percentile (``p99 <= 500ms``:
  at most 1% of requests may exceed 500 ms);
* an :class:`SLOMonitor` computes **multi-window burn rates** from the
  registry's EXISTING request series (``serving_requests_total`` +
  ``serving_request_seconds`` on a replica, ``fleet_requests_total`` +
  ``fleet_request_seconds`` on the router) — no second instrumentation
  path that could disagree with the metrics wall. A burn rate of 1.0
  means the service is consuming error budget exactly as fast as the
  objective allows; the classic paging pair is a FAST window (default
  5 m — "we are on fire now") and a SLOW window (default 1 h — "this is
  sustained, not a blip");
* three gauges per process carry the verdict: ``slo_burn_rate_fast``,
  ``slo_burn_rate_slow``, ``slo_error_budget_remaining`` (1.0 = the
  whole budget intact, 0.0 = spent, negative = blown), plus an
  ``slo_objective_info`` info-gauge whose labels name the declared
  objective so a scrape is self-describing.

The monitor is **pull-refreshed** exactly like the saturation layer: a
``publish()`` on every ``/metrics``/``/metrics.json``/``/readyz`` hit
samples the cumulative series and re-derives the window deltas, and the
drain publishes once more so ``--metrics-out`` carries the final
verdict. Probe traffic is excluded by construction — the router's
canaries land under ``status="probe"`` (ISSUE 14 satellite), a status
class neither the good nor the bad set contains.

jax-free AND numpy-free at import by contract (NM301 pins ``obs``); all
shared state is lock-guarded (NM331 scans the module). Gauge names are
owned by :mod:`~nm03_capstone_project_tpu.obs.metrics` (NM392 keeps the
docs/OBSERVABILITY.md table in lockstep).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

from nm03_capstone_project_tpu.obs.metrics import (
    SLO_BURN_RATE_FAST,
    SLO_BURN_RATE_SLOW,
    SLO_ERROR_BUDGET_REMAINING,
    SLO_OBJECTIVE_INFO,
)

DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0

# replica-side status classes (serving_requests_total{status}): `shed`
# counts against availability (the client got a 503), `invalid` does not
# (a malformed body is the client's unavailability, not ours), `probe`
# never counts anywhere (the canary-exclusion satellite)
GOOD_STATUSES = ("ok",)
BAD_STATUSES = ("error", "timeout", "shed")


class SLOObjective:
    """One declared service-level objective.

    ``availability_pct`` is the fraction of requests that must terminate
    ok (99.5 = at most 0.5% may fail). ``latency_target_s`` (optional)
    declares a latency SLI at ``latency_pct`` (default 99.0): at most
    ``100 - latency_pct`` percent of requests may exceed the target.
    Pick targets on latency-histogram bucket bounds — the monitor reads
    slow counts from the cumulative buckets, so a target between bounds
    is effectively rounded UP to the next bound (documented, not hidden).
    """

    __slots__ = (
        "availability_pct", "latency_target_s", "latency_pct",
        "window_fast_s", "window_slow_s",
    )

    def __init__(
        self,
        availability_pct: float = 99.0,
        latency_target_s: Optional[float] = None,
        latency_pct: float = 99.0,
        window_fast_s: float = DEFAULT_FAST_WINDOW_S,
        window_slow_s: float = DEFAULT_SLOW_WINDOW_S,
    ):
        if not 0.0 < float(availability_pct) < 100.0:
            raise ValueError(
                f"availability_pct must be in (0, 100), got {availability_pct}"
            )
        if latency_target_s is not None and float(latency_target_s) <= 0:
            raise ValueError(
                f"latency_target_s must be positive, got {latency_target_s}"
            )
        if not 0.0 < float(latency_pct) < 100.0:
            raise ValueError(
                f"latency_pct must be in (0, 100), got {latency_pct}"
            )
        if float(window_fast_s) <= 0 or float(window_slow_s) <= 0:
            raise ValueError("SLO windows must be positive")
        if float(window_fast_s) > float(window_slow_s):
            raise ValueError(
                f"fast window ({window_fast_s}s) must not exceed the slow "
                f"window ({window_slow_s}s)"
            )
        self.availability_pct = float(availability_pct)
        self.latency_target_s = (
            float(latency_target_s) if latency_target_s is not None else None
        )
        self.latency_pct = float(latency_pct)
        self.window_fast_s = float(window_fast_s)
        self.window_slow_s = float(window_slow_s)

    @property
    def availability_budget(self) -> float:
        """The allowed bad fraction (99.5% objective -> 0.005)."""
        return (100.0 - self.availability_pct) / 100.0

    @property
    def latency_budget(self) -> float:
        """The allowed slow fraction (p99 target -> 0.01)."""
        return (100.0 - self.latency_pct) / 100.0

    def describe(self) -> dict:
        return {
            "availability_pct": self.availability_pct,
            "latency_target_ms": (
                round(self.latency_target_s * 1e3, 3)
                if self.latency_target_s is not None else None
            ),
            "latency_pct": self.latency_pct,
            "window_fast_s": self.window_fast_s,
            "window_slow_s": self.window_slow_s,
        }


class _Totals:
    """One cumulative reading: good/bad requests, slow/total latencies."""

    __slots__ = ("t", "good", "bad", "slow", "lat_total")

    def __init__(self, t, good, bad, slow, lat_total):
        self.t = t
        self.good = good
        self.bad = bad
        self.slow = slow
        self.lat_total = lat_total


class SLOMonitor:
    """Burn-rate/budget computation over one process's request series.

    Reads the registry the process already maintains — it never counts
    requests itself, so the SLO verdict and the metrics wall cannot
    disagree. ``publish()`` appends one cumulative sample to a bounded
    ring and re-derives:

    * per window W (fast/slow): the burn rate over the delta between the
      newest sample and the best baseline sample ~W ago — the maximum of
      the availability burn (``bad_fraction / availability_budget``) and
      the latency burn (``slow_fraction / latency_budget``). No traffic
      in the window = burn 0.0 (nothing burned, nothing served);
    * the error budget remaining since monitor start: ``1 - consumed``
      where consumed is the worst SLI's cumulative bad share against its
      budget (negative = the objective is already blown for this run).

    Early in the process the windows are shorter than declared (a 30 s
    old process has 30 s of history); the baseline then is the oldest
    sample — the honest "burn since start".
    """

    def __init__(
        self,
        registry,
        objective: SLOObjective,
        requests_counter: str,
        latency_histogram: str,
        good_statuses: Sequence[str] = GOOD_STATUSES,
        bad_statuses: Sequence[str] = BAD_STATUSES,
        status_label: str = "status",
        clock: Callable[[], float] = time.monotonic,
    ):
        self.registry = registry
        self.objective = objective
        self.requests_counter = str(requests_counter)
        self.latency_histogram = str(latency_histogram)
        self.good_statuses = frozenset(good_statuses)
        self.bad_statuses = frozenset(bad_statuses)
        self.status_label = str(status_label)
        self._clock = clock
        self._lock = threading.Lock()
        # the t0 baseline is held OUTSIDE the window ring: the budget
        # computation needs the true first reading forever, and a bounded
        # ring under a fast scraper would silently evict it
        self._first = self._read()
        # bounded sample ring for the window baselines: age-evicted past
        # the slow window, maxlen a backstop against a pathological
        # scrape storm (a dropped old sample only coarsens a baseline)
        self._samples: deque = deque(maxlen=8192)
        self._samples.append(self._first)
        self._last_block: Optional[dict] = None
        # the gauges exist from construction on (budget intact, nothing
        # burning), so "never computed" is distinguishable from absent
        self._gauge(SLO_ERROR_BUDGET_REMAINING,
                    "fraction of the declared error budget left for this "
                    "process's lifetime (1 = intact, <=0 = blown)").set(1.0)
        self._gauge(SLO_BURN_RATE_FAST,
                    "error-budget burn rate over the fast window (1.0 = "
                    "burning exactly at the objective's allowed rate)").set(0.0)
        self._gauge(SLO_BURN_RATE_SLOW,
                    "error-budget burn rate over the slow window").set(0.0)
        d = objective.describe()
        self.registry.gauge(
            SLO_OBJECTIVE_INFO,
            help="the declared SLO (value is always 1; the labels carry "
            "the objective)",
            availability_pct=str(d["availability_pct"]),
            latency_target_ms=str(d["latency_target_ms"]),
            latency_pct=str(d["latency_pct"]),
            window_fast_s=str(int(d["window_fast_s"])),
            window_slow_s=str(int(d["window_slow_s"])),
        ).set(1)

    def _gauge(self, name: str, help: str):
        return self.registry.gauge(name, help=help)

    # -- cumulative reads --------------------------------------------------

    def _read(self) -> _Totals:
        """One cumulative reading of the request series, registry truth."""
        good = bad = 0.0
        for m in self.registry.series():
            if m.kind != "counter" or m.name != self.requests_counter:
                continue
            status = m.labels.get(self.status_label)
            if status in self.good_statuses:
                good += m.value
            elif status in self.bad_statuses:
                bad += m.value
            # anything else (invalid, probe, future classes) is neither
        slow = lat_total = 0
        target = self.objective.latency_target_s
        for m in self.registry.series():
            if m.kind != "histogram" or m.name != self.latency_histogram:
                continue
            cum = m.cumulative()
            total = cum[-1][1] if cum else 0
            lat_total += total
            if target is None:
                continue
            # the smallest bound >= target: requests above it are slow.
            # A target between bounds therefore rounds UP to the next
            # bound (conservative toward "fast"); a target past every
            # finite bound cannot be measured and counts nothing slow.
            at_bound = None
            for le, c in cum:
                if le == "+Inf":
                    continue
                if float(le) >= target:
                    at_bound = c
                    break
            if at_bound is not None:
                slow += total - at_bound
        return _Totals(self._clock(), good, bad, slow, lat_total)

    # -- burn math ---------------------------------------------------------

    def _baseline(self, now: float, window_s: float) -> _Totals:
        """The newest sample at least ``window_s`` old (else the oldest)."""
        base = self._samples[0]
        for s in self._samples:
            if s.t <= now - window_s:
                base = s
            else:
                break
        return base

    def _burn(self, cur: _Totals, base: _Totals) -> float:
        burns = [0.0]
        d_req = (cur.good - base.good) + (cur.bad - base.bad)
        if d_req > 0:
            bad_frac = max(cur.bad - base.bad, 0.0) / d_req
            burns.append(bad_frac / self.objective.availability_budget)
        if self.objective.latency_target_s is not None:
            d_lat = cur.lat_total - base.lat_total
            if d_lat > 0:
                slow_frac = max(cur.slow - base.slow, 0.0) / d_lat
                burns.append(slow_frac / self.objective.latency_budget)
        return max(burns)

    def _budget_remaining(self, cur: _Totals) -> float:
        """1 - the worst SLI's cumulative budget consumption since start."""
        first = self._first
        consumed = [0.0]
        total_req = (cur.good - first.good) + (cur.bad - first.bad)
        if total_req > 0:
            allowed = self.objective.availability_budget * total_req
            consumed.append((cur.bad - first.bad) / allowed)
        if self.objective.latency_target_s is not None:
            total_lat = cur.lat_total - first.lat_total
            if total_lat > 0:
                allowed = self.objective.latency_budget * total_lat
                consumed.append((cur.slow - first.slow) / allowed)
        return 1.0 - max(consumed)

    # -- the pull-refresh entry point --------------------------------------

    def publish(self) -> dict:
        """Sample, recompute, refresh the gauges; returns the SLO block.

        Called on every scrape/readyz probe and once at drain (the same
        cadence contract the saturation monitor follows).
        """
        with self._lock:
            cur = self._read()
            self._samples.append(cur)
            # age-evict past the slow window (+25% slack): the ring only
            # needs to reach one slow-window baseline back
            horizon = cur.t - self.objective.window_slow_s * 1.25
            while len(self._samples) > 2 and self._samples[0].t < horizon:
                self._samples.popleft()
            fast = self._burn(cur, self._baseline(cur.t,
                                                  self.objective.window_fast_s))
            slow = self._burn(cur, self._baseline(cur.t,
                                                  self.objective.window_slow_s))
            remaining = self._budget_remaining(cur)
        self._gauge(SLO_BURN_RATE_FAST, "").set(round(fast, 6))
        self._gauge(SLO_BURN_RATE_SLOW, "").set(round(slow, 6))
        self._gauge(SLO_ERROR_BUDGET_REMAINING, "").set(round(remaining, 6))
        block = {
            "objective": self.objective.describe(),
            "burn_rate_fast": round(fast, 6),
            "burn_rate_slow": round(slow, 6),
            "error_budget_remaining": round(remaining, 6),
        }
        with self._lock:
            self._last_block = block
        return block

    def last_block(self) -> dict:
        """The most recent ``publish()`` result (publishing once if the
        monitor never has) — for payload builders whose caller already
        refreshed the gauges this scrape, so one probe samples once."""
        with self._lock:
            block = self._last_block
        return block if block is not None else self.publish()


def objective_from_args(args) -> Optional[SLOObjective]:
    """The CLI wiring shared by ``nm03-serve`` and ``nm03-fleet serve``.

    Returns None (no SLO plane) unless at least one objective flag was
    given; a latency target without an availability flag uses the 99.0
    default availability.
    """
    availability = getattr(args, "slo_availability", None)
    p99_ms = getattr(args, "slo_p99_ms", None)
    if availability is None and p99_ms is None:
        return None
    fast = getattr(args, "slo_fast_window_s", None)
    slow = getattr(args, "slo_slow_window_s", None)
    # explicit None checks, not `or`: a (bogus) --slo-fast-window-s 0
    # must reach SLOObjective's "windows must be positive" error, never
    # be silently swallowed into the default
    return SLOObjective(
        availability_pct=availability if availability is not None else 99.0,
        latency_target_s=(p99_ms / 1e3) if p99_ms is not None else None,
        window_fast_s=DEFAULT_FAST_WINDOW_S if fast is None else fast,
        window_slow_s=DEFAULT_SLOW_WINDOW_S if slow is None else slow,
    )


def add_slo_args(parser_group) -> None:
    """The shared ``--slo-*`` flag set (docs/OBSERVABILITY.md, SLO plane)."""
    parser_group.add_argument(
        "--slo-availability", type=float, default=None, metavar="PCT",
        help="declare an availability objective (e.g. 99.5 = at most 0.5%% "
        "of requests may fail); enables the slo_* gauges",
    )
    parser_group.add_argument(
        "--slo-p99-ms", type=float, default=None, metavar="MS",
        help="declare a p99 latency target in milliseconds (at most 1%% of "
        "requests may exceed it); pick a value on a latency-histogram "
        "bucket bound — in-between targets round up to the next bound",
    )
    parser_group.add_argument(
        "--slo-fast-window-s", type=float, default=None, metavar="S",
        help=f"fast burn-rate window (default {DEFAULT_FAST_WINDOW_S:.0f}s "
        "— the 'on fire now' pager window)",
    )
    parser_group.add_argument(
        "--slo-slow-window-s", type=float, default=None, metavar="S",
        help=f"slow burn-rate window (default {DEFAULT_SLOW_WINDOW_S:.0f}s "
        "— the 'sustained, not a blip' window)",
    )


__all__ = [
    "DEFAULT_FAST_WINDOW_S",
    "DEFAULT_SLOW_WINDOW_S",
    "SLOMonitor",
    "SLOObjective",
    "add_slo_args",
    "objective_from_args",
]
