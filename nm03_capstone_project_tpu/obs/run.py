"""RunContext: the one object a driver wires observability through.

Ties together the metrics registry, the span recorder, and the structured
event log for one run, and owns the per-patient outcome protocol:

* exactly ONE terminal ``patient_outcome`` event per patient (a second
  emission for the same patient is a programming error and raises);
* ``grow_truncated`` WARNING events + the ``pipeline_grow_truncated_total``
  counter for patients whose region-growing fixpoint hit its iteration cap
  (the ``grow_converged`` flag the pipeline returns and drivers previously
  under-surfaced);
* the ``run_started`` / ``run_finished`` envelope and an optional periodic
  heartbeat.

Drivers construct one with :meth:`RunContext.create` (``--metrics-out``,
``--log-json``, ``--heartbeat-s``); library callers get a sink-less context
by default — metrics still accumulate in memory, events are recorded in the
in-memory tail, nothing touches disk.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from nm03_capstone_project_tpu.obs.events import EventLog, Heartbeat, LogBridge

# canonical metric names live in obs.metrics (the NM392-gated name home);
# re-exported here because every driver imports them from this module
from nm03_capstone_project_tpu.obs.metrics import (  # noqa: F401
    GROW_TRUNCATED_TOTAL,
    HEARTBEATS_TOTAL,
    MEDIAN_COMPARATOR_OPS,
    PATIENT_OUTCOMES_TOTAL,
    PIPELINE_DEGRADED_TOTAL,
    PIPELINE_PATH_INFO,
    RESILIENCE_FAULTS_INJECTED_TOTAL,
    RESILIENCE_RETRIES_TOTAL,
    SLICES_TOTAL,
    MetricsRegistry,
)
from nm03_capstone_project_tpu.obs.spans import SpanRecorder

PATIENT_STATUSES = ("ok", "failed")


class RunContext:
    """Shared observability state for one driver run."""

    def __init__(
        self,
        driver: str,
        registry: MetricsRegistry,
        events: EventLog,
        spans: SpanRecorder,
        metrics_out=None,
        heartbeat: Optional[Heartbeat] = None,
        log_bridge: Optional[LogBridge] = None,
    ):
        self.driver = driver
        self.registry = registry
        self.events = events
        self.spans = spans
        self.metrics_out = metrics_out
        self._heartbeat = heartbeat
        self._log_bridge = log_bridge
        self._lock = threading.RLock()  # signal-handler reentrancy
        self._outcomes: Dict[str, str] = {}
        self._closed = False

    # -- construction ------------------------------------------------------

    @classmethod
    def create(
        cls,
        driver: str,
        metrics_out=None,
        log_json=None,
        heartbeat_s: float = 0.0,
        run_id: Optional[str] = None,
        argv=None,
        stream=None,
    ) -> "RunContext":
        """Build + start a context; emits ``run_started``.

        ``metrics_out``/``log_json`` are paths (or None); ``stream`` is an
        alternative writable for the event log (tests). A positive
        ``heartbeat_s`` starts the heartbeat thread only when the event log
        has a sink — a sink-less heartbeat would be pure overhead.
        """
        events = EventLog(path=log_json, stream=stream, run_id=run_id)
        registry = MetricsRegistry()
        spans = SpanRecorder(registry=registry)
        heartbeat = None
        if heartbeat_s and heartbeat_s > 0 and events.enabled:
            heartbeat = Heartbeat(events, heartbeat_s, registry=registry).start()
        log_bridge = None
        if events.enabled:
            # mirror the package logger's WARNING+ into the event stream so
            # per-slice containment messages become structured records
            import logging

            from nm03_capstone_project_tpu.utils.reporter import get_logger

            log_bridge = LogBridge(events, level=logging.WARNING)
            get_logger().addHandler(log_bridge)
        ctx = cls(
            driver,
            registry,
            events,
            spans,
            metrics_out=metrics_out,
            heartbeat=heartbeat,
            log_bridge=log_bridge,
        )
        started = {"driver": driver}
        if argv is not None:
            started["argv"] = list(argv)
        events.emit("run_started", **started)
        return ctx

    # -- per-patient telemetry ---------------------------------------------

    def patient_outcome(
        self,
        patient_id: str,
        status: str,
        *,
        slices_total: int = 0,
        slices_ok: int = 0,
        slices_failed: int = 0,
        slices_truncated: int = 0,
        grow_truncated: Optional[bool] = None,
        error_class: Optional[str] = None,
        retries: int = 0,
        **fields,
    ) -> dict:
        """The ONE terminal record of a patient's run.

        Increments the outcome counters and emits the ``patient_outcome``
        event (WARNING when the patient failed or its mask was truncated,
        INFO otherwise). Raises on a duplicate emission for the same
        patient — the schema's exactly-once contract is enforced at the
        source, not just in the validator.
        """
        if status not in PATIENT_STATUSES:
            raise ValueError(f"status {status!r} not in {PATIENT_STATUSES}")
        pid = str(patient_id)
        with self._lock:
            if pid in self._outcomes:
                raise RuntimeError(
                    f"duplicate patient_outcome for {pid!r} "
                    f"(already {self._outcomes[pid]!r})"
                )
            self._outcomes[pid] = status
        if grow_truncated is None:
            grow_truncated = slices_truncated > 0
        self.registry.counter(
            PATIENT_OUTCOMES_TOTAL,
            help="terminal patient outcomes by status",
            status=status,
        ).inc()
        for n, slice_status in (
            (slices_ok, "done"),
            (slices_failed, "failed"),
            (slices_truncated, "truncated"),
        ):
            if n:
                self.registry.counter(
                    SLICES_TOTAL,
                    help="slices by terminal status (truncated slices are "
                    "also counted done: the pair exists)",
                    status=slice_status,
                ).inc(n)
        level = "WARNING" if (status != "ok" or grow_truncated) else "INFO"
        return self.events.emit(
            "patient_outcome",
            level=level,
            patient_id=pid,
            status=status,
            slices_total=int(slices_total),
            slices_ok=int(slices_ok),
            slices_failed=int(slices_failed),
            slices_truncated=int(slices_truncated),
            grow_truncated=bool(grow_truncated),
            error_class=error_class,
            retries=int(retries),
            **fields,
        )

    def has_outcome(self, patient_id: str) -> bool:
        """True when a terminal outcome was already recorded — exception
        handlers use this so a failure AFTER the ok-outcome emission cannot
        trip the exactly-once guard from inside the containment path."""
        with self._lock:
            return str(patient_id) in self._outcomes

    def grow_truncated(self, patient_id: str, count: int = 1, **fields) -> dict:
        """Surface a capped region-growing fixpoint: WARNING event + counter.

        ``count`` is the number of truncated work items — slices in the 2D
        drivers, 1 (the whole volume) in the volume driver.
        """
        self.registry.counter(
            GROW_TRUNCATED_TOTAL,
            help="region-growing fixpoints that hit the iteration cap "
            "(masks under-cover; raise --grow-max-iters)",
        ).inc(count)
        return self.events.emit(
            "grow_truncated",
            level="WARNING",
            patient_id=str(patient_id),
            count=int(count),
            **fields,
        )

    # -- resilience telemetry ----------------------------------------------

    def retry(self, cause: str, attempt: int = 1, **fields) -> dict:
        """One supervised retry: counter (per-cause label) + INFO event."""
        self.registry.counter(
            RESILIENCE_RETRIES_TOTAL,
            help="supervised retries by cause (resilience.RetryPolicy)",
            cause=str(cause),
        ).inc()
        return self.events.emit(
            "retry", cause=str(cause), attempt=int(attempt), **fields
        )

    def fault_injected(self, site: str, kind: str, **fields) -> dict:
        """One fired fault-plan rule: counter (site/kind labels) + event."""
        self.registry.counter(
            RESILIENCE_FAULTS_INJECTED_TOTAL,
            help="faults fired by the seeded fault plan "
            "(resilience.FaultPlan; zero outside chaos runs)",
            site=str(site),
            kind=str(kind),
        ).inc()
        return self.events.emit(
            "fault_injected", site=str(site), kind=str(kind), **fields
        )

    def degraded(self, cause: str, **fields) -> dict:
        """The run flipped to its degraded (CPU-fallback) path: WARNING
        event + ``pipeline_degraded_total`` counter. Emitted once per
        degradation transition, not per fallback batch."""
        self.registry.counter(
            PIPELINE_DEGRADED_TOTAL,
            help="degradation transitions (dispatch deadline expiry or "
            "device lost; the run finished on the CPU fallback)",
            cause=str(cause),
        ).inc()
        return self.events.emit(
            "degraded", level="WARNING", cause=str(cause), **fields
        )

    # the comparator_counts() keys that are actually op counts — "window"
    # is the kernel size and must not be emitted under an ops-gauge name
    _COMPARATOR_COUNT_KEYS = (
        "merge_minmax_full",
        "merge_minmax_pruned",
        "merge_minmax_pruned_shared",
        "presort_minmax",
    )

    def record_pipeline_paths(
        self,
        median_impl: str,
        render_fused: bool,
        fuse_preprocess: bool,
        use_pallas: bool,
        comparators: Optional[dict] = None,
        **extra_labels: str,
    ) -> None:
        """Make the metrics snapshot self-describing about which median /
        render implementation the run ACTUALLY used (ISSUE 2 satellite):
        an info-style gauge whose labels carry the paths, plus the
        median's comparator counts when the caller supplies them
        (pure-Python data from ops.selection_network — this module stays
        jax-free). The single owner of these series: the CLI drivers and
        bench.py both emit through here so the label contract cannot
        drift.

        ``use_pallas`` must already be resolved against the real backend
        (a --use-pallas request silently degrades off-TPU). When the
        fused Pallas preprocess runs, it always executes the shared
        pruned plan — ``median_impl`` is not consulted — so the label is
        overridden accordingly rather than attributing the run to an
        implementation that never executed. ``extra_labels`` lets callers
        add context (bench: ``winning_path``).
        """
        if use_pallas:
            # both the fused preprocess kernel and the standalone band
            # kernel run the shared pruned plan; median_impl only selects
            # among the XLA implementations
            median_impl = "pallas_shared_pruned"
        self.registry.gauge(
            PIPELINE_PATH_INFO,
            help="pipeline implementation choices for this run (value is "
            "always 1; the labels carry the information)",
            median_impl=str(median_impl),
            render="fused" if render_fused else "unfused",
            preprocess="fused_pallas" if (use_pallas and fuse_preprocess) else "xla",
            use_pallas=str(bool(use_pallas)).lower(),
            **{k: str(v) for k, v in extra_labels.items()},
        ).set(1)
        for key in self._COMPARATOR_COUNT_KEYS:
            if key in (comparators or {}):
                self.registry.gauge(
                    MEDIAN_COMPARATOR_OPS,
                    help="min/max ops per pixel of the median merge phase by "
                    "network variant (ops.selection_network)",
                    variant=key,
                ).set(float(comparators[key]))

    # -- export / teardown -------------------------------------------------

    def metrics_snapshot(self) -> dict:
        return self.registry.snapshot(
            run_id=self.events.run_id, git_sha=self.events.git_sha
        )

    def write_metrics(self, path=None) -> None:
        path = path or self.metrics_out
        if path:
            self.registry.write_snapshot(
                path, run_id=self.events.run_id, git_sha=self.events.git_sha
            )

    def close(self, status: str = "ok", **fields) -> None:
        """Stop the heartbeat, write the metrics snapshot, emit the final
        ``run_finished`` record (always the stream's last), close the log.
        Idempotent — drivers call it from ``finally``."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if self._log_bridge is not None:
            from nm03_capstone_project_tpu.utils.reporter import get_logger

            get_logger().removeHandler(self._log_bridge)
        try:
            self.write_metrics()
        except Exception as e:  # noqa: BLE001 — telemetry never costs the run
            # an unwritable --metrics-out (read-only dir, full disk) must not
            # turn a successful run into exit 1 at the very end
            import sys

            print(
                f"warning: metrics snapshot write failed: {e}", file=sys.stderr
            )
        finally:
            self.events.emit("run_finished", status=status, **fields)
            self.events.close()

    def __enter__(self) -> "RunContext":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(status="error" if exc_type else "ok")
