"""Saturation & goodput telemetry: how much of the hardware a run used.

Every layer below this one reports *that* it worked — requests served,
batches dispatched, retries survived. None of it can show a chip sitting
idle, a window padded to waste, or a driver stalled on I/O, which is
exactly the blind spot the reference paper's speedup-only evidence chain
has (and the VSIPL/OpenMP study, PAPERS.md, shows conceals feed/compute
imbalance). This module is the missing *efficiency* layer (ISSUE 10):

* :class:`SaturationMonitor` — serving-side accounting fed by the
  executor's dispatch intervals and the batcher's chunk/window geometry,
  computed over a lock-guarded bounded sliding time window:
  per-lane busy/idle fractions + idle-gap histogram, padding waste
  (real vs dead rows), window occupancy vs fleet capacity, per-bucket
  fill, and MFU (achieved flops rate ÷ a per-platform peak table);
* :class:`PhaseAccountant` — driver-side busy-interval accounting for the
  serial decode→stage→dispatch→fetch feed, producing the ``feed_stall``
  report (fraction of wall the device sat starved) that ROADMAP item 3's
  streaming-ingest work must erase — measured *before* it is built;
* :func:`peak_flops_for` — the roofline denominators: real per-chip
  numbers for known TPU generations, a documented order-of-magnitude
  estimate for CPU hosts (MFU on CPU is a trend line, not a claim).

jax-free AND numpy-free at import by the obs package contract (NM301);
thread-shared state is lock-guarded (NM331 — this module is in the rule's
scanned scope). Metric names live in :mod:`.metrics` so the NM392
metrics↔docs gate covers them.
"""

from __future__ import annotations

import bisect
import collections
import contextlib
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from nm03_capstone_project_tpu.obs.metrics import (
    SERVING_BATCH_ROWS_TOTAL,
    SERVING_BUCKET_FILL_RATIO,
    SERVING_BUSY_FRACTION,
    SERVING_LANE_BUSY_FRACTION,
    SERVING_LANE_IDLE_GAP_SECONDS,
    SERVING_LANE_MFU,
    SERVING_LANE_PEAK_FLOPS,
    SERVING_MFU,
    SERVING_PADDING_WASTE_RATIO,
    SERVING_WINDOW_OCCUPANCY_RATIO,
)

# how far back the efficiency window looks: long enough to smooth batching
# jitter, short enough that a quarantined lane's idleness shows within a
# probe interval or two
DEFAULT_WINDOW_S = 60.0
# ring bound per lane / per sample stream — at one entry per device batch
# this covers minutes of saturated traffic; past it the oldest evidence
# ages out early (the window result is then conservative, never wrong)
DEFAULT_MAX_ENTRIES = 2048

# -- the roofline peak table --------------------------------------------------

# Per-chip peak dense FLOP/s by TPU generation (bf16/f32 systolic peak as
# published per chip, not per core or per board). Matched by substring of
# ``device_kind`` (jax reports e.g. "TPU v4"). These are the REAL
# denominators the MFU gauges divide by on TPU backends.
TPU_PEAK_FLOPS: Dict[str, float] = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}
# unknown future TPU kinds: use the oldest generation's number — MFU then
# over-reports on newer chips, which reads as "suspiciously good, check the
# peak table", never as hidden idleness
TPU_PEAK_FLOPS_DEFAULT = 45e12

# CPU hosts: a DOCUMENTED ESTIMATE, not a measurement — a many-core server
# sustains O(1) TFLOP/s f32 with FMA/AVX; 2e12 keeps CPU MFU an
# order-of-magnitude trend line (docs/OBSERVABILITY.md). Virtual CPU lanes
# share one host, so per-lane CPU MFU overcounts by the lane count — the
# process-wide gauge is the honest one there.
CPU_PEAK_FLOPS_ESTIMATE = 2e12


def peak_flops_for(platform: str, device_kind: str = "") -> Optional[float]:
    """Peak FLOP/s for one chip of this platform/kind, or None (unknown).

    None means "no roofline denominator here" — MFU gauges are simply not
    published for such lanes rather than divided by a made-up number.
    """
    p = (platform or "").lower()
    if p == "cpu":
        return CPU_PEAK_FLOPS_ESTIMATE
    if p in ("tpu", "libtpu"):
        kind = (device_kind or "").lower()
        best = None
        for key, peak in TPU_PEAK_FLOPS.items():
            if key in kind and (best is None or len(key) > best[0]):
                best = (len(key), peak)
        return best[1] if best is not None else TPU_PEAK_FLOPS_DEFAULT
    return None


def _union_seconds(intervals: Sequence[Tuple[float, float]]) -> float:
    """Total length of the union of (t0, t1) intervals (any order)."""
    total = 0.0
    end = None
    for t0, t1 in sorted(intervals):
        if t1 <= t0:
            continue
        if end is None or t0 >= end:
            total += t1 - t0
            end = t1
        elif t1 > end:
            total += t1 - end
            end = t1
    return total


# fill-ratio buckets: fractions of a warm bucket actually carrying real
# rows — eighths resolve every fill level of the default bucket set
FILL_RATIO_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)
# idle-gap buckets: sub-ms back-to-back dispatch up to probe-interval gaps
IDLE_GAP_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0,
)


class SaturationMonitor:
    """Serving-side efficiency accounting over a sliding time window.

    Fed by the executor (:meth:`record_dispatch`, per supervised device
    batch) and the batcher (:meth:`record_chunk` per padded chunk,
    :meth:`record_window` per coalescing window); read by
    :meth:`publish`/:meth:`snapshot` on every metrics scrape and
    ``/readyz`` probe. All state is lock-guarded (NM331) and every ring is
    doubly bounded — by the time window and by a max entry count — so an
    arbitrarily long serving run holds O(window) evidence, never O(run).

    ``clock`` is injectable (tests pin a fake monotonic clock); everything
    else uses one process-wide ``time.monotonic`` timebase, the same one
    the trace spans ride.
    """

    def __init__(
        self,
        registry=None,
        window_s: float = DEFAULT_WINDOW_S,
        max_entries: int = DEFAULT_MAX_ENTRIES,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.registry = registry
        self.window_s = float(window_s)
        self.max_entries = int(max_entries)
        self._clock = clock
        self._lock = threading.Lock()
        self._epoch = clock()
        # lane -> deque[(t0, t1, flops)]; flops 0.0 for failed dispatches
        # (the chip was occupied — busy — but achieved nothing)
        self._dispatches: Dict[int, collections.deque] = {}
        self._last_end: Dict[int, float] = {}
        # lane -> (platform, device_kind, peak_flops-or-None)
        self._lanes: List[Tuple[str, str, Optional[float]]] = []
        # (lane, bucket) -> flops per dispatch (from executable_cost)
        self._flops: Dict[Tuple[int, int], float] = {}
        # goodput rings: (t, real_rows, bucket_rows) / (t, riders, capacity)
        self._chunks: collections.deque = collections.deque(
            maxlen=self.max_entries
        )
        self._windows: collections.deque = collections.deque(
            maxlen=self.max_entries
        )

    # -- feeding (executor / batcher side) ---------------------------------

    def set_lanes(self, lanes: Sequence[Tuple[str, str]]) -> None:
        """Declare the fleet: one (platform, device_kind) per lane.

        Publishes every lane's gauges at zero immediately, so "lane 3 was
        never busy" is a reported 0.0, distinguishable from "lane 3 was
        never resolved" (the same presence contract as
        ``serving_lane_state``).
        """
        rows = [
            (str(p), str(k), peak_flops_for(str(p), str(k)))
            for p, k in lanes
        ]
        with self._lock:
            self._lanes = rows
            for lane in range(len(rows)):
                self._dispatches.setdefault(
                    lane, collections.deque(maxlen=self.max_entries)
                )
        self.publish()

    def set_lane_bucket_flops(
        self, lane: int, bucket: int, flops: Optional[float]
    ) -> None:
        """Pin the per-dispatch flops of one (lane, bucket) executable —
        ``executable_cost()`` output, recorded once at warmup."""
        if flops is None:
            return
        with self._lock:
            self._flops[(int(lane), int(bucket))] = float(flops)

    def record_dispatch(
        self,
        lane: int,
        t0: float,
        t1: float,
        bucket: Optional[int] = None,
        counted: bool = True,
    ) -> None:
        """One device-batch interval on one lane (success or failure).

        ``counted=False`` (a failed/quarantining dispatch) keeps the busy
        time — the chip WAS occupied — but contributes zero achieved flops
        to MFU. The idle gap since the lane's previous dispatch feeds the
        idle-gap histogram.
        """
        lane = int(lane)
        t0, t1 = float(t0), float(t1)
        if t1 < t0:
            t0, t1 = t1, t0
        flops = 0.0
        if counted and bucket is not None:
            with self._lock:
                flops = self._flops.get((lane, int(bucket)), 0.0)
        gap = None
        with self._lock:
            ring = self._dispatches.setdefault(
                lane, collections.deque(maxlen=self.max_entries)
            )
            last = self._last_end.get(lane)
            if last is not None and t0 > last:
                gap = t0 - last
            self._last_end[lane] = max(last or t1, t1)
            ring.append((t0, t1, flops))
        if gap is not None and self.registry is not None:
            self.registry.histogram(
                SERVING_LANE_IDLE_GAP_SECONDS,
                help="gap between consecutive device dispatches on one "
                "replica lane (the shape of its idleness)",
                buckets=IDLE_GAP_BUCKETS,
                lane=str(lane),
            ).observe(gap)

    def record_chunk(self, real_rows: int, bucket_rows: int) -> None:
        """One padded chunk: ``real_rows`` riders in a ``bucket_rows``
        canvas stack; the difference is pure dead compute."""
        real, bucket = int(real_rows), int(bucket_rows)
        now = self._clock()
        with self._lock:
            self._chunks.append((now, real, bucket))
        if self.registry is not None:
            rows = self.registry.counter(
                SERVING_BATCH_ROWS_TOTAL,
                help="dispatched batch rows by kind: real riders vs padding "
                "(dead lanes of the bucket canvas)",
                kind="real",
            )
            rows.inc(real)
            self.registry.counter(
                SERVING_BATCH_ROWS_TOTAL,
                help="dispatched batch rows by kind: real riders vs padding "
                "(dead lanes of the bucket canvas)",
                kind="padded",
            ).inc(max(bucket - real, 0))
            if bucket > 0:
                self.registry.histogram(
                    SERVING_BUCKET_FILL_RATIO,
                    help="real rows / bucket size per dispatched chunk",
                    buckets=FILL_RATIO_BUCKETS,
                    bucket=str(bucket),
                ).observe(real / bucket)

    def record_window(self, riders: int, capacity: int) -> None:
        """One coalescing window: ``riders`` requests against the healthy
        fleet's row capacity at close time."""
        now = self._clock()
        with self._lock:
            self._windows.append((now, int(riders), max(int(capacity), 1)))

    # -- reading (scrape / readyz side) ------------------------------------

    def _window_start(self, now: float) -> float:
        # never reach before the monitor existed: a fresh server's first
        # scrape divides by its true uptime, not by a 60 s window it has
        # not lived yet
        return max(now - self.window_s, self._epoch)

    def _evict(self, now: float) -> None:
        """Drop entries that ended before the window (callers hold lock)."""
        horizon = now - self.window_s
        for ring in self._dispatches.values():
            while ring and ring[0][1] < horizon:
                ring.popleft()
        for ring in (self._chunks, self._windows):
            while ring and ring[0][0] < horizon:
                ring.popleft()

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The efficiency view over the current window (one lock hold).

        ``lanes[i].busy_fraction`` is the union of dispatch intervals
        clipped to the window over the window's span; ``mfu`` divides the
        achieved flops rate by the lane's peak (None where no peak is
        known or no flops were pinned). The process-wide ``mfu`` divides
        total achieved flops by the whole fleet's peak — the number that
        says what fraction of the machine the serving process used.
        """
        now = self._clock() if now is None else float(now)
        with self._lock:
            self._evict(now)
            w0 = self._window_start(now)
            span = max(now - w0, 1e-9)
            lanes = []
            total_flops = 0.0
            total_peak = 0.0
            busy_sum = 0.0
            for lane, (platform, kind, peak) in enumerate(self._lanes):
                ring = self._dispatches.get(lane, ())
                clipped = [
                    (max(t0, w0), min(t1, now))
                    for t0, t1, _ in ring
                    if t1 > w0
                ]
                busy = _union_seconds(clipped)
                flops = sum(f for t0, t1, f in ring if t1 > w0)
                frac = min(busy / span, 1.0)
                busy_sum += frac
                mfu = None
                if peak is not None and peak > 0:
                    mfu = (flops / span) / peak
                    total_flops += flops
                    total_peak += peak
                lanes.append(
                    {
                        "lane": lane,
                        "platform": platform,
                        "device_kind": kind,
                        "peak_flops": peak,
                        "busy_fraction": round(frac, 4),
                        "mfu": round(mfu, 6) if mfu is not None else None,
                    }
                )
            real = sum(r for _, r, _ in self._chunks)
            padded = sum(max(b - r, 0) for _, r, b in self._chunks)
            occ = [r / c for _, r, c in self._windows]
            total_rows = real + padded
        out = {
            "window_s": self.window_s,
            "lanes": lanes,
            "busy_fraction": (
                round(busy_sum / len(lanes), 4) if lanes else 0.0
            ),
            "mfu": (
                round((total_flops / span) / total_peak, 6)
                if total_peak > 0
                else None
            ),
            "padding_waste_ratio": (
                round(padded / total_rows, 4) if total_rows else 0.0
            ),
            "window_occupancy_ratio": (
                round(sum(occ) / len(occ), 4) if occ else 0.0
            ),
            "rows": {"real": real, "padded": padded},
        }
        return out

    def publish(self, now: Optional[float] = None) -> dict:
        """Refresh the saturation gauges from :meth:`snapshot`; returns it.

        Called on every ``/metrics``/``/metrics.json`` scrape and
        ``/readyz`` probe (gauges are pull-refreshed: the window slides
        whether or not traffic arrives) and once at drain so the final
        ``--metrics-out`` snapshot carries the run's last window.
        """
        snap = self.snapshot(now=now)
        reg = self.registry
        if reg is None:
            return snap
        for row in snap["lanes"]:
            lane = str(row["lane"])
            reg.gauge(
                SERVING_LANE_BUSY_FRACTION,
                help="fraction of the sliding window one replica lane spent "
                "executing device batches",
                lane=lane,
            ).set(row["busy_fraction"])
            if row["peak_flops"] is not None:
                reg.gauge(
                    SERVING_LANE_PEAK_FLOPS,
                    help="per-chip peak FLOP/s used as the lane's MFU "
                    "denominator (TPU: published per-generation numbers; "
                    "CPU: documented order-of-magnitude estimate)",
                    lane=lane,
                ).set(row["peak_flops"])
            if row["mfu"] is not None:
                reg.gauge(
                    SERVING_LANE_MFU,
                    help="achieved flops rate / peak flops per replica lane "
                    "over the sliding window",
                    lane=lane,
                ).set(row["mfu"])
        reg.gauge(
            SERVING_BUSY_FRACTION,
            help="mean lane busy fraction over the sliding window",
        ).set(snap["busy_fraction"])
        if snap["mfu"] is not None:
            reg.gauge(
                SERVING_MFU,
                help="process-wide model flops utilization: achieved flops "
                "rate / whole-fleet peak over the sliding window",
            ).set(snap["mfu"])
        reg.gauge(
            SERVING_PADDING_WASTE_RATIO,
            help="dead (padded) rows / total dispatched rows over the "
            "sliding window — the goodput gap dynamic batching pays for "
            "fixed compile shapes",
        ).set(snap["padding_waste_ratio"])
        reg.gauge(
            SERVING_WINDOW_OCCUPANCY_RATIO,
            help="mean riders-per-window / healthy fleet row capacity over "
            "the sliding window",
        ).set(snap["window_occupancy_ratio"])
        return snap


# -- driver-side feed accounting ---------------------------------------------

# the feed phase vocabulary both batch drivers report (docs/OBSERVABILITY.md
# feed_stall schema); "dispatch" is the device-occupied phase — everything
# else is the serial feed ROADMAP item 3 exists to overlap away
FEED_PHASES = ("decode", "stage", "dispatch", "fetch", "export")


class PhaseAccountant:
    """Bounded busy-interval accounting for the driver feed phases.

    Records (t0, t1) busy intervals per named phase from any thread (the
    parallel driver's IO pool fetches on workers) and reports per-phase
    union seconds plus the headline ``feed_stall_ratio``: the fraction of
    wall time NO ``dispatch`` interval was active — device starvation by
    the serial decode→stage→dispatch→fetch feed. Intervals are merged
    incrementally into disjoint runs, so memory is bounded by the number
    of *gaps*, with a hard ``max_intervals`` cap past which the oldest
    runs collapse into an exact closed-sum (the report stays correct, the
    per-interval detail ages out).
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        max_intervals: int = 4096,
    ):
        if max_intervals < 2:
            raise ValueError(f"max_intervals must be >= 2, got {max_intervals}")
        self._clock = clock
        self.max_intervals = int(max_intervals)
        self._lock = threading.Lock()
        # phase -> sorted disjoint [t0, t1] runs (lists: ends get extended)
        self._runs: Dict[str, List[List[float]]] = {}
        # phase -> busy seconds of collapsed (aged-out) runs, and the time
        # horizon that collapse covered: late out-of-order intervals are
        # clamped to it so already-closed busy time is never counted twice
        self._closed: Dict[str, float] = {}
        self._horizon: Dict[str, float] = {}
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None

    @contextlib.contextmanager
    def busy(self, phase: str):
        """Time one busy interval of ``phase`` (records even on a raise —
        the device/decoder was occupied either way)."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.record(phase, t0, self._clock())

    def record(self, phase: str, t0: float, t1: float) -> None:
        t0, t1 = float(t0), float(t1)
        if t1 < t0:
            t0, t1 = t1, t0
        key = str(phase)
        with self._lock:
            if self._t_first is None or t0 < self._t_first:
                self._t_first = t0
            if self._t_last is None or t1 > self._t_last:
                self._t_last = t1
            # a late arrival reaching into the collapsed prefix is clamped
            # to the horizon: its overlap with the closed runs must never
            # count twice. (Time falling in a GAP of the collapsed prefix
            # is forfeited — without the per-run detail it cannot be told
            # apart from a duplicate; busy is then conservative, which for
            # the stall report errs toward reporting MORE starvation.)
            horizon = self._horizon.get(key)
            if horizon is not None:
                if t1 <= horizon:
                    return  # wall extent recorded above; busy already closed
                t0 = max(t0, horizon)
            runs = self._runs.setdefault(key, [])
            # insert keeping runs sorted + disjoint: merge every run the
            # new interval touches (threads deliver out of order)
            i = bisect.bisect_left(runs, [t0, t1])
            if i > 0 and runs[i - 1][1] >= t0:
                i -= 1
            j = i
            while j < len(runs) and runs[j][0] <= t1:
                t0 = min(t0, runs[j][0])
                t1 = max(t1, runs[j][1])
                j += 1
            runs[i:j] = [[t0, t1]]
            if len(runs) > self.max_intervals:
                # collapse the oldest half into the exact closed sum: the
                # union is already disjoint, so the total stays correct
                cut = len(runs) // 2
                self._closed[key] = self._closed.get(key, 0.0) + sum(
                    b - a for a, b in runs[:cut]
                )
                self._horizon[key] = runs[cut - 1][1]
                del runs[:cut]

    def busy_seconds(self, phase: str) -> float:
        with self._lock:
            return self._closed.get(phase, 0.0) + sum(
                b - a for a, b in self._runs.get(phase, ())
            )

    def report(self) -> dict:
        """The ``feed_stall`` record (docs/OBSERVABILITY.md).

        ``feed_stall_ratio`` is None when no dispatch interval was ever
        recorded (an empty cohort measured nothing — a 0.0 there would
        read as a perfectly-fed device).
        """
        with self._lock:
            phases = sorted(set(self._runs) | set(self._closed))
            t0, t1 = self._t_first, self._t_last
        busy = {p: round(self.busy_seconds(p), 4) for p in phases}
        wall = max((t1 - t0), 0.0) if t0 is not None and t1 is not None else 0.0
        out = {
            "wall_s": round(wall, 4),
            "busy_s": busy,
            "busy_fraction": {
                p: round(s / wall, 4) if wall > 0 else 0.0
                for p, s in busy.items()
            },
        }
        dispatch = busy.get("dispatch")
        if dispatch is not None and wall > 0:
            out["feed_stall_ratio"] = round(
                max(1.0 - dispatch / wall, 0.0), 4
            )
            out["stall_s"] = round(max(wall - dispatch, 0.0), 4)
        else:
            out["feed_stall_ratio"] = None
            out["stall_s"] = None
        return out
