"""Structured event log: JSON-lines run telemetry.

Every record is one JSON object on one line carrying the run envelope —
run id, git SHA, a monotonically increasing sequence number, wall AND
monotonic timestamps — plus a level, an event name, and free-form fields.
The schema (``nm03.events.v1``) is documented in docs/OBSERVABILITY.md and
enforced by scripts/check_telemetry.py; drivers write it via ``--log-json``.

Also here:

* :class:`Heartbeat` — a daemon thread emitting a periodic ``heartbeat``
  event with uptime and live counter totals, so a stalled cohort run is
  distinguishable from a slow one by tailing the event stream;
* :class:`LogBridge` — a ``logging.Handler`` that mirrors the package
  logger's WARNING+ records into the event stream, so the existing
  ``log.warning`` fault-containment messages (decode failures, export
  failures) become structured events without touching every call site.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional

SCHEMA_EVENTS = "nm03.events.v1"
LEVELS = ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL")
# the run envelope; emit() rejects field names that would shadow it
RESERVED_KEYS = (
    "schema", "run_id", "git_sha", "seq", "ts_unix", "mono_s", "level", "event",
)


def new_run_id() -> str:
    return uuid.uuid4().hex[:12]


_GIT_SHA_CACHE: Optional[str] = None


def _default_git_sha() -> str:
    # lazy (utils.timing shells out to git; never at import time) and cached
    # per process: library callers construct many sink-less EventLogs and
    # must not pay two subprocesses each
    global _GIT_SHA_CACHE
    if _GIT_SHA_CACHE is None:
        try:
            from nm03_capstone_project_tpu.utils.timing import git_sha

            _GIT_SHA_CACHE = git_sha()
        except Exception:  # noqa: BLE001 — stamping must never break a run
            _GIT_SHA_CACHE = "unknown"
    return _GIT_SHA_CACHE


class EventLog:
    """Thread-safe JSONL event writer with a fixed run envelope.

    One run per file: ``path`` is truncated at open (the schema demands a
    single run_id per stream), and a failing sink write disables the sink
    rather than raising — emit() can only raise on contract violations
    (unknown level, envelope shadowing), never on I/O.

    With neither ``path`` nor ``stream`` the log is a sink-less recorder:
    records are still built (and kept in a small in-memory tail for tests
    and post-mortems) but nothing touches disk — the default for library
    use so :class:`~nm03_capstone_project_tpu.obs.run.RunContext` can be
    unconditional in the drivers.
    """

    def __init__(
        self,
        path=None,
        stream=None,
        run_id: Optional[str] = None,
        git_sha: Optional[str] = None,
        tail: int = 256,
    ):
        if path is not None and stream is not None:
            raise ValueError("pass path or stream, not both")
        self.run_id = run_id or new_run_id()
        self.git_sha = git_sha if git_sha is not None else _default_git_sha()
        # RLock: bench's signal handler may close() this log on the main
        # thread mid-emit (same-thread re-acquisition must not deadlock)
        self._lock = threading.RLock()
        self._seq = 0
        self._owns_fh = False
        self._fh = stream
        if path is not None:
            path = str(path)
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            # truncate, don't append: the schema (and check_telemetry.py)
            # demand ONE run per stream — one run_id, seq from 0,
            # run_started first / run_finished last. Appending a second run
            # would make the validator reject two individually valid runs.
            # nm03-lint: disable=NM351 long-lived line-buffered streaming sink, not an artifact write: the JSONL contract is one run per file (truncate at open) and readers tolerate a torn tail (check_telemetry validates run_finished-last)
            self._fh = open(path, "w", buffering=1)
            self._owns_fh = True
        self.tail = deque(maxlen=tail)

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def emit(self, event: str, level: str = "INFO", **fields) -> dict:
        """Write one record; returns it (also kept in the in-memory tail)."""
        if level not in LEVELS:
            raise ValueError(f"unknown level {level!r} (want one of {LEVELS})")
        clash = [k for k in fields if k in RESERVED_KEYS]
        if clash:
            raise ValueError(f"fields shadow the run envelope: {clash}")
        with self._lock:
            record = {
                "schema": SCHEMA_EVENTS,
                "run_id": self.run_id,
                "git_sha": self.git_sha,
                "seq": self._seq,
                "ts_unix": round(time.time(), 6),
                "mono_s": round(time.monotonic(), 6),
                "level": level,
                "event": str(event),
            }
            record.update(fields)
            self._seq += 1
            self.tail.append(record)
            if self._fh is not None:
                # default=str: an un-JSON-able field value must degrade to
                # its repr, never kill the run or tear the line
                line = json.dumps(record, default=str) + "\n"
                try:
                    self._fh.write(line)
                except Exception as e:  # noqa: BLE001 — ENOSPC/EPIPE/closed fd
                    # telemetry must never cost the run its results: degrade
                    # to sink-less mode (in-memory tail keeps recording) and
                    # say so once on stderr — the write will not come back
                    self._fh = None
                    import sys

                    print(
                        f"warning: event log write failed; telemetry sink "
                        f"disabled: {e}",
                        file=sys.stderr,
                    )
        return record

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                with contextlib.suppress(Exception):
                    self._fh.flush()
                if self._owns_fh:
                    with contextlib.suppress(Exception):
                        self._fh.close()
                self._fh = None


class LogBridge(logging.Handler):
    """Mirror WARNING+ package-logger records into the event stream."""

    def __init__(self, events: EventLog, level=logging.WARNING):
        super().__init__(level=level)
        self.events = events

    def emit(self, record: logging.LogRecord) -> None:
        with contextlib.suppress(Exception):  # logging must never raise
            self.events.emit(
                "log",
                level=record.levelname if record.levelname in LEVELS else "WARNING",
                logger=record.name,
                message=record.getMessage(),
            )


class Heartbeat:
    """Daemon thread emitting a periodic ``heartbeat`` event.

    The payload carries uptime and the registry's live counter totals
    (slices done/failed so far, patients completed, ...), making progress
    visible mid-run from the event stream alone.
    """

    def __init__(self, events: EventLog, interval_s: float, registry=None):
        if interval_s <= 0:
            raise ValueError("heartbeat interval must be positive")
        self.events = events
        self.interval_s = float(interval_s)
        self.registry = registry
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="nm03-obs-heartbeat", daemon=True
        )

    def start(self) -> "Heartbeat":
        self._thread.start()
        return self

    def _run(self) -> None:
        n = 0
        while not self._stop.wait(self.interval_s):
            n += 1
            fields = {"uptime_s": round(time.monotonic() - self._t0, 3), "beat": n}
            if self.registry is not None:
                fields["counters"] = self.registry.counter_totals()
            with contextlib.suppress(Exception):  # never kill the run
                self.events.emit("heartbeat", **fields)

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
