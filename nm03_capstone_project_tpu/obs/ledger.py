"""Device-time ledger: what every served request COSTS (ISSUE 16).

The saturation layer (ISSUE 10) answers "how busy are the chips" and the
SLO plane (ISSUE 14) answers "are we meeting objectives"; neither can say
*where the device time goes* or *what one request costs*. This module is
that missing attribution layer, three coupled planes over evidence the
serving stack already produces:

* **Per-request cost attribution** — the executor's per-dispatch busy
  intervals, prorated across the riders of each padded chunk: real rows
  charged to the ``request`` account, dead rows to ``padding``, fleet
  probation canaries to ``probe`` (visible but excluded from the
  per-request histogram, the PR 14 contract). The three accounts sum to
  the executor's recorded busy time *exactly* — proration conserves.
* **Live stage shares** — a cadence-driven sampler takes short
  ``jax.profiler`` captures (through the one-at-a-time lock
  ``utils.profiling`` already owns), reduces the device timeline into
  per-stage self-time using the optimized HLO's ``source_file`` metadata
  (fusions attributed by majority vote over their fused computation), and
  publishes the r05 bench pie as ``serving_device_time_share{stage}`` —
  live, on ``/metrics``.
* **HBM ledger** — per-bucket executable memory analysis from the compile
  hub's ``executable_cost``, published as
  ``serving_executable_hbm_bytes{bucket,kind}`` at warmup.

jax-free AND numpy-free at import by the obs package contract (NM301):
the HLO text and the Chrome-trace JSON are both parsed with stdlib only,
and the profiler capture function is injected (the serving layer hands in
``utils.profiling.capture_profile``; tests hand in fakes). Thread-shared
state is lock-guarded (NM331). Metric names live in :mod:`.metrics` so
the NM392 metrics<->docs gate covers them.
"""

from __future__ import annotations

import base64
import collections
import gzip
import io
import json
import logging
import re
import threading
import time
import zipfile
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from nm03_capstone_project_tpu.obs.metrics import (
    LEDGER_PROFILE_SKIPPED_TOTAL,
    SERVING_DEVICE_SECONDS_PER_REQUEST,
    SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN,
    SERVING_DEVICE_SECONDS_TOTAL,
    SERVING_DEVICE_TIME_SHARE,
    SERVING_EXECUTABLE_HBM_BYTES,
)

_log = logging.getLogger("nm03.ledger")

# the three cost accounts every dispatched row lands in (and sums across)
ACCOUNTS = ("request", "padding", "probe")

# the serving pipeline's stage vocabulary — the same names the r05 bench
# pie uses, plus "other" for device time no stage claims (infeed, copies,
# glue the compiler didn't tag with a pipeline source file)
STAGES = ("normalize", "median7", "sharpen", "grow", "morph", "render")

# pipeline source-file basename fragments -> stage. The optimized HLO
# carries ``source_file`` metadata per instruction; the fragment match is
# on the basename so a refactor that moves ops/ around does not silently
# retag the pie. Order matters only for documentation — fragments are
# disjoint.
STAGE_BY_FILE: Tuple[Tuple[str, str], ...] = (
    ("median", "median7"),
    ("sharpen", "sharpen"),
    ("region_growing", "grow"),
    ("seeds", "grow"),
    ("morphology", "morph"),
    ("elementwise", "normalize"),
    ("neighborhood", "normalize"),
    ("render", "render"),
)

# executable_cost() keys -> the {kind} label of serving_executable_hbm_bytes
HBM_KINDS: Tuple[Tuple[str, str], ...] = (
    ("argument_bytes", "argument"),
    ("output_bytes", "output"),
    ("temp_bytes", "temp"),
    ("alias_bytes", "alias"),
    ("code_bytes", "code"),
    ("peak_hbm_bytes", "peak"),
)

# per-request device-seconds: sub-ms TPU rows up to tens of seconds of a
# degraded CPU lane — much finer at the bottom than the latency buckets,
# because a row's device share is latency divided by the batch size
DEVICE_SECONDS_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def stage_for_source(path: str) -> str:
    """Stage owning one HLO ``source_file`` path ("other" if none does)."""
    base = (path or "").replace("\\", "/").rsplit("/", 1)[-1]
    for fragment, stage in STAGE_BY_FILE:
        if fragment in base:
            return stage
    return "other"


# computation headers start at column 0: "%fused_computation.1 (p: ...) ->"
# or "ENTRY %main.42 (...) ->"
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([A-Za-z0-9_.\-]+)\s*\(.*\)\s*->")
_INST_RE = re.compile(r"%([A-Za-z0-9_.\-]+) = .*?source_file=\"([^\"]+)\"")
_FUSION_RE = re.compile(
    r"%([A-Za-z0-9_.\-]+) = .*? fusion\(.*?calls=%([A-Za-z0-9_.\-]+)"
)


def stage_map_from_hlo(hlo_text: str) -> Dict[str, str]:
    """instruction name -> stage, from optimized HLO text.

    Plain instructions are attributed by their own ``source_file``
    metadata; ``fusion`` instructions by majority vote over the
    instructions of the computation they call (a fused region spans ops
    from several source lines — the vote picks the stage that contributed
    most of its body, preferring any real stage over "other"). The map is
    what the trace reducer joins device events against: profiler events
    carry ``hlo_op`` names, not source files.
    """
    comp_counts: Dict[str, collections.Counter] = {}
    fusions: List[Tuple[str, str]] = []
    out: Dict[str, str] = {}
    current: Optional[str] = None
    for line in (hlo_text or "").splitlines():
        if line and not line[0].isspace():
            m = _COMP_RE.match(line)
            if m:
                current = m.group(1)
                comp_counts.setdefault(current, collections.Counter())
                continue
        fm = _FUSION_RE.search(line)
        im = _INST_RE.search(line)
        if im:
            name, src = im.group(1), im.group(2)
            stage = stage_for_source(src)
            if current is not None:
                comp_counts[current][stage] += 1
            if fm is None:
                out[name] = stage
        if fm:
            fusions.append((fm.group(1), fm.group(2)))
    for instr, called in fusions:
        counts = comp_counts.get(called) or collections.Counter()
        ranked = {s: c for s, c in counts.items() if s != "other"}
        out[instr] = max(ranked, key=ranked.get) if ranked else "other"
    return out


def reduce_trace_events(
    events: Iterable[dict], stage_of: Dict[str, str]
) -> Dict[str, float]:
    """Per-stage device SELF-time (seconds) from Chrome-trace events.

    Considers only complete (``ph == "X"``) events carrying an ``hlo_op``
    arg — the device op lanes; host-side thunk/executor events carry no
    ``hlo_op`` and are excluded. Events nest on each (pid, tid) timeline
    (a fusion's region contains its constituent ops), so durations are
    reduced to self-time with an interval stack: a child's duration is
    subtracted from its enclosing parent's stage, never double-counted.
    """
    per_thread: Dict[Tuple, List[Tuple[float, float, str]]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        op = args.get("hlo_op")
        if not op:
            continue
        try:
            ts = float(ev["ts"])
            dur = float(ev.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        key = (ev.get("pid"), ev.get("tid"))
        per_thread.setdefault(key, []).append((ts, dur, str(op).lstrip("%")))
    stage_us: Dict[str, float] = collections.defaultdict(float)
    for rows in per_thread.values():
        # at equal start times the LONGER event is the parent: sort it first
        rows.sort(key=lambda r: (r[0], -r[1]))
        stack: List[Tuple[str, float]] = []  # (stage, end_ts)
        for ts, dur, op in rows:
            while stack and stack[-1][1] <= ts:
                stack.pop()
            stage = stage_of.get(op, "other")
            stage_us[stage] += dur
            if stack:
                stage_us[stack[-1][0]] -= dur
            stack.append((stage, ts + dur))
    return {s: us / 1e6 for s, us in stage_us.items() if us > 1e-9}


def trace_events_from_capture(capture: dict) -> List[dict]:
    """Extract Chrome-trace events from a ``capture_profile`` result.

    The capture zips the whole profiler directory; the ``*.trace.json.gz``
    member inside is gzipped Chrome-trace JSON (stdlib all the way down).
    Oversized captures kept server-side (``zip_dropped``) are read back
    from ``zip_path``. Returns ``[]`` when no trace rode the capture.
    """
    data = None
    if capture.get("zip_b64"):
        data = base64.b64decode(capture["zip_b64"])
    elif capture.get("zip_path"):
        with open(capture["zip_path"], "rb") as f:
            data = f.read()
    if not data:
        return []
    events: List[dict] = []
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        for name in zf.namelist():
            if name.endswith(".trace.json.gz"):
                doc = json.loads(gzip.decompress(zf.read(name)))
            elif name.endswith(".trace.json"):
                doc = json.loads(zf.read(name))
            else:
                continue
            events.extend(doc.get("traceEvents") or [])
    return events


class DeviceTimeLedger:
    """Per-request device-time accounting + live stage shares + HBM ledger.

    Fed by the executor (accumulated chunk busy seconds, warmup HLO text
    and memory analysis), charged by the batcher per dispatched chunk
    (:meth:`charge_chunk` prorates; :meth:`observe_request` lands each
    non-probe rider's total in the histogram), sampled by a
    :class:`ProfileSampler`, and read by :meth:`publish`/:meth:`snapshot`
    on every scrape and once at drain — the same pull-refresh contract as
    the SaturationMonitor. All shared state is lock-guarded (NM331).
    """

    def __init__(
        self,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._accounts: Dict[str, float] = {a: 0.0 for a in ACCOUNTS}
        self._request_count = 0
        self._request_seconds = 0.0
        self._stage_map: Dict[str, str] = {}
        # cumulative reduced device seconds per stage across every sample:
        # shares smooth over sampling jitter instead of flapping per trace
        self._stage_seconds: Dict[str, float] = {}
        self._samples_taken = 0
        self._samples_skipped = 0
        self._hbm: Dict[int, Dict[str, int]] = {}

    # -- feeding (executor / batcher side) ---------------------------------

    def charge_chunk(
        self,
        busy_s: float,
        bucket_rows: int,
        real_rows: int,
        probe_rows: int = 0,
    ) -> float:
        """Prorate one chunk's device-busy seconds across its canvas rows.

        ``bucket_rows`` is the padded canvas height the device actually
        ran; ``real_rows`` the non-probe riders, ``probe_rows`` the fleet
        probation canaries aboard. Every row costs the same share
        (``busy_s / bucket_rows`` — the device computes padding as hard as
        payload), so request + probe + padding always sums back to
        ``busy_s`` exactly. Returns the per-row share the caller stamps on
        each rider.
        """
        busy = max(float(busy_s), 0.0)
        rows = max(int(bucket_rows), 1)
        real = max(int(real_rows), 0)
        probe = max(int(probe_rows), 0)
        pad = max(rows - real - probe, 0)
        share = busy / rows
        with self._lock:
            self._accounts["request"] += share * real
            self._accounts["probe"] += share * probe
            self._accounts["padding"] += share * pad
        if self.registry is not None and busy > 0:
            for account, amount in (
                ("request", share * real),
                ("probe", share * probe),
                ("padding", share * pad),
            ):
                if amount > 0:
                    self.registry.counter(
                        SERVING_DEVICE_SECONDS_TOTAL,
                        help="device-busy seconds by cost account: request "
                        "(real riders), padding (dead canvas rows), probe "
                        "(fleet probation canaries) — the three sum to the "
                        "executor's recorded busy time",
                        account=account,
                    ).inc(amount)
        return share

    def observe_request(self, seconds: float) -> None:
        """One finished NON-probe request's total device-seconds (its
        prorated share, summed over every dispatch attempt it rode)."""
        s = max(float(seconds), 0.0)
        with self._lock:
            self._request_count += 1
            self._request_seconds += s
        if self.registry is not None:
            self.registry.histogram(
                SERVING_DEVICE_SECONDS_PER_REQUEST,
                help="prorated device-seconds each served request cost "
                "(probe canaries excluded)",
                buckets=DEVICE_SECONDS_BUCKETS,
            ).observe(s)

    def note_profile_skipped(self) -> None:
        """The sampler yielded to a client capture (busy lock) — counted,
        never queued (ISSUE 16 bugfix: a queued sample would stack behind
        an operator's pull and fire at an arbitrary later moment)."""
        with self._lock:
            self._samples_skipped += 1
        if self.registry is not None:
            self.registry.counter(
                LEDGER_PROFILE_SKIPPED_TOTAL,
                help="ledger profile samples skipped because a client "
                "GET /debug/profile capture held the profiler lock",
            ).inc()

    def ingest_hlo(self, hlo_text: str) -> int:
        """Merge one executable's optimized-HLO stage map (warmup feed;
        instruction names are unique enough across buckets that last-wins
        merging is safe — colliding names map to the same stage)."""
        mapping = stage_map_from_hlo(hlo_text)
        with self._lock:
            self._stage_map.update(mapping)
        return len(mapping)

    def set_bucket_hbm(self, bucket: int, cost: Optional[dict]) -> None:
        """One bucket's executable memory analysis (``executable_cost``
        output; best-effort — absent kinds are simply not published)."""
        if not cost:
            return
        kinds = {
            label: int(cost[key])
            for key, label in HBM_KINDS
            if isinstance(cost.get(key), (int, float))
        }
        if not kinds:
            return
        with self._lock:
            self._hbm[int(bucket)] = kinds

    def ingest_trace_events(self, events: Iterable[dict]) -> Dict[str, float]:
        """Reduce one capture's events into stage self-time and fold it
        into the cumulative shares; returns this sample's stage seconds."""
        with self._lock:
            stage_of = dict(self._stage_map)
        sample = reduce_trace_events(events, stage_of)
        with self._lock:
            self._samples_taken += 1
            for stage, s in sample.items():
                self._stage_seconds[stage] = (
                    self._stage_seconds.get(stage, 0.0) + s
                )
        return sample

    def ingest_capture(self, capture: dict) -> Dict[str, float]:
        """Full path for one ``capture_profile`` result: unzip, parse the
        Chrome trace, reduce, accumulate."""
        return self.ingest_trace_events(trace_events_from_capture(capture))

    # -- reading (scrape / drain side) -------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            accounts = {a: round(v, 9) for a, v in self._accounts.items()}
            total_stage = sum(self._stage_seconds.values())
            shares = {
                s: round(v / total_stage, 4)
                for s, v in sorted(self._stage_seconds.items())
                if total_stage > 0
            }
            # per-share rounding can overshoot the pie (sum 1.0001); the
            # "shares sum to <= 1" contract is load-bearing (the
            # --expect-gauge-sum-range gate), so shave the excess off the
            # largest slice
            excess = round(sum(shares.values()) - 1.0, 9)
            if excess > 0:
                top = max(shares, key=shares.get)
                shares[top] = round(shares[top] - excess, 9)
            stage_seconds = {
                s: round(v, 6) for s, v in sorted(self._stage_seconds.items())
            }
            count, seconds = self._request_count, self._request_seconds
            hbm = {b: dict(k) for b, k in sorted(self._hbm.items())}
            taken, skipped = self._samples_taken, self._samples_skipped
        return {
            "accounts": accounts,
            "device_seconds_total": round(sum(accounts.values()), 9),
            "requests": {
                "count": count,
                "device_seconds_sum": round(seconds, 9),
                "device_seconds_mean": (
                    round(seconds / count, 9) if count else None
                ),
            },
            "stage_shares": shares,
            "stage_seconds": stage_seconds,
            "profile_samples": {"taken": taken, "skipped": skipped},
            "hbm_bytes": hbm,
        }

    def publish(self) -> dict:
        """Refresh the ledger gauges from :meth:`snapshot`; returns it.

        Counters and histograms land at feed time; this pushes the
        derived gauges (stage shares, the per-request mean, the HBM
        table) so every scrape and the drain snapshot carry them.
        """
        snap = self.snapshot()
        reg = self.registry
        if reg is None:
            return snap
        for stage, share in snap["stage_shares"].items():
            reg.gauge(
                SERVING_DEVICE_TIME_SHARE,
                help="fraction of sampled device self-time spent in one "
                "pipeline stage (profiler-sampled; shares sum to <= 1)",
                stage=stage,
            ).set(share)
        mean = snap["requests"]["device_seconds_mean"]
        if mean is not None:
            reg.gauge(
                SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN,
                help="mean prorated device-seconds per served request "
                "(probe canaries excluded) — the gauge twin of the "
                "histogram, for nm03-top and gauge-range gates",
            ).set(mean)
        for bucket, kinds in snap["hbm_bytes"].items():
            for kind, nbytes in kinds.items():
                reg.gauge(
                    SERVING_EXECUTABLE_HBM_BYTES,
                    help="per-bucket executable memory analysis from the "
                    "compile hub: argument/output/temp/alias/code/peak "
                    "bytes of each warm serving executable",
                    bucket=str(bucket),
                    kind=kind,
                ).set(nbytes)
        return snap


class ProfileSampler:
    """Cadence-driven stage-share sampler for one :class:`DeviceTimeLedger`.

    Every ``interval_s`` it takes a short profiler capture through the
    injected ``capture`` callable (the serving layer passes
    ``utils.profiling.capture_profile``, which owns the process-global
    one-at-a-time lock) and feeds the reduced trace to the ledger. When a
    client ``GET /debug/profile`` pull holds the lock the sample is
    SKIPPED and counted — never queued — so an operator's capture is
    never contended and the sampler can never stack behind one
    (the ISSUE 16 bugfix contract). Capture or reduction failures are
    logged and swallowed: sampling must never take serving down.
    """

    def __init__(
        self,
        ledger: DeviceTimeLedger,
        interval_s: float = 30.0,
        duration_ms: int = 200,
        capture: Optional[Callable[[int], dict]] = None,
    ):
        self.ledger = ledger
        self.interval_s = float(interval_s)
        self.duration_ms = int(duration_ms)
        self._capture = capture
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self) -> bool:
        """One sample attempt; True when a trace landed in the ledger."""
        capture = self._capture
        if capture is None:
            from nm03_capstone_project_tpu.utils.profiling import (
                capture_profile as capture,
            )
        try:
            result = capture(self.duration_ms)
        except Exception as exc:
            from nm03_capstone_project_tpu.utils.profiling import ProfileBusy

            if isinstance(exc, ProfileBusy):
                self.ledger.note_profile_skipped()
            else:
                _log.warning("ledger profile capture failed: %s", exc)
            return False
        try:
            self.ledger.ingest_capture(result)
        except Exception as exc:
            _log.warning("ledger trace reduction failed: %s", exc)
            return False
        return True

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="ledger-sampler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None
