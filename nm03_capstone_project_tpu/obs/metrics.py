"""Metrics registry: counters, gauges, and bucketed histograms.

The reference's evidence chain lives outside its repo (hyperfine wall
clocks, perf profiles, gitignored results JSONs — reference README.md:90-96).
This registry is the in-tree replacement's substrate: every run accumulates
named, labeled metrics and snapshots them to JSON (embedded in
``--results-json`` payloads, written standalone by ``--metrics-out``) and to
the Prometheus text exposition format for scrape-based collection.

Design constraints:

* **Thread-safe.** The parallel batch driver increments counters from IO
  pool threads while the main thread observes stage latencies.
* **Pure stdlib.** The registry must import (and snapshot) in processes
  that never touch jax — bench.py's orchestrator deliberately doesn't.
* **Bounded cardinality is the caller's job**, but the registry enforces
  name/label hygiene (Prometheus-legal names, string label values) so a
  drifting call site fails at the increment, not in the scrape pipeline.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

SCHEMA_METRICS = "nm03.metrics.v1"

# -- canonical metric names ---------------------------------------------------
# This module (with serving/metrics.py) owns every metric NAME the package
# registers, by contract: lint rule NM392 cross-checks these constants
# against the docs/OBSERVABILITY.md tables in both directions, so a series
# can neither ship undocumented nor linger documented after removal. Other
# modules import their names from here (obs.run, obs.spans, utils.sanitize).

# spans / driver accounting
STAGE_LATENCY_METRIC = "nm03_stage_latency_seconds"
PATIENT_OUTCOMES_TOTAL = "nm03_patient_outcomes_total"
SLICES_TOTAL = "nm03_slices_total"
GROW_TRUNCATED_TOTAL = "pipeline_grow_truncated_total"
HEARTBEATS_TOTAL = "nm03_heartbeats_total"
RUN_WALL_SECONDS = "nm03_run_wall_seconds"
TRAIN_FINAL_LOSS = "nm03_train_final_loss"
TRAIN_IOU_VS_TEACHER = "nm03_train_iou_vs_teacher"
PIPELINE_PATH_INFO = "nm03_pipeline_path_info"
MEDIAN_COMPARATOR_OPS = "nm03_median_comparator_minmax_ops"
# resilience subsystem (docs/RESILIENCE.md; validated by check_telemetry.py)
RESILIENCE_RETRIES_TOTAL = "resilience_retries_total"
RESILIENCE_FAULTS_INJECTED_TOTAL = "resilience_faults_injected_total"
PIPELINE_DEGRADED_TOTAL = "pipeline_degraded_total"
# --sanitize recompile watchdog (utils.sanitize; docs/STATIC_ANALYSIS.md)
PIPELINE_RECOMPILES_TOTAL = "pipeline_recompiles_total"
# driver feed accounting (obs.saturation.PhaseAccountant, ISSUE 10): the
# fraction of wall the device sat starved by the serial feed
PIPELINE_FEED_STALL_RATIO = "pipeline_feed_stall_ratio"
# streaming ingest (ingest/ subsystem, ISSUE 11): how the host->HBM
# pipeline is doing — ring fill, decode lookahead, upload/compute overlap.
# Published live by IngestPipeline.publish() and once at drain so the
# final --metrics-out snapshot carries the run's totals.
INGEST_RING_OCCUPANCY_RATIO = "ingest_ring_occupancy_ratio"
INGEST_DECODE_QUEUE_DEPTH = "ingest_decode_queue_depth"
INGEST_UPLOAD_OVERLAP_RATIO = "ingest_upload_overlap_ratio"

# saturation / goodput telemetry (obs.saturation, ISSUE 10). These are
# serving_* series, but they are DEFINED here, not in serving/metrics.py:
# the SaturationMonitor lives in obs/ (jax-/numpy-free by the package
# contract) and obs must not import the serving package, whose __init__
# pulls numpy. serving/metrics.py re-exports them for serving-side callers.
SERVING_LANE_BUSY_FRACTION = "serving_lane_busy_fraction"
# fleet front-end (fleet/ subsystem, ISSUE 13): the replica-level fault
# domain's telemetry — replica routing state, routed/failover/shed
# accounting and the routed-capacity fraction. Defined HERE (not in a
# fleet-local module) for the same reason as the serving saturation
# names: the fleet package is jax-/numpy-free by contract and this
# module is the NM392-checked definition home, so a fleet series can
# neither ship undocumented nor linger documented after removal.
FLEET_REPLICAS_READY = "fleet_replicas_ready"
FLEET_REPLICAS_EJECTED = "fleet_replicas_ejected"
FLEET_ROUTED_CAPACITY = "fleet_routed_capacity"
FLEET_REPLICA_STATE = "fleet_replica_state"
FLEET_REPLICA_CAPACITY = "fleet_replica_capacity"
FLEET_REQUESTS_ROUTED_TOTAL = "fleet_requests_routed_total"
FLEET_FAILOVERS_TOTAL = "fleet_failovers_total"
FLEET_SHED_TOTAL = "fleet_shed_total"
FLEET_REPLICA_EJECTIONS_TOTAL = "fleet_replica_ejections_total"
FLEET_REPLICA_REINSTATED_TOTAL = "fleet_replica_reinstated_total"
FLEET_PROBES_TOTAL = "fleet_probes_total"
# fleet request accounting (ISSUE 14): the SLO layer's inputs on the
# router side — terminal proxied-request outcomes by status class and the
# client-observed proxy latency (admission at the front-end to the final
# verdict, failover hops included)
FLEET_REQUESTS_TOTAL = "fleet_requests_total"
FLEET_REQUEST_SECONDS = "fleet_request_seconds"
# SLO plane (obs.slo, ISSUE 14): multi-window burn rates and the error
# budget, computed from the request counters/histograms above (replica:
# serving_requests_total + serving_request_seconds; fleet:
# fleet_requests_total + fleet_request_seconds). Published on both
# replica and fleet /metrics whenever an objective is declared.
SLO_ERROR_BUDGET_REMAINING = "slo_error_budget_remaining"
SLO_BURN_RATE_FAST = "slo_burn_rate_fast"
SLO_BURN_RATE_SLOW = "slo_burn_rate_slow"
SLO_OBJECTIVE_INFO = "slo_objective_info"
SERVING_BUSY_FRACTION = "serving_busy_fraction"
SERVING_LANE_IDLE_GAP_SECONDS = "serving_lane_idle_gap_seconds"
SERVING_LANE_MFU = "serving_lane_mfu"
SERVING_MFU = "serving_mfu"
SERVING_LANE_PEAK_FLOPS = "serving_lane_peak_flops"
SERVING_PADDING_WASTE_RATIO = "serving_padding_waste_ratio"
SERVING_WINDOW_OCCUPANCY_RATIO = "serving_window_occupancy_ratio"
SERVING_BATCH_ROWS_TOTAL = "serving_batch_rows_total"
SERVING_BUCKET_FILL_RATIO = "serving_bucket_fill_ratio"
# device-time ledger (obs.ledger, ISSUE 16): what each served request
# COSTS — per-dispatch busy seconds prorated across chunk riders by cost
# account, the profiler-sampled per-stage device-time pie, and the
# per-bucket executable memory table. Defined HERE (not in
# serving/metrics.py) for the same reason as the saturation names: the
# ledger lives in jax-/numpy-free obs/ and obs must not import serving.
SERVING_DEVICE_SECONDS_TOTAL = "serving_device_seconds_total"
SERVING_DEVICE_SECONDS_PER_REQUEST = "serving_device_seconds_per_request"
SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN = (
    "serving_device_seconds_per_request_mean"
)
SERVING_DEVICE_TIME_SHARE = "serving_device_time_share"
SERVING_EXECUTABLE_HBM_BYTES = "serving_executable_hbm_bytes"
LEDGER_PROFILE_SKIPPED_TOTAL = "ledger_profile_skipped_total"
# content-addressed result tier (cache/ subsystem, ISSUE 19): lookup
# outcomes by tier — router (a hit never spends a WRR round), replica
# (store in front of the batcher) and inflight (the batcher dedup window
# plus volume-gang coalescing) — and the replica store's resident bytes.
# Defined HERE for the fleet reason: the cache package is jax-/numpy-free
# by contract and the router-side store must not import serving.
SERVING_RESULT_CACHE_HIT_TOTAL = "serving_result_cache_hit_total"
SERVING_RESULT_CACHE_MISS_TOTAL = "serving_result_cache_miss_total"
SERVING_RESULT_CACHE_FILL_TOTAL = "serving_result_cache_fill_total"
SERVING_RESULT_CACHE_EVICT_TOTAL = "serving_result_cache_evict_total"
SERVING_RESULT_CACHE_BYTES = "serving_result_cache_bytes"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency buckets in seconds, spanning sub-ms device dispatches to the
# multi-minute cohort sections the volume driver times per patient.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise ValueError(f"invalid metric name: {name!r}")
    return name


def _check_labels(labels: Dict[str, str]) -> Dict[str, str]:
    out = {}
    for k in sorted(labels):
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name: {k!r}")
        out[k] = str(labels[k])
    return out


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    """One (name, labels) series. Subclasses define the value semantics."""

    kind = "untyped"

    def __init__(self, name: str, labels: Dict[str, str], help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        # RLock, not Lock: bench's SIGTERM handler snapshots the registry on
        # the main thread, possibly interrupting a frame that already holds
        # this lock — a non-reentrant lock would deadlock the guaranteed-emit
        # path (same-thread re-acquisition must succeed)
        self._lock = threading.RLock()


class Counter(_Metric):
    """Monotone non-decreasing accumulator (Prometheus counter semantics)."""

    kind = "counter"

    def __init__(self, name, labels, help=""):
        super().__init__(name, labels, help)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc by {amount})"
            )
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render(self) -> dict:
        return {"value": self.value}


class Gauge(_Metric):
    """Point-in-time value; may move in both directions."""

    kind = "gauge"

    def __init__(self, name, labels, help=""):
        super().__init__(name, labels, help)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render(self) -> dict:
        return {"value": self.value}


class Histogram(_Metric):
    """Bucketed distribution with Prometheus cumulative-``le`` semantics."""

    kind = "histogram"

    def __init__(self, name, labels, help="", buckets: Iterable[float] = None):
        super().__init__(name, labels, help)
        bounds = tuple(buckets) if buckets is not None else DEFAULT_LATENCY_BUCKETS
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError(f"histogram buckets must be strictly increasing: {bounds}")
        if not all(math.isfinite(b) for b in bounds):
            # the +Inf bucket is implicit (always last); a non-finite bound
            # must fail here, at creation, not at snapshot/export time
            raise ValueError(f"histogram buckets must be finite: {bounds}")
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        # per-bucket (non-cumulative) counts; the +Inf bucket is the last slot
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _state(self) -> Tuple[List[Tuple[str, int]], float, int]:
        """(cumulative buckets, sum, count) read under ONE lock hold, so a
        concurrent observe() can never tear a snapshot (a torn +Inf-vs-count
        pair would fail the check_telemetry gate on a file the registry
        itself wrote)."""
        with self._lock:
            out, acc = [], 0
            for b, c in zip(self.bounds, self._counts):
                acc += c
                out.append((repr(b) if b != int(b) else str(int(b)), acc))
            out.append(("+Inf", acc + self._counts[-1]))
            return out, self._sum, self._count

    def cumulative(self) -> List[Tuple[str, int]]:
        """[(le-string, cumulative count)] ending with ('+Inf', total)."""
        return self._state()[0]

    def _render(self) -> dict:
        cum, s, c = self._state()
        return {"buckets": [[le, n] for le, n in cum], "sum": s, "count": c}


class MetricsRegistry:
    """Get-or-create home for every metric series of one run."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.RLock()  # signal-handler reentrancy (see _Metric)
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], _Metric] = {}
        self._kind_by_name: Dict[str, str] = {}

    def _get_or_create(self, cls, name, help, labels, **kwargs) -> _Metric:
        _check_name(name)
        labels = _check_labels(labels)
        key = (name, tuple(labels.items()))
        with self._lock:
            existing_kind = self._kind_by_name.get(name)
            if existing_kind is not None and existing_kind != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {existing_kind}, "
                    f"requested {cls.kind}"
                )
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, labels, help=help, **kwargs)
                self._metrics[key] = m
                self._kind_by_name[name] = cls.kind
            return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(
        self, name: str, help: str = "", buckets: Iterable[float] = None, **labels
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels, buckets=buckets)

    def get(self, name: str, **labels) -> Optional[_Metric]:
        """Existing series or None (never creates; for tests/validators)."""
        key = (name, tuple(_check_labels(labels).items()))
        with self._lock:
            return self._metrics.get(key)

    def series(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def counter_totals(self) -> Dict[str, float]:
        """Sum of every counter across its label sets (heartbeat payload)."""
        out: Dict[str, float] = {}
        for m in self.series():
            if isinstance(m, Counter):
                out[m.name] = out.get(m.name, 0.0) + m.value
        return {k: out[k] for k in sorted(out)}

    # -- export ------------------------------------------------------------

    def snapshot(
        self, run_id: Optional[str] = None, git_sha: Optional[str] = None
    ) -> dict:
        """JSON-able snapshot (schema ``nm03.metrics.v1``)."""
        metrics = []
        for m in sorted(self.series(), key=lambda m: (m.name, sorted(m.labels.items()))):
            rec = {"name": m.name, "type": m.kind, "labels": m.labels}
            if m.help:
                rec["help"] = m.help
            rec.update(m._render())
            metrics.append(rec)
        return {
            "schema": SCHEMA_METRICS,
            "run_id": run_id,
            "git_sha": git_sha,
            "created_unix": round(time.time(), 3),
            "metrics": metrics,
        }

    def write_snapshot(self, path, run_id=None, git_sha=None) -> None:
        import os

        path = str(path)
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(run_id=run_id, git_sha=git_sha), f, indent=1)
            f.write("\n")
        os.replace(tmp, path)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        by_name: Dict[str, List[_Metric]] = {}
        for m in self.series():
            by_name.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_name):
            group = by_name[name]
            help_text = next((m.help for m in group if m.help), "")
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {group[0].kind}")
            for m in sorted(group, key=lambda m: sorted(m.labels.items())):
                if isinstance(m, Histogram):
                    buckets, h_sum, h_count = m._state()  # one atomic read
                    for le, cum in buckets:
                        le_sel = f'le="{le}"'
                        lines.append(
                            f"{name}_bucket{_format_labels(m.labels, le_sel)} {cum}"
                        )
                    lines.append(f"{name}_sum{_format_labels(m.labels)} {h_sum}")
                    lines.append(f"{name}_count{_format_labels(m.labels)} {h_count}")
                else:
                    v = m.value
                    out = int(v) if float(v).is_integer() else v
                    lines.append(f"{name}{_format_labels(m.labels)} {out}")
        return "\n".join(lines) + "\n" if lines else ""
