"""ctypes bindings for the native C++ runtime (csrc/nm03native.cpp).

The reference's host-side runtime — DICOM import, batch-parallel decode,
JPEG export — is native C++ (FAST/Qt/OpenMP). This package binds the
TPU framework's own native layer the same way the rest of the system is
built: no pybind11, just a C ABI loaded via ctypes.

The shared library is compiled on first use with g++ (cached under
``csrc/build/``, keyed by a source hash) or can be prebuilt with
``cmake csrc && make``. Every entry point has a pure-Python fallback
(data.dicomlite, PIL) so the framework still runs where no C++ toolchain
exists; ``available()`` says which path is active, and
``NM03_NO_NATIVE=1`` forces the fallback.
"""

from __future__ import annotations

import ctypes
import os
import threading
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np

from nm03_capstone_project_tpu.utils.reporter import get_logger

_log = get_logger("native")

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "csrc" / "nm03native.cpp"
_BUILD_DIR = _REPO_ROOT / "csrc" / "build"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _compile() -> Optional[Path]:
    """Build the shared library with g++; returns its path or None."""
    from nm03_capstone_project_tpu.native.buildlib import build_shared_library

    # -ffp-contract=off: the host-export renderer mirrors NumPy's f32
    # arithmetic operation for operation; letting the compiler contract the
    # lerp into FMAs would break the byte-identical-render guarantee
    return build_shared_library(
        _SRC, _BUILD_DIR, "nm03native", ["-pthread", "-ffp-contract=off"], _log
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("NM03_NO_NATIVE") == "1":
            _log.info("native layer disabled via NM03_NO_NATIVE")
            return None
        path = _compile()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError as e:
            _log.warning("failed to load %s: %s", path, e)
            return None

        lib.nm03_last_error.restype = ctypes.c_char_p
        lib.nm03_version.restype = ctypes.c_int
        lib.nm03_dicom_read.restype = ctypes.c_int
        lib.nm03_dicom_read.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.nm03_load_batch.restype = ctypes.c_int
        lib.nm03_load_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.nm03_jpeg_encode_gray.restype = ctypes.c_long
        lib.nm03_jpeg_encode_gray.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.c_long,
        ]
        lib.nm03_render_pair.restype = ctypes.c_int
        lib.nm03_render_pair.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.POINTER(ctypes.c_ubyte),
        ]
        _lib = lib
        _log.info("native layer loaded (%s)", path.name)
        return _lib


def available() -> bool:
    """True when the native shared library is loaded (or loadable)."""
    return _load() is not None


def last_error() -> str:
    lib = _load()
    return lib.nm03_last_error().decode() if lib else "native layer unavailable"


def read_dicom_native(path: str | os.PathLike,
                      max_dim: int = 4096) -> np.ndarray:
    """Decode one DICOM slice via the C++ parser → float32 (rows, cols).

    Raises ValueError on parse failure (same failure surface as
    data.dicomlite.read_dicom).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    buf = np.empty(max_dim * max_dim, np.float32)
    rows = ctypes.c_int(0)
    cols = ctypes.c_int(0)
    rc = lib.nm03_dicom_read(
        os.fspath(path).encode(),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        buf.size,
        ctypes.byref(rows),
        ctypes.byref(cols),
    )
    if rc != 0:
        raise ValueError(f"native DICOM decode failed: {last_error()}")
    return buf[: rows.value * cols.value].reshape(rows.value, cols.value).copy()


# error codes returned per-slice by nm03_load_batch
BATCH_ERRORS = {
    0: "ok",
    1: "cannot read file",
    2: "DICOM parse failed",
    3: "image dimensions too small",
    4: "slice exceeds canvas; raise --canvas",
}


def load_batch_native(
    paths: Sequence[str | os.PathLike],
    canvas: int,
    min_dim: int,
    threads: int = 8,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Threaded decode of a slice batch into a padded canvas arena.

    Returns (pixels, dims, ok, err): pixels (n, canvas, canvas) float32
    zero-padded, dims (n, 2) int32 rows/cols, ok (n,) bool, err (n,) int32
    per-slice failure codes (see BATCH_ERRORS). Failed slices have ok=False
    and keep min_dim dims + a zero slot — the contract _pad_stack/_read_slice
    implement in Python (cli/runner.py).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    n = len(paths)
    pixels = np.zeros((n, canvas, canvas), np.float32)
    dims = np.full((n, 2), min_dim, np.int32)
    ok = np.zeros(n, np.uint8)
    err = np.zeros(n, np.int32)
    if n == 0:
        return pixels, dims, ok.astype(bool), err
    encoded = [os.fspath(p).encode() for p in paths]
    arr = (ctypes.c_char_p * n)(*encoded)
    lib.nm03_load_batch(
        arr, n, canvas, canvas, min_dim, threads,
        pixels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        err.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
    )
    return pixels, dims, ok.astype(bool), err


def encode_jpeg_gray(image: np.ndarray, quality: int = 90) -> bytes:
    """Encode a uint8 grayscale (H, W) array as baseline JPEG bytes."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    arr = np.ascontiguousarray(image)
    if arr.dtype != np.uint8 or arr.ndim != 2:
        raise ValueError(f"expected 2D uint8 image, got {arr.dtype} {arr.shape}")
    h, w = arr.shape
    cap = h * w * 2 + 4096  # worst case far below uncompressed x2 + headers
    out = np.empty(cap, np.uint8)
    n = lib.nm03_jpeg_encode_gray(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        h, w, quality,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        cap,
    )
    if n < 0:
        raise ValueError(f"native JPEG encode failed: {last_error()}")
    return out[:n].tobytes()


def render_pair_native(
    pixels: np.ndarray, mask: np.ndarray, dims, cfg
) -> "tuple[np.ndarray, np.ndarray]":
    """C++ twin of render.host_render.host_render_pair — identical bytes.

    ``pixels``: (canvas, canvas) float32 padded slice; ``mask``: uint8 canvas
    mask; ``dims``: true (h, w). Returns the (gray, seg) uint8 pair at
    ``cfg.render_size``. Raises RuntimeError when the native layer is
    unavailable (callers fall back to the NumPy renderer).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native layer unavailable")
    px = np.ascontiguousarray(pixels, np.float32)
    mk = np.ascontiguousarray(mask, np.uint8)
    h, w = int(dims[0]), int(dims[1])
    out = int(cfg.render_size)
    gray = np.empty((out, out), np.uint8)
    seg = np.empty((out, out), np.uint8)
    rc = lib.nm03_render_pair(
        px.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        px.shape[0], px.shape[1],
        mk.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        mk.shape[0], mk.shape[1],
        h, w, out,
        ctypes.c_float(cfg.overlay_opacity),
        ctypes.c_float(cfg.overlay_border_opacity),
        int(cfg.overlay_border_radius),
        gray.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        seg.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
    )
    if rc != 0:
        raise ValueError(f"native render failed: {last_error()}")
    return gray, seg
