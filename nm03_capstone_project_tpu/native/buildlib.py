"""Shared build-on-first-use scheme for the ctypes-bound C++ layers.

One implementation of the compile-cache-publish dance used by both native
shims (csrc/nm03native.cpp via native/__init__.py and csrc/nm03gdcm.cpp via
data/gdcm_fallback.py): output keyed by a source hash so edits rebuild,
compiled to a process-private temp name and published atomically so a
concurrent process never CDLL-loads a half-written library, stale builds of
older source revisions pruned. Every failure mode (missing toolchain,
compile error, read-only build dir) returns None — callers degrade to their
pure-Python fallbacks, never crash.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
from pathlib import Path
from typing import Optional, Sequence


def build_shared_library(
    src: Path,
    build_dir: Path,
    stem: str,
    extra_flags: Sequence[str],
    log: logging.Logger,
    timeout_s: float = 180.0,
    failure_level: int = logging.WARNING,
) -> Optional[Path]:
    """Compile ``src`` to ``build_dir/lib{stem}-{hash}.so``; None on failure.

    ``failure_level``: severity for build failures — WARNING for mandatory
    fast paths (a fallback exists but the operator should know), INFO for
    deliberately-optional shims whose absence is expected behavior.
    """
    try:
        if not src.exists():
            log.log(failure_level, "native source %s not found", src)
            return None
        tag = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
        out = build_dir / f"lib{stem}-{tag}.so"
        if out.exists():
            return out
        build_dir.mkdir(parents=True, exist_ok=True)
    except OSError as e:
        # read-only install etc. — degrade, never crash the caller's contract
        log.info("build dir unavailable for %s: %s", stem, e)
        return None
    tmp = out.with_name(f".{out.name}.{os.getpid()}.tmp")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        str(src), *extra_flags, "-o", str(tmp),
    ]
    try:
        # nm03-lint: disable=NM422 callers hold their one-shot load lock across this build ON PURPOSE: peers must wait for the artifact instead of racing g++ for the same .so
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.log(failure_level, "build of %s failed to run: %s", stem, e)
        return None
    if proc.returncode != 0:
        log.log(failure_level, "build of %s failed:\n%s", stem, proc.stderr[-2000:])
        tmp.unlink(missing_ok=True)
        return None
    try:
        os.replace(tmp, out)
        for old in build_dir.glob(f"lib{stem}-*.so"):
            if old != out:
                try:
                    old.unlink()
                except OSError:
                    pass
    except OSError as e:
        log.info("publish of %s failed: %s", stem, e)
        return None
    return out
