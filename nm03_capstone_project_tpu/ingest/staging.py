"""Device staging: THE home of host→HBM placement (NM401).

Every ``jax.device_put`` that feeds batch compute lives here, the way
every jit lives in ``compilehub/`` (NM361): a scattered staging site is a
hidden re-upload the transfer guard can't attribute and the ingest
telemetry can't see. The lint rule NM401 (``analysis/staginghome.py``)
enforces the contract; the reasoned escapes (CPU-degradation fallbacks,
one-time model-parameter placement, bench's measurement harness) carry
suppressions at their sites.

``jax.device_put`` is asynchronous: enqueuing the next batch's H2D copy
while the current batch computes hides the transfer entirely — the
:class:`~nm03_capstone_project_tpu.ingest.pipeline.IngestPipeline`
stager calls :func:`stage_batch` one-to-two batches ahead for exactly
that reason (double buffering; SURVEY.md section 7 step 4 "hard part
#2"). jax is imported lazily so the module costs nothing in jax-free
processes (the package import contract, NM301).
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence, TypeVar

T = TypeVar("T")

# the batch keys the drivers stage by default (host copies kept as
# <key>_host for host-side render/export and the CPU-degradation fallback)
DEFAULT_STAGE_KEYS = ("pixels", "dims")


def stage_batch(
    item: dict,
    keys: Sequence[str] = DEFAULT_STAGE_KEYS,
    placement: Optional[Any] = None,
    keep_host: bool = True,
    host_only: bool = False,
) -> dict:
    """Stage the named array leaves of one batch dict onto the device.

    ``placement`` is a ``jax.Device`` or ``Sharding`` (None = default
    device); with a mesh sharding the H2D copy is already batch-sharded,
    so each chip receives only its shard. ``keep_host=True`` preserves the
    host array as ``<key>_host`` — the host-render export path reads it,
    and the CPU-degradation fallback must never have to fetch from the
    (possibly wedged) device it is escaping.

    ``host_only=True`` skips the device_put entirely but still writes the
    ``<key>_host`` aliases (and never imports jax): the degraded-run mode
    — every dispatch is served by the CPU fallback, so staging onto the
    wedged/lost device would be at best wasted and at worst the very hang
    the degradation escaped, while downstream consumers keep reading one
    key contract.
    """
    out = dict(item)
    if host_only:
        for k in keys:
            if out.get(k) is not None:
                out[f"{k}_host"] = out[k]
        return out
    import jax

    for k in keys:
        v = out.get(k)
        if v is None:
            continue
        if keep_host:
            out[f"{k}_host"] = v
        out[k] = jax.device_put(v, placement)
    return out


def stage_arrays(arrays: Iterable[Any], placement: Optional[Any] = None) -> list:
    """Stage a flat list of arrays (the single-slice drivers' shape)."""
    import jax

    return [jax.device_put(a, placement) for a in arrays]


def stage_volume(volume: Any, dims: Any, mesh: Any) -> tuple:
    """Stage one (D, H, W) study onto a z-sharded mesh; ``(vol, dims)``.

    The volume gang's upload home (ISSUE 15): the stack lands
    ``NamedSharding(mesh, P('z', None, None))`` — each chip receives only
    its z-shard's planes over one H2D enqueue — and ``dims`` replicates.
    Lives here, not in serving/, because host→HBM placement is this
    module's contract (NM401): the whole-volume request path must be as
    visible to the transfer guard and the staging telemetry as the batch
    drivers' feed.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    vol_sh = NamedSharding(mesh, P("z", None, None))
    rep_sh = NamedSharding(mesh, P())
    return (
        jax.device_put(volume, vol_sh),
        jax.device_put(dims, rep_sh),
    )


def prefetch_to_device(
    iterator: Iterable[T],
    depth: int = 2,
    device: Optional[Any] = None,
    to_device: Optional[Callable[[Any], Any]] = None,
) -> Iterator[T]:
    """Yield items from ``iterator`` with arrays staged on device ``depth``
    ahead (absorbed from the retired ``data/prefetch.py`` helper).

    Each item is a pytree; its array leaves are moved with
    ``jax.device_put`` (asynchronous — the copy overlaps whatever the
    device is running). Non-array leaves (strings, metadata) pass through
    untouched. The full :class:`..pipeline.IngestPipeline` supersedes this
    for the drivers (it adds the decode pool, backpressure ring, fault
    site and telemetry); this stays as the minimal generator form for
    library callers with pre-decoded streams.

    Args:
      iterator: source of pytree batches.
      depth: how many batches to keep in flight (2 = double buffering).
      device: target `jax.Device` or `Sharding` (default backend's device 0).
      to_device: override the per-item transfer (e.g. to apply a
        NamedSharding to some leaves only).
    """
    import jax

    it = iter(iterator)
    if to_device is None:
        tgt = device if device is not None else jax.devices()[0]

        def to_device(item):
            return jax.tree.map(
                lambda x: jax.device_put(x, tgt) if hasattr(x, "shape") else x,
                item,
            )

    queue: collections.deque = collections.deque()

    def enqueue(n: int) -> None:
        for item in itertools.islice(it, n):
            queue.append(to_device(item))

    enqueue(max(depth, 1))
    while queue:
        out = queue.popleft()
        enqueue(1)
        yield out
