"""The host→HBM ingest pipeline: decode ahead, stage ahead, never starve.

This is the single home for getting bytes onto the chip (ROADMAP item 1,
the OpenCLIPER thesis applied to the data path): PR 10's telemetry pinned
both batch drivers' serial decode→stage→dispatch→fetch loops as the
``feed_stall`` — the device idle a large fraction of wall while the host
finished its turn. The pipeline dissolves the turn-taking:

* a **decode pool** (``decode_workers`` threads) runs the caller's
  ``decode`` callable up to ``decode_workers`` work items ahead, results
  collected strictly in order;
* a bounded **staging ring** (:class:`.ring.StagingRing`, depth
  ``depth``) holds decoded host batches — its capacity is the
  backpressure contract: when HBM-side consumption stalls, the ring
  fills, the feeder blocks, and decode can never outrun the chip;
* a **stager thread** runs the caller's ``stage`` callable (the
  ``jax.device_put`` upload — built by :mod:`.staging`, the NM401 home)
  one-to-two batches ahead of compute, so batch N+1's H2D copy overlaps
  batch N's execution (``device_put`` is asynchronous; double/triple
  buffering per ``staged_depth``);
* the **consumer** (the driver loop) iterates staged batches; donated
  program inputs recycle their HBM because the pipeline drops every
  reference the moment a batch is handed out;
* result fetch streams back through :meth:`submit` on the same pool,
  overlapped with the next batch's compute.

Instrumentation is built in, not bolted on: the caller's
:class:`~nm03_capstone_project_tpu.obs.saturation.PhaseAccountant`
receives the decode/stage busy intervals from the worker threads (so the
same ``pipeline_feed_stall_ratio`` that pinned the before-number proves
the after-number), and :meth:`stats`/:meth:`publish` expose ring
occupancy, decode queue depth, and the upload↔compute overlap ratio
(``ingest_*`` gauges, docs/OBSERVABILITY.md).

Fault site ``ingest`` (docs/RESILIENCE.md): ``decode_error`` fails one
work item through the ordinary containment path (an
:class:`IngestFailure` record the driver counts, never a crashed run);
``stall`` wedges the stager for ``hang_s`` seconds — the drill that
proves backpressure holds and the run completes anyway.

jax-free at import by the package contract (NM301): jax enters only
through the caller-supplied ``stage`` callable. Thread-shared state is
lock-guarded (NM331 — this package is in the rule's scanned scope).
"""

from __future__ import annotations

import collections
import concurrent.futures as cf
import threading
import time
from typing import Callable, Iterable, Optional

from nm03_capstone_project_tpu.ingest.ring import (
    RingClosed,
    RingFinished,
    StagingRing,
)

# default ring depth: one batch decoding, one staged, one in reserve —
# triple buffering without holding a whole cohort of host batches alive
DEFAULT_DEPTH = 3
# staged (device-side) lookahead: the upload queue. 2 = double buffering —
# batch N computing, batch N+1's upload enqueued; deeper holds more HBM
# hostage for no additional overlap
DEFAULT_STAGED_DEPTH = 2
# bound on the interval evidence kept for the overlap ratio: past this the
# oldest intervals age out of the *detail* (the ratio then reflects the
# most recent window — bounded memory for arbitrarily long cohorts)
MAX_INTERVALS = 4096


class IngestFailure:
    """One work item that failed decode; rides the pipeline as a record so
    failure handling stays in item order (the drivers' containment
    contract: a bad batch is counted, never propagated)."""

    __slots__ = ("index", "item", "error")

    def __init__(self, index: int, item, error: BaseException):
        self.index = index
        self.item = item
        self.error = error

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"IngestFailure(index={self.index}, error={self.error!r})"


def _union(intervals) -> list:
    """Sorted disjoint union of (t0, t1) intervals."""
    out: list = []
    for t0, t1 in sorted(intervals):
        if t1 <= t0:
            continue
        if out and t0 <= out[-1][1]:
            out[-1][1] = max(out[-1][1], t1)
        else:
            out.append([t0, t1])
    return out


def _intersection_seconds(a, b) -> float:
    """Total overlap between two interval sets (unions taken first)."""
    ua, ub = _union(a), _union(b)
    i = j = 0
    total = 0.0
    while i < len(ua) and j < len(ub):
        lo = max(ua[i][0], ub[j][0])
        hi = min(ua[i][1], ub[j][1])
        if hi > lo:
            total += hi - lo
        if ua[i][1] <= ub[j][1]:
            i += 1
        else:
            j += 1
    return total


def publish_gauges(registry, occupancy, queue_depth, overlap=None) -> None:
    """THE one home of the ``ingest_*`` gauge registrations: both the
    per-pipeline :meth:`IngestPipeline.publish` and the drivers' run-level
    drained aggregate set them through here, so names/help can never
    drift between the two call sites."""
    from nm03_capstone_project_tpu.obs.metrics import (
        INGEST_DECODE_QUEUE_DEPTH,
        INGEST_RING_OCCUPANCY_RATIO,
        INGEST_UPLOAD_OVERLAP_RATIO,
    )

    registry.gauge(
        INGEST_RING_OCCUPANCY_RATIO,
        help="time-weighted mean fill fraction of the ingest staging "
        "ring (~1 = chip-bound with backpressure holding decode; ~0 = "
        "decode-bound, the chip waits)",
    ).set(occupancy)
    registry.gauge(
        INGEST_DECODE_QUEUE_DEPTH,
        help="decode work items in flight on the ingest pool (the final "
        "--metrics-out snapshot carries the run's high-water mark)",
    ).set(queue_depth)
    if overlap is not None:
        registry.gauge(
            INGEST_UPLOAD_OVERLAP_RATIO,
            help="fraction of the stager's staging-call wall that "
            "overlapped the consumer's compute window (~1 = staging never "
            "blocked compute). On synchronous backends the call IS the "
            "copy; on async backends it is the enqueue — read it as "
            "'staging off the critical path', not a DMA measurement",
        ).set(overlap)


class IngestPipeline:
    """Decode-pool → staging-ring → stager → consumer, with backpressure.

    Use as a context manager; iterate for staged records in source order::

        with IngestPipeline(source=batches, decode=dec, stage=stg) as pipe:
            for batch in pipe:          # staged, in order
                out = run(batch)        # dispatch (the caller's phase)
                pipe.submit(fetch, out) # result fetch off the feed path
        pipe.stats()                    # drained-at-exit snapshot

    ``decode(item)`` runs on pool threads (must be thread-safe across
    items); ``stage(decoded)`` runs on the single stager thread. A decode
    exception becomes an :class:`IngestFailure` record; a stage exception
    aborts the pipeline (staging failures are device-path failures the
    driver's supervisor owns, not per-item noise).
    """

    def __init__(
        self,
        source: Iterable,
        decode: Callable,
        stage: Optional[Callable] = None,
        *,
        depth: int = DEFAULT_DEPTH,
        decode_workers: int = 4,
        staged_depth: int = DEFAULT_STAGED_DEPTH,
        feed=None,
        spans=None,
        obs=None,
        fault_plan=None,
        fault_patient: Optional[str] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if decode_workers < 1:
            raise ValueError(
                f"decode_workers must be >= 1, got {decode_workers}"
            )
        if staged_depth < 1:
            raise ValueError(f"staged_depth must be >= 1, got {staged_depth}")
        self._source = source
        self._decode = decode
        self._stage = stage
        self.depth = int(depth)
        self.decode_workers = int(decode_workers)
        self._feed = feed
        self._spans = spans
        self._obs = obs
        self._fault_plan = fault_plan
        self._fault_patient = fault_patient
        self._clock = clock
        self._ring = StagingRing(depth, clock=clock)
        self._staged = StagingRing(staged_depth, clock=clock)
        self._pool = cf.ThreadPoolExecutor(
            max_workers=self.decode_workers, thread_name_prefix="nm03-ingest"
        )
        self._cancel = threading.Event()
        self._lock = threading.Lock()
        self._started = False
        self._error: Optional[BaseException] = None
        self._feeder: Optional[threading.Thread] = None
        self._stager: Optional[threading.Thread] = None
        # telemetry (all guarded by _lock)
        self._decode_inflight = 0
        self._decode_inflight_peak = 0
        self._counts = {"decoded": 0, "failed": 0, "staged": 0, "yielded": 0}
        self._upload_intervals: collections.deque = collections.deque(
            maxlen=MAX_INTERVALS
        )
        self._consumer_intervals: collections.deque = collections.deque(
            maxlen=MAX_INTERVALS
        )
        self._drained: Optional[dict] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "IngestPipeline":
        with self._lock:
            if self._started:
                return self
            self._started = True
            self._feeder = threading.Thread(
                target=self._feed_loop, name="nm03-ingest-feed", daemon=True
            )
            self._stager = threading.Thread(
                target=self._stage_loop, name="nm03-ingest-stage", daemon=True
            )
        self._feeder.start()
        self._stager.start()
        return self

    def __enter__(self) -> "IngestPipeline":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def close(self) -> None:
        """Tear down: cancel threads, drain the pool, freeze stats().

        Idempotent; safe mid-iteration (a consumer exception must never
        leave the feeder parked on a full ring). Submitted result-fetch
        work is allowed to finish — the pool shutdown waits — so callers
        collect their futures before or after close() identically.
        """
        with self._lock:
            if self._drained is not None:
                return
            # freeze the snapshot BEFORE the rings close (close() clears
            # them): this is the drained-at-exit view publish() exports
            self._drained = self._stats_locked()
        self._cancel.set()
        self._ring.close()
        self._staged.close()
        for t in (self._feeder, self._stager):
            if t is not None and t.is_alive():
                t.join(timeout=10.0)
        self._pool.shutdown(wait=True)

    def submit(self, fn, *args, **kwargs) -> cf.Future:
        """Run ``fn`` on the ingest pool: the home for result fetch/export
        work that should stream back while the next batch computes."""
        return self._pool.submit(fn, *args, **kwargs)

    # -- the three stages --------------------------------------------------

    def _busy(self, phase: str):
        if self._feed is not None:
            return self._feed.busy(phase)
        import contextlib

        return contextlib.nullcontext()

    def _span(self, name: str):
        if self._spans is not None:
            return self._spans.section(name)
        import contextlib

        return contextlib.nullcontext()

    def _fire_fault(self, index: int, item):
        """Consult the ingest fault site for this work item (None when
        off). Returns the fired rule; the caller maps kind→action
        (``decode_error`` raises here, ``stall`` rides the record to the
        stager)."""
        plan = self._fault_plan
        if plan is None or not plan.has_site("ingest"):
            return None
        stem = getattr(item, "stem", None)
        return plan.fire(
            "ingest",
            obs=self._obs,
            patient=self._fault_patient,
            stem=stem,
            index=index,
        )

    def _decode_one(self, index: int, item):
        """Pool-side decode of one work item; containment built in."""
        stall_s = 0.0
        try:
            rule = self._fire_fault(index, item)
            if rule is not None:
                if rule.kind == "decode_error":
                    raise RuntimeError(
                        f"injected ingest decode fault (item {index})"
                    )
                stall_s = rule.hang_s  # applied by the stager
            with self._span("decode"), self._busy("decode"):
                payload = self._decode(item)
            return (index, payload, stall_s)
        except Exception as e:  # noqa: BLE001 - per-item containment
            return IngestFailure(index, item, e)

    def _feed_loop(self) -> None:
        """Submit decodes up to ``decode_workers`` ahead; collect strictly
        in order; push into the ring (a full ring blocks — backpressure)."""
        inflight: collections.deque = collections.deque()
        it = iter(enumerate(self._source))
        exhausted = False
        try:
            while not self._cancel.is_set():
                while not exhausted and len(inflight) < self.decode_workers:
                    try:
                        index, item = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    inflight.append(self._pool.submit(self._decode_one, index, item))
                    with self._lock:
                        self._decode_inflight = len(inflight)
                        if len(inflight) > self._decode_inflight_peak:
                            self._decode_inflight_peak = len(inflight)
                if not inflight:
                    break
                rec = inflight.popleft().result()
                with self._lock:
                    self._decode_inflight = len(inflight)
                    self._counts[
                        "failed" if isinstance(rec, IngestFailure) else "decoded"
                    ] += 1
                self._ring.put(rec)
            self._ring.finish()
        except RingClosed:
            pass  # torn down mid-flight; close() owns the cleanup
        except BaseException as e:  # noqa: BLE001 - surfaced to the consumer
            self._abort(e)

    def _stage_loop(self) -> None:
        """Pop decoded batches in order, upload ahead of compute."""
        try:
            while not self._cancel.is_set():
                try:
                    rec = self._ring.get()
                except RingFinished:
                    break
                if isinstance(rec, IngestFailure):
                    self._staged.put(rec)
                    continue
                index, payload, stall_s = rec
                if stall_s > 0:
                    # injected stager wedge (fault kind "stall"): prove the
                    # ring absorbs it — decode blocks on backpressure, the
                    # run completes late, never wrong. Cancel-aware so
                    # close() is never held hostage by a drill.
                    self._cancel.wait(timeout=stall_s)
                if self._stage is not None:
                    t0 = self._clock()
                    with self._span("stage"), self._busy("stage"):
                        payload = self._stage(payload)
                    with self._lock:
                        self._upload_intervals.append((t0, self._clock()))
                with self._lock:
                    self._counts["staged"] += 1
                self._staged.put((index, payload))
            self._staged.finish()
        except RingClosed:
            pass
        except BaseException as e:  # noqa: BLE001 - surfaced to the consumer
            self._abort(e)

    def _abort(self, error: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = error
        self._cancel.set()
        self._ring.close()
        self._staged.close()

    # -- consumer ----------------------------------------------------------

    def __iter__(self):
        """Staged records in source order (:class:`IngestFailure` for
        contained decode failures). The time between a yield and the next
        request is accounted as the consumer's busy window — the
        denominator side of the upload-overlap ratio."""
        self.start()
        while True:
            try:
                rec = self._staged.get()
            except RingFinished:
                break
            except RingClosed:
                break
            t_yield = self._clock()
            try:
                with self._lock:
                    self._counts["yielded"] += 1
                if isinstance(rec, IngestFailure):
                    yield rec
                else:
                    # hand out the ONLY reference: donated program inputs
                    # must be able to recycle their HBM the moment the
                    # compiled call consumes them
                    index, payload = rec
                    del rec
                    yield payload
            finally:
                with self._lock:
                    self._consumer_intervals.append((t_yield, self._clock()))
        with self._lock:
            err = self._error
        if err is not None:
            raise err

    # -- telemetry ---------------------------------------------------------

    def _stats_locked(self) -> dict:
        uploads = list(self._upload_intervals)
        consumer = list(self._consumer_intervals)
        upload_s = sum(t1 - t0 for t0, t1 in _union(uploads))
        consumer_s = sum(t1 - t0 for t0, t1 in _union(consumer))
        overlap = None
        if upload_s > 0:
            overlap = min(
                _intersection_seconds(uploads, consumer) / upload_s, 1.0
            )
        return {
            "ring": self._ring.stats(),
            "decode_queue_depth": self._decode_inflight,
            "decode_queue_peak": self._decode_inflight_peak,
            "upload_s": round(upload_s, 4),
            "consumer_busy_s": round(consumer_s, 4),
            # fraction of the staging-call wall that ran UNDER the
            # consumer's busy window: ~1.0 = staging never blocked the
            # consumer. On synchronous backends (CPU) the device_put call
            # IS the copy; on async ones it is the enqueue, so this says
            # "staging stayed off the critical path", not "the DMA hid".
            # None = the stage callable never uploaded (host-only runs)
            "upload_overlap_ratio": (
                round(overlap, 4) if overlap is not None else None
            ),
            "counts": dict(self._counts),
        }

    def stats(self) -> dict:
        """Live view, or the frozen drained-at-exit snapshot after
        close() — so a driver's final ``--metrics-out`` write sees the
        run's true totals, not an emptied ring."""
        with self._lock:
            if self._drained is not None:
                return dict(self._drained)
            return self._stats_locked()

    def publish(self, registry) -> dict:
        """Refresh the ``ingest_*`` gauges (docs/OBSERVABILITY.md) from
        :meth:`stats`; returns the snapshot."""
        snap = self.stats()
        if registry is not None:
            publish_gauges(
                registry,
                occupancy=snap["ring"]["occupancy_ratio"],
                queue_depth=snap["decode_queue_depth"],
                overlap=snap["upload_overlap_ratio"],
            )
        return snap
