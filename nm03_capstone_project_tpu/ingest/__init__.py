"""Device-resident streaming ingest: the host→HBM data path, once.

The single home for getting bytes onto the chip (ROADMAP item 1): a
decode thread-pool fills a bounded staging ring of host batches, a stager
overlaps batch N+1's upload with batch N's compute, backpressure
propagates from the ring so decode can never outrun HBM, and result
fetch streams back on the same pool while the next batch runs. Both
batch drivers and bench's streamed feed leg run through here; the
``jax.device_put`` call sites are confined to :mod:`.staging` (lint rule
NM401, mirroring NM361's compile-home contract).

jax-free AND numpy-free at import by the package import contract
(NM301): the orchestration layer must be unit-testable — and its
telemetry drainable — without a backend; jax enters only through the
staging callables at call time.
"""

from nm03_capstone_project_tpu.ingest.pipeline import (  # noqa: F401
    DEFAULT_DEPTH,
    DEFAULT_STAGED_DEPTH,
    IngestFailure,
    IngestPipeline,
)
from nm03_capstone_project_tpu.ingest.ring import (  # noqa: F401
    RingClosed,
    RingFinished,
    StagingRing,
)
from nm03_capstone_project_tpu.ingest.staging import (  # noqa: F401
    prefetch_to_device,
    stage_arrays,
    stage_batch,
    stage_volume,
)
