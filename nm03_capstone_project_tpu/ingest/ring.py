"""Bounded staging ring: the backpressure primitive of the ingest pipeline.

A :class:`StagingRing` is an ordered, bounded, thread-safe hand-off between
one producer stage and one consumer stage of the host→HBM pipeline. It is
deliberately *small*: capacity IS the backpressure contract ("decode can
never outrun HBM" — docs/OPERATIONS.md "Feeding the chip"), so a blocked
``put`` is the mechanism, not a failure.

Beyond Queue semantics it accounts for itself: a time-weighted occupancy
integral (how full the ring sat, on average, over its lifetime — the
``ingest_ring_occupancy_ratio`` gauge) plus peak depth and put/get counts,
all with an injectable monotonic clock so tests pin exact ratios.

Two terminal states, because "no more items" and "abandon ship" are
different facts:

* :meth:`finish` — the producer is done; ``get`` drains the remaining
  items, then raises :class:`RingFinished`;
* :meth:`close` — abort; both ends raise :class:`RingClosed` immediately
  (pending blockers wake), so a consumer exception can never leave a
  producer thread parked on a full ring.

stdlib-only by the ingest package's import contract (NM301): the ring must
be unit-testable — and its occupancy drained into a crash snapshot — from
processes that never paid a backend import.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional


class RingClosed(RuntimeError):
    """The ring was aborted (:meth:`StagingRing.close`)."""


class RingFinished(RuntimeError):
    """The producer finished and every item has been drained."""


class StagingRing:
    """Ordered bounded hand-off with occupancy accounting.

    All mutable state is guarded by one lock (NM331 — the ingest package is
    in the rule's scanned scope); the condition variable shares it.
    """

    def __init__(
        self,
        capacity: int,
        clock: Callable[[], float] = time.monotonic,
    ):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items: collections.deque = collections.deque()
        self._closed = False
        self._finished = False
        # occupancy integral: sum(depth * dt) since construction, advanced
        # on every transition so occupancy_ratio() is exact at any instant
        self._t0 = clock()
        self._t_last = self._t0
        self._occ_integral = 0.0
        self._peak = 0
        self._puts = 0
        self._gets = 0

    # -- accounting (callers hold the lock) --------------------------------

    def _advance(self, now: float) -> None:
        if now > self._t_last:
            # nm03-lint: disable=NM331 every caller holds self._lock (put/get/close via the condition, occupancy_ratio directly) — _advance is the shared tail of their critical sections
            self._occ_integral += len(self._items) * (now - self._t_last)
            self._t_last = now  # nm03-lint: disable=NM331 see above: callers hold the lock

    # -- producer side -----------------------------------------------------

    def put(self, item, timeout: Optional[float] = None) -> None:
        """Append ``item``; blocks while full (this IS the backpressure).

        Raises :class:`RingClosed` if the ring is aborted (before or while
        blocked) and TimeoutError when ``timeout`` elapses first.
        """
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                if self._closed:
                    raise RingClosed("staging ring closed")
                if self._finished:
                    raise RingClosed("staging ring already finished")
                if len(self._items) < self.capacity:
                    break
                remaining = None
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"staging ring full for {timeout}s "
                            f"(capacity {self.capacity})"
                        )
                self._cond.wait(remaining)
            self._advance(self._clock())
            self._items.append(item)
            self._puts += 1
            if len(self._items) > self._peak:
                self._peak = len(self._items)
            self._cond.notify_all()

    def finish(self) -> None:
        """Producer done: drain-then-:class:`RingFinished` for the consumer."""
        with self._cond:
            self._finished = True
            self._cond.notify_all()

    # -- consumer side -----------------------------------------------------

    def get(self, timeout: Optional[float] = None):
        """Pop the oldest item; blocks while empty.

        Raises :class:`RingFinished` once the producer finished and the
        ring drained, :class:`RingClosed` on abort, TimeoutError on a
        ``timeout``.
        """
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                if self._closed:
                    raise RingClosed("staging ring closed")
                if self._items:
                    break
                if self._finished:
                    raise RingFinished("staging ring drained")
                remaining = None
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        raise TimeoutError(f"staging ring empty for {timeout}s")
                self._cond.wait(remaining)
            self._advance(self._clock())
            item = self._items.popleft()
            self._gets += 1
            self._cond.notify_all()
            return item

    # -- teardown / introspection ------------------------------------------

    def close(self) -> None:
        """Abort: wake every blocked producer/consumer with RingClosed."""
        with self._cond:
            self._advance(self._clock())
            self._closed = True
            self._items.clear()
            self._cond.notify_all()

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def peak(self) -> int:
        with self._lock:
            return self._peak

    def occupancy_ratio(self) -> float:
        """Time-weighted mean fill fraction since construction: the
        ``ingest_ring_occupancy_ratio`` gauge. ~1.0 = the consumer is the
        bottleneck (good: the chip is saturated and backpressure holds the
        decoders); ~0.0 = the decoders can't keep the ring fed."""
        with self._lock:
            now = self._clock()
            self._advance(now)
            elapsed = now - self._t0
            if elapsed <= 0:
                return 0.0
            return min(self._occ_integral / (elapsed * self.capacity), 1.0)

    def stats(self) -> dict:
        with self._lock:
            depth, peak = len(self._items), self._peak
            puts, gets = self._puts, self._gets
        return {
            "capacity": self.capacity,
            "depth": depth,
            "peak": peak,
            "puts": puts,
            "gets": gets,
            "occupancy_ratio": round(self.occupancy_ratio(), 4),
        }
