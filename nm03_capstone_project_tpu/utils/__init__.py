"""Aux subsystems: reporting, timing, manifest/resume, profiling."""

from nm03_capstone_project_tpu.utils.manifest import Manifest  # noqa: F401
from nm03_capstone_project_tpu.utils.reporter import (  # noqa: F401
    configure_reporting,
    get_logger,
)
from nm03_capstone_project_tpu.utils.timing import (  # noqa: F401
    Timer,
    timeit_sync,
    write_results_json,
)
