"""In-tree timing harness + results JSON.

The reference benchmarks *outside* the repo (hyperfine / ``time``,
README.md:90-96) and gitignores the results
(parallel_results.json/sequential_results.json, .gitignore:46-47). Per
SURVEY.md section 5, this framework keeps the harness in-tree: wall-clock
sections with device synchronization (``block_until_ready``), per-stage
accumulation, and a writer for the results JSON the reference kept
out-of-tree.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Dict, List

from nm03_capstone_project_tpu.utils.atomicio import atomic_write_text


def sync(tree) -> None:
    """Block until every array in the pytree is computed (honest timing).

    ``block_until_ready`` alone is not trustworthy on every backend: on the
    tunneled TPU platform it returns before execution finishes (bench.py
    measured a flat 0.02 ms regardless of problem size). A device_get is the
    only universal synchronization, so on non-CPU backends this additionally
    fetches one element per array — a tiny slice enqueued after the producer
    on the same FIFO stream, whose arrival proves the producer ran.
    """
    import jax

    leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(tree)
        if isinstance(leaf, jax.Array) and leaf.size
    ]
    jax.block_until_ready(leaves)
    probes = [
        leaf[(0,) * leaf.ndim]  # true 1-element slice, no O(n) reshape
        for leaf in leaves
        if leaf.devices() and next(iter(leaf.devices())).platform != "cpu"
    ]
    if probes:
        jax.device_get(probes)


# Timer is superseded by (and now aliases) the obs span recorder: same
# section()/report()/sections/counts contract, plus nested-span tracking and
# optional per-stage latency histograms when built with a registry. Kept
# under its old name so existing imports and call sites stay valid.
from nm03_capstone_project_tpu.obs.spans import SpanRecorder as Timer  # noqa: E402,F401


def timeit_sync(fn, *args, warmup: int = 1, iters: int = 5) -> Dict[str, float]:
    """Median/mean wall-clock of fn(*args) with device sync each call."""
    for _ in range(warmup):
        sync(fn(*args))
    times: List[float] = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(fn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return {
        "median_s": times[len(times) // 2],
        "mean_s": sum(times) / len(times),
        "min_s": times[0],
        "iters": iters,
    }


def git_sha() -> str:
    """Short SHA of HEAD (+ ``-dirty``) of the repo containing this package.

    Every results artifact carries the SHA it measured: the round-2 chip
    record went stale against HEAD with nothing in the file to prove which
    code it timed (VERDICT r2 weak item 5)."""
    import subprocess

    root = Path(__file__).resolve().parents[2]
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10, cwd=root,
        ).stdout.strip()
        dirty = subprocess.run(
            ["git", "status", "--porcelain"],
            capture_output=True, text=True, timeout=10, cwd=root,
        ).stdout.strip()
        return sha + ("-dirty" if dirty else "") if sha else "unknown"
    except Exception:  # noqa: BLE001 — stamping must never break a run
        return "unknown"


def write_results_json(path: str, payload: dict) -> None:
    """The in-tree replacement for the reference's out-of-tree results files
    (always stamped with the git SHA the numbers were measured at)."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    payload = {**payload, "git_sha": payload.get("git_sha", git_sha())}
    # atomic (NM351): a results JSON is a gate input (check_bench_
    # regression, judges) — a kill mid-write must never leave half a record
    atomic_write_text(p, json.dumps(payload, indent=1, sort_keys=True) + "\n")
