"""Atomic artifact writes — THE tmp+rename idiom, in one place.

Every artifact this codebase promises to readers (results JSON, manifests,
checkpoint sidecars, cached synthetic inputs, baselines) must be written
complete-or-not-at-all: a SIGTERM/SIGKILL/ENOSPC mid-write may leave a
stray ``<path>.tmp``, never a torn file that parses as truth
(docs/RESILIENCE.md; lint rule NM351 in docs/STATIC_ANALYSIS.md enforces
the idiom statically). These helpers are that idiom's single point of
correctness — hand-rolling it per call site is how the six slightly
different copies this module replaced happened.

stdlib-only by design: callers include jax-free contract modules.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from pathlib import Path


def atomic_write_bytes(path: str | os.PathLike, payload: bytes) -> None:
    """Write ``payload`` to ``path`` atomically (private tmp + os.replace).

    The tmp file comes from ``mkstemp`` in the target's directory, so two
    concurrent writers of the same artifact each write a PRIVATE temp and
    the outcome is last-complete-writer-wins — a fixed ``<path>.tmp``
    sibling would let one writer rename the other's half-written bytes
    into place (two racing synthetic-cohort generators, two runs updating
    the same results JSON).
    """
    p = Path(path)
    fd, tmp = tempfile.mkstemp(dir=p.parent, prefix=p.name + ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        # mkstemp creates 0600; published artifacts should carry the same
        # umask-derived mode a plain open() would have given them
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(tmp, 0o666 & ~umask)
        os.replace(tmp, p)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def atomic_write_text(path: str | os.PathLike, text: str) -> None:
    """Write ``text`` to ``path`` atomically; see :func:`atomic_write_bytes`."""
    atomic_write_bytes(path, text.encode("utf-8"))
