"""Runtime lockdep: the instrumented-lock witness twin of NM421/NM422.

The static analysis (:mod:`nm03_capstone_project_tpu.analysis.lockorder`)
proves a *may-hold* graph from source; this module observes the *actual*
one. :func:`install` patches ``threading.Lock``/``threading.RLock`` so that
every lock **created by package code after the patch** is wrapped: each
acquire records the acquiring thread's currently-held set and adds
``held -> acquired`` edges to an observed acquisition-order graph, detects
inversions live (an edge whose reverse was already observed — the runtime
face of an NM421 cycle, caught on the FIRST inverted pair, not the eventual
deadlock), and flags holds that exceed an optional budget. The result dumps
as ``lockdep_witness.json`` (tmp+rename, NM351), which
``scripts/check_static.py --lockdep-witness`` gates: zero inversions, zero
observed cycles, and every observed edge explained by the static graph —
so "the lock discipline is sound" is a *checked* claim on a real serving
drill, not a belief.

Opt-in and zero-overhead when off, like every ``--sanitize`` twin:
nothing here runs unless :func:`install` is called (the server calls
:func:`install_from_env`, gated on ``NM03_LOCKDEP=1``). Production pays
nothing — the factories are untouched and no wrapper exists.

Scope rules (why "created by package code"):

* stdlib internals (``queue``, ``concurrent.futures``, ``threading.Event``,
  ``Thread``'s started-flag) create locks from stdlib frames — they pass
  through uninstrumented, so the witness speaks only about the package's
  own ~40 lock sites; a C extension creating a lock under a package frame
  (numpy's BitGenerator) is filtered by requiring the creating source line
  to spell ``Lock``/``RLock``/``Condition``;
* a lock's identity is its **creation site** ``path:line`` — exactly the
  registry key the static graph uses, so the witness maps 1:1 onto
  :class:`~nm03_capstone_project_tpu.analysis.lockorder.LockGraph.by_site`;
* tests may pass ``extra_prefixes`` to also instrument fixture locks
  (the ABBA inversion battery creates its pair inside tests/).
"""

from __future__ import annotations

import json
import linecache
import os
import re
import sys
import threading
import time
import traceback
from pathlib import Path
from typing import Dict, List, Optional, Tuple

__all__ = [
    "install",
    "install_from_env",
    "uninstall",
    "active",
    "state",
    "dump_witness",
    "LockdepState",
]

_ENV_FLAG = "NM03_LOCKDEP"
_ENV_BUDGET = "NM03_LOCKDEP_BUDGET_MS"
_ENV_WITNESS = "NM03_LOCKDEP_WITNESS"

_STATE: Optional["LockdepState"] = None
_ORIG: Optional[Tuple[type, type]] = None

_STACK_LIMIT = 16
_STACK_KEEP = 8
_OVER_BUDGET_CAP = 200


def _short_stack() -> List[str]:
    """Compact formatted stack, trimmed of lockdep/threading noise."""
    here = __file__
    tmod = getattr(threading, "__file__", "")
    out = []
    for fr in traceback.extract_stack(limit=_STACK_LIMIT):
        if fr.filename == here or fr.filename == tmod:
            continue
        out.append(f"{fr.filename}:{fr.lineno} in {fr.name}")
    return out[-_STACK_KEEP:]


class _Site:
    __slots__ = ("id", "path", "line", "kind", "acquires")

    def __init__(self, sid: str, path: str, line: int, kind: str):
        self.id = sid
        self.path = path
        self.line = line
        self.kind = kind
        self.acquires = 0


class LockdepState:
    """One installed lockdep session: sites, edges, inversions, budgets."""

    def __init__(
        self,
        orig_lock,
        budget_s: Optional[float],
        prefixes: Tuple[str, ...],
        repo_root: Path,
    ):
        # a REAL (uninstrumented) lock guards the graph structures
        self._glock = orig_lock()
        self._tls = threading.local()
        self.budget_s = budget_s
        self.prefixes = prefixes
        self.repo_root = repo_root
        self.sites: Dict[Tuple[str, int], _Site] = {}
        self.edges: Dict[Tuple[str, str], Dict] = {}
        self.inversions: List[Dict] = []
        self.over_budget: List[Dict] = []

    # -- identity --------------------------------------------------------

    def site_for(self, path: str, line: int, kind: str) -> _Site:
        with self._glock:
            site = self.sites.get((path, line))
            if site is None:
                site = _Site(f"{path}:{line}", path, line, kind)
                self.sites[(path, line)] = site
            return site

    # -- per-thread held stack -------------------------------------------

    def _held(self) -> List[Tuple[object, _Site, float, bool]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    # -- events ----------------------------------------------------------

    def note_acquire(self, lock: "_InstrumentedLock") -> None:
        held = self._held()
        reentrant = lock._is_rlock and any(e[0] is lock for e in held)
        if not reentrant:
            with self._glock:
                lock._site.acquires += 1
                for hlock, hsite, _t0, _re in held:
                    if hlock is lock:
                        continue
                    self._edge_locked(hsite, lock._site)
        held.append((lock, lock._site, time.monotonic(), reentrant))

    def note_release(self, lock: "_InstrumentedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _l, site, t0, reentrant = held.pop(i)
                if not reentrant and self.budget_s:
                    dur = time.monotonic() - t0
                    if dur > self.budget_s:
                        with self._glock:
                            if len(self.over_budget) < _OVER_BUDGET_CAP:
                                self.over_budget.append(
                                    {
                                        "site": site.id,
                                        "held_s": round(dur, 6),
                                        "budget_s": self.budget_s,
                                        "stack": _short_stack(),
                                    }
                                )
                return
        # acquired before install() or handed across threads: nothing to pop

    def _edge_locked(self, src: _Site, dst: _Site) -> None:
        key = (src.id, dst.id)
        rec = self.edges.get(key)
        if rec is None:
            rec = {"count": 0, "stack": _short_stack()}
            self.edges[key] = rec
            rev = self.edges.get((dst.id, src.id))
            if rev is not None and src.id != dst.id:
                # the runtime NM421: both orders of the same pair observed.
                # Name BOTH stacks — the fix needs the two call paths, and
                # by the time the deadlock fires neither is on a stack.
                self.inversions.append(
                    {
                        "first": src.id,
                        "second": dst.id,
                        "stack": _short_stack(),
                        "prior_stack": list(rev["stack"]),
                    }
                )
        rec["count"] += 1

    # -- artifact --------------------------------------------------------

    def snapshot(self) -> Dict:
        with self._glock:
            return {
                "version": 1,
                "budget_s": self.budget_s,
                "sites": [
                    {
                        "id": s.id,
                        "path": s.path,
                        "line": s.line,
                        "kind": s.kind,
                        "acquires": s.acquires,
                    }
                    for s in sorted(self.sites.values(), key=lambda s: s.id)
                ],
                "edges": [
                    {
                        "src": a,
                        "dst": b,
                        "count": rec["count"],
                        "stack": list(rec["stack"]),
                    }
                    for (a, b), rec in sorted(self.edges.items())
                ],
                "inversions": [dict(i) for i in self.inversions],
                "over_budget": [dict(o) for o in self.over_budget],
            }


class _InstrumentedLock:
    """Drop-in ``threading.Lock`` wrapper that reports to the state.

    Deliberately does NOT expose ``_release_save``/``_acquire_restore``:
    ``threading.Condition`` then falls back to plain ``release()``/
    ``acquire()``, which keeps condition waits flowing through the tracked
    path (the wait's re-acquire is a real acquisition).
    """

    _is_rlock = False
    __slots__ = ("_inner", "_site", "_state")

    def __init__(self, inner, site: _Site, state: LockdepState):
        self._inner = inner
        self._site = site
        self._state = state

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._state.note_acquire(self)
        return ok

    def release(self) -> None:
        self._state.note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<lockdep {self._site.id} wrapping {self._inner!r}>"


class _InstrumentedRLock(_InstrumentedLock):
    _is_rlock = True
    __slots__ = ()

    def locked(self) -> bool:  # RLocks grew .locked() only in 3.12
        locked_fn = getattr(self._inner, "locked", None)
        if locked_fn is not None:
            return locked_fn()
        # acquire-probe fallback; an owner-thread probe would reentrantly
        # succeed, so check ownership first
        if getattr(self._inner, "_is_owned", lambda: False)():
            return True
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def _make_factory(state: LockdepState, orig, rlock: bool):
    kind = "RLock" if rlock else "Lock"
    wrapper = _InstrumentedRLock if rlock else _InstrumentedLock
    tfile = getattr(threading, "__file__", "")
    # the creating line must spell the factory: C extensions (numpy's
    # BitGenerator) call threading.Lock with the PACKAGE caller's frame on
    # top, and instrumenting a foreign internal lock — misattributed to
    # whatever package line invoked the extension — poisons the witness
    factory_re = re.compile(r"\b(?:Lock|RLock|Condition)\b")

    def factory():
        inner = orig()
        f = sys._getframe(1)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:
            return inner
        filename = f.f_code.co_filename
        if filename == tfile:
            # threading-internal creation (Event/Thread/Condition() build
            # their own locks): stdlib-owned, not a package site
            return inner
        if not any(filename.startswith(p) for p in state.prefixes):
            return inner  # stdlib / third-party / pre-existing code paths
        if not factory_re.search(linecache.getline(filename, f.f_lineno)):
            return inner  # C-extension creation under a package frame
        try:
            rel = Path(filename).resolve().relative_to(state.repo_root)
            path = rel.as_posix()
        except ValueError:
            path = filename
        site = state.site_for(path, f.f_lineno, kind)
        return wrapper(inner, site, state)

    factory.__name__ = f"lockdep_{kind}"
    return factory


# -- lifecycle ----------------------------------------------------------------


def active() -> bool:
    return _STATE is not None


def state() -> Optional[LockdepState]:
    return _STATE


def install(
    budget_s: Optional[float] = None,
    extra_prefixes: Tuple[str, ...] = (),
) -> LockdepState:
    """Patch the lock factories; idempotent (returns the live state).

    Only locks created AFTER install are instrumented — construct the
    serving app inside the lockdep window. ``extra_prefixes`` widens the
    instrumented creation-site set beyond the package (test fixtures).
    """
    global _STATE, _ORIG
    if _STATE is not None:
        return _STATE
    pkg_root = Path(__file__).resolve().parents[1]
    repo_root = pkg_root.parent
    prefixes = (str(pkg_root) + os.sep,) + tuple(
        str(Path(p).resolve()) + os.sep for p in extra_prefixes
    )
    orig = (threading.Lock, threading.RLock)
    st = LockdepState(orig[0], budget_s, prefixes, repo_root)
    threading.Lock = _make_factory(st, orig[0], rlock=False)
    threading.RLock = _make_factory(st, orig[1], rlock=True)
    _ORIG = orig
    _STATE = st
    return st


def uninstall() -> Optional[LockdepState]:
    """Restore the original factories; returns the finished state.

    Wrappers already handed out keep working (their inner lock is real);
    they just stop gaining siblings. Drain threads releasing after
    uninstall still balance their held stacks through the same state.
    """
    global _STATE, _ORIG
    if _STATE is None:
        return None
    assert _ORIG is not None
    threading.Lock, threading.RLock = _ORIG
    st = _STATE
    _STATE = None
    _ORIG = None
    return st


def install_from_env() -> Optional[LockdepState]:
    """Env-gated install: the ``--sanitize``/server entry point.

    ``NM03_LOCKDEP=1`` turns it on; ``NM03_LOCKDEP_BUDGET_MS`` sets the
    informational hold budget; ``NM03_LOCKDEP_WITNESS=<path>`` dumps the
    witness at interpreter exit (the serving drill's artifact).
    """
    if os.environ.get(_ENV_FLAG, "").lower() not in ("1", "true", "on", "yes"):
        return None
    budget_ms = os.environ.get(_ENV_BUDGET, "").strip()
    budget_s = float(budget_ms) / 1e3 if budget_ms else None
    st = install(budget_s=budget_s)
    witness = os.environ.get(_ENV_WITNESS, "").strip()
    if witness and not getattr(st, "_atexit_hooked", False):
        import atexit

        atexit.register(dump_witness, witness, st)
        st._atexit_hooked = True  # type: ignore[attr-defined]
    return st


def dump_witness(path: str | os.PathLike, st: Optional[LockdepState] = None) -> Path:
    """Write the witness JSON atomically (tmp+rename — NM351)."""
    st = st or _STATE
    if st is None:
        raise RuntimeError("lockdep is not installed and no state was given")
    out = Path(path)
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_name(out.name + ".tmp")
    tmp.write_text(json.dumps(st.snapshot(), indent=1, sort_keys=True) + "\n")
    os.replace(tmp, out)
    return out
