"""``--sanitize``: the runtime twins of the nm03-lint static rules.

Static analysis catches the hazards visible in source; these runtime
checks catch the same hazard *classes* where only execution can see them
(docs/STATIC_ANALYSIS.md pairs each rule with its twin):

* ``jax_debug_nans`` — a NaN produced anywhere in a jitted program fails
  the run at the producing op instead of surfacing as a garbage mask three
  stages later (the dtype-discipline rules keep f64 out; this catches the
  f32 overflow/0-division cases no static rule can);
* a **recompile watchdog** — ``jax_log_compiles`` emits one WARNING per
  XLA compilation; the watchdog counts them into
  ``pipeline_recompiles_total`` (docs/OBSERVABILITY.md). A steady-state
  run compiles a small fixed set up front; a *growing* counter is the
  runtime face of the NM312 retrace hazard, attributable in the metrics
  snapshot instead of invisible in lost throughput;
* ``jax.transfer_guard_host_to_device("disallow")`` **around dispatch** —
  the runtime face of NM321/NM322: inside a :func:`guard_transfers` block,
  an implicit host->device upload (a numpy array handed to a compiled
  call) raises instead of silently re-staging per dispatch. The guard is
  deliberately upload-only: device->host fetches are *sanctioned* inside
  the supervised primary (the deadline must cover them, PR 3), and on
  accelerator backends a full ``transfer_guard("disallow")`` would reject
  exactly those fetches — CPU's zero-copy d2h masks that, so the
  direction matters.

jax is imported lazily: constructing the objects costs nothing in jax-free
processes (bench.py's orchestrator wires the counter from worker-reported
counts without ever enabling the config flags itself).
"""

from __future__ import annotations

import contextlib
import logging
from typing import Optional

# canonical name home is obs.metrics (NM392); aliased for the call sites
from nm03_capstone_project_tpu.obs.metrics import (
    PIPELINE_RECOMPILES_TOTAL as RECOMPILES_TOTAL,
)

_COMPILE_PREFIXES = ("Compiling ",)

# process-wide sanitize state: set by enable(), consulted by the zero-
# plumbing guard_dispatch() the drivers wrap their dispatch sites in
_ACTIVE: Optional["SanitizeState"] = None


def active() -> bool:
    """True when enable() ran in this process."""
    return _ACTIVE is not None


def state() -> Optional["SanitizeState"]:
    return _ACTIVE


class RecompileWatchdog(logging.Handler):
    """Counts XLA compilations from the ``jax_log_compiles`` WARNING stream.

    Attach to the root ``jax`` logger: the compile records propagate up
    from ``jax._src.interpreters.*``/``jax._src.dispatch`` regardless of
    which internal module emits them in a given jax version.
    """

    def __init__(self, registry=None):
        super().__init__(level=logging.WARNING)
        self.registry = registry
        self.count = 0

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — a logging handler must never raise
            return
        if not msg.startswith(_COMPILE_PREFIXES):
            return
        self.count += 1
        if self.registry is not None:
            try:
                self.registry.counter(
                    RECOMPILES_TOTAL,
                    help="XLA compilations observed by the --sanitize "
                    "recompile watchdog (growth past warmup = retrace "
                    "hazard, see docs/STATIC_ANALYSIS.md NM312)",
                ).inc()
            except Exception:  # noqa: BLE001 — telemetry never costs the run
                pass


class SanitizeState:
    """Handle for one enabled sanitize session (keeps the handler removable)."""

    def __init__(self, watchdog: RecompileWatchdog, enabled: bool):
        self.watchdog = watchdog
        self.enabled = enabled

    @property
    def recompiles(self) -> int:
        return self.watchdog.count

    def close(self) -> None:
        logging.getLogger("jax").removeHandler(self.watchdog)


def enable(registry=None) -> SanitizeState:
    """Turn on the runtime twins in this process (imports jax).

    Sanitize is deliberately ONE-WAY for the process, like PR 3's CPU
    degradation: ``jax_debug_nans``/``jax_log_compiles`` stay on until the
    process exits, and no caller un-sets them (a mode that half-restores
    global config mid-process is worse than one that honestly doesn't).
    Idempotent: a repeat call detaches the previous watchdog (its stale
    registry stops receiving counts) and installs a fresh one for the new
    ``registry`` — in-process callers running several drivers get one
    watchdog, not a stack. ``registry`` may be None (bench workers report
    ``state.recompiles`` to the jax-free orchestrator instead). The
    counter is created at 0 immediately so a sanitized run's snapshot
    always carries it, even when nothing ever compiles.
    """
    # the lock-order twin (NM421/NM422) rides the same opt-in, but stays
    # env-gated on NM03_LOCKDEP: instrumented locks only help when enable()
    # runs BEFORE the threaded objects exist, and only the caller knows
    # that — the env flag is that assertion. jax-free, zero cost when off.
    from nm03_capstone_project_tpu.utils import lockdep

    lockdep.install_from_env()
    import jax

    jax.config.update("jax_debug_nans", True)
    jax.config.update("jax_log_compiles", True)
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()  # detach the previous watchdog: no stacking,
        # and the prior run's registry stops accumulating
    watchdog = RecompileWatchdog(registry)
    jax_logger = logging.getLogger("jax")
    jax_logger.addHandler(watchdog)
    if jax_logger.level > logging.WARNING or jax_logger.level == logging.NOTSET:
        jax_logger.setLevel(logging.WARNING)
    if registry is not None:
        registry.counter(
            RECOMPILES_TOTAL,
            help="XLA compilations observed by the --sanitize recompile "
            "watchdog (growth past warmup = retrace hazard, see "
            "docs/STATIC_ANALYSIS.md NM312)",
        ).inc(0)
    _ACTIVE = SanitizeState(watchdog, enabled=True)
    return _ACTIVE


def record_external_recompiles(registry, count: int) -> None:
    """Fold a worker process's watchdog count into this process's registry.

    bench.py's orchestrator never imports jax; its workers run sanitized
    and report their compile counts in the result record, which lands here
    so ``--metrics-out`` carries one coherent ``pipeline_recompiles_total``.
    """
    registry.counter(
        RECOMPILES_TOTAL,
        help="XLA compilations observed by the --sanitize recompile "
        "watchdog (growth past warmup = retrace hazard, see "
        "docs/STATIC_ANALYSIS.md NM312)",
    ).inc(max(int(count), 0))


@contextlib.contextmanager
def guard_transfers(enabled: bool = True):
    """Upload-only transfer guard scoped to a dispatch window.

    ``jax.transfer_guard_host_to_device("disallow")``: an implicit numpy
    argument to a compiled call raises; explicit ``device_put`` staging
    and all device->host fetches (the supervised primary's job) pass on
    EVERY backend — a bidirectional ``disallow`` only looks workable on
    CPU, where d2h is zero-copy and unguarded. A no-op (and jax-free)
    when ``enabled`` is false so call sites can thread the flag
    unconditionally.
    """
    if not enabled:
        yield
        return
    import jax

    with jax.transfer_guard_host_to_device("disallow"):
        yield


@contextlib.contextmanager
def guard_dispatch():
    """Zero-plumbing dispatch guard for the drivers.

    Equivalent to ``guard_transfers(active())``: call sites wrap their
    staged-input dispatch unconditionally; the guard only exists when the
    process ran :func:`enable` (``--sanitize``). Explicit ``device_put``
    staging and result fetches pass on every backend; an *implicit*
    host-array argument upload — the NM322 hazard at runtime — raises.
    """
    with guard_transfers(active()):
        yield
