"""Output manifest + resume.

The reference has no checkpoint/resume: every rerun wipes each patient's
output directory (``rm -rf *`` in setupOutputDirectory,
main_sequential.cpp:35-37) and recomputes everything. SURVEY.md section 5
calls for a resumable manifest; this is it: a JSON file per output root
recording per-patient, per-slice status, written atomically after every
patient so an interrupted run restarts where it stopped (``--resume``).
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict

MANIFEST_NAME = "manifest.json"

STATUS_DONE = "done"
STATUS_FAILED = "failed"
# exported pair exists, but the region-growing cap truncated the mask: NOT
# "done" for --resume purposes, so a rerun with a raised --grow-max-iters
# actually recomputes it (the warning's advertised remedy)
STATUS_TRUNCATED = "truncated"


class Manifest:
    """Per-run record: {patient_id: {slice_stem: status}}."""

    def __init__(self, out_root: str | os.PathLike, name: str = MANIFEST_NAME):
        # a multi-process run gives each rank its own manifest file (disjoint
        # patient subsets; one shared JSON would race on flush)
        self.path = Path(out_root) / name
        self.data: Dict[str, Dict[str, str]] = {}

    @classmethod
    def load_or_create(
        cls, out_root: str | os.PathLike, name: str = MANIFEST_NAME
    ) -> "Manifest":
        m = cls(out_root, name)
        if m.path.exists():
            try:
                m.data = json.loads(m.path.read_text())
            except (json.JSONDecodeError, OSError):
                m.data = {}
        return m

    def record(self, patient_id: str, stem: str, status: str) -> None:
        self.data.setdefault(patient_id, {})[stem] = status

    def is_done(self, patient_id: str, stem: str) -> bool:
        return self.data.get(patient_id, {}).get(stem) == STATUS_DONE

    def patient_done(self, patient_id: str, stems) -> bool:
        done = self.data.get(patient_id, {})
        return all(done.get(s) == STATUS_DONE for s in stems) and bool(stems)

    def patient_accounted(self, patient_id: str, stems) -> bool:
        """Every stem has SOME recorded status (done or failed) — i.e. a
        prior run fully visited this patient; permanently-bad slices must not
        force an eternal re-run under --resume. Truncated stems do NOT count
        as accounted: their masks under-cover and a rerun (presumably with a
        raised --grow-max-iters) must recompute them."""
        seen = self.data.get(patient_id, {})
        return (
            all(s in seen and seen[s] != STATUS_TRUNCATED for s in stems)
            and bool(stems)
        )

    def flush(self) -> None:
        """Atomic write (tmp + rename) so a crash never corrupts the manifest."""
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps(self.data, indent=1, sort_keys=True))
        os.replace(tmp, self.path)

    def summary(self) -> Dict[str, int]:
        done = sum(
            1 for p in self.data.values() for s in p.values() if s == STATUS_DONE
        )
        failed = sum(
            1 for p in self.data.values() for s in p.values() if s == STATUS_FAILED
        )
        return {"patients": len(self.data), "done": done, "failed": failed}
