"""Logging configuration: the Reporter equivalent.

The reference routes FAST's global Reporter so INFO is silenced and
WARNING/ERROR go to the console (main_sequential.cpp:310-315,349-354,
main_parallel.cpp:394-399). This module reproduces that routing on Python
logging, plus a ``--verbose`` escape hatch the reference lacks.
"""

from __future__ import annotations

import logging
import sys

LOGGER_NAME = "nm03_tpu"


def get_logger(child: str | None = None) -> logging.Logger:
    name = LOGGER_NAME if child is None else f"{LOGGER_NAME}.{child}"
    return logging.getLogger(name)


def configure_reporting(verbose: bool = False, stream=None) -> logging.Logger:
    """INFO silenced (unless verbose), WARNING/ERROR to console.

    Mirrors Reporter::setGlobalReportMethod(INFO, NONE) /(WARNING, COUT) /
    (ERROR, COUT).
    """
    logger = logging.getLogger(LOGGER_NAME)
    logger.handlers.clear()
    handler = logging.StreamHandler(stream or sys.stdout)
    handler.setFormatter(logging.Formatter("%(levelname)s %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(logging.INFO if verbose else logging.WARNING)
    logger.propagate = False
    return logger
