"""In-tree profiling harness.

The reference profiles *outside* the repo with perf + Hotspot
(reference README.md:93-95). The TPU-native equivalent per SURVEY.md
section 5 is ``jax.profiler``: traces viewable in TensorBoard/Perfetto,
captured in-tree via ``--profile-dir`` on any driver, plus named trace
annotations so pipeline stages show up in the timeline.

The obs span API (docs/OBSERVABILITY.md) calls :func:`annotate` for every
span, so sections timed for the metrics histograms and sections visible on
the profiler timeline are the same names by construction.
"""

from __future__ import annotations

import contextlib
import threading
from pathlib import Path

from nm03_capstone_project_tpu.utils.reporter import get_logger

_log = get_logger("profiling")


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """Capture a jax.profiler trace into ``trace_dir`` (no-op when None).

    View with ``tensorboard --logdir <dir>`` or upload the .perfetto
    trace to ui.perfetto.dev.
    """
    if not trace_dir:
        yield
        return
    import jax

    Path(trace_dir).mkdir(parents=True, exist_ok=True)
    _log.info("capturing profiler trace to %s", trace_dir)
    with jax.profiler.trace(str(trace_dir)):
        yield
    _log.info("profiler trace written to %s", trace_dir)


def annotate(name: str):
    """Named region that appears on the profiler timeline (host + device)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class ProfileBusy(RuntimeError):
    """A capture is already in flight (the jax profiler is process-global)."""


# one capture at a time: jax.profiler.start_trace raises on a concurrent
# start, and two HTTP pulls racing would turn a debug aid into a crash
_CAPTURE_LOCK = threading.Lock()

MAX_CAPTURE_MS = 10_000
# past this, the zip is kept SERVER-SIDE (the response names its path and
# carries the file listing) instead of riding the wire — a remote pull
# must not OOM the replica it is debugging, but a post-mortem capture
# must never be destroyed either
MAX_ZIP_BYTES = 32 << 20


def capture_profile(duration_ms: int, zip_cap_bytes: int = MAX_ZIP_BYTES) -> dict:
    """On-demand ``jax.profiler`` capture for the remote debug pull.

    Runs a trace for ``duration_ms`` (REJECTED outside [10, 10000] ms —
    a capture is a live-process intrusion, bounded by construction),
    zips the trace directory in memory and returns a JSON-able dict:
    ``{duration_ms, files: [{name, bytes}], zip_b64, zip_bytes}``. When
    the archive exceeds ``zip_cap_bytes`` the base64 payload is dropped
    from the response (``zip_dropped: true``) but the archive itself is
    saved server-side and ``zip_path`` names it — an operator's capture
    is never destroyed, only kept off the wire. Raises
    :class:`ProfileBusy` when a capture is already running (the HTTP
    layer maps it to 409), ``ValueError`` on an out-of-bounds duration.
    """
    ms = int(duration_ms)
    if not 10 <= ms <= MAX_CAPTURE_MS:
        raise ValueError(
            f"profile duration must be in [10, {MAX_CAPTURE_MS}] ms, got {ms}"
        )
    if not _CAPTURE_LOCK.acquire(blocking=False):
        raise ProfileBusy("a profiler capture is already in flight")
    try:
        import base64
        import io
        import os
        import shutil
        import tempfile
        import time
        import zipfile

        import jax

        tmp = tempfile.mkdtemp(prefix="nm03_profile_")
        try:
            jax.profiler.start_trace(tmp)
            # nm03-lint: disable=NM422 the sleep IS the capture window; _CAPTURE_LOCK exists to serialize exactly this (one profiler session per process), so concurrent callers get ProfileBusy, not a queue
            time.sleep(ms / 1e3)
            jax.profiler.stop_trace()
            files = []
            buf = io.BytesIO()
            with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
                for root, _dirs, names in os.walk(tmp):
                    for name in sorted(names):
                        full = os.path.join(root, name)
                        rel = os.path.relpath(full, tmp)
                        files.append(
                            {"name": rel, "bytes": os.path.getsize(full)}
                        )
                        zf.write(full, rel)
            out = {"duration_ms": ms, "files": files}
            data = buf.getvalue()
            out["zip_bytes"] = len(data)
            if len(data) <= zip_cap_bytes:
                out["zip_b64"] = base64.b64encode(data).decode("ascii")
            else:
                # too big for the wire: keep the archive on the replica
                # (named in the response) — the listing alone would name
                # files that no longer exist anywhere
                fd, zip_path = tempfile.mkstemp(
                    prefix="nm03_profile_", suffix=".zip"
                )
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                out["zip_dropped"] = True
                out["zip_path"] = zip_path
            return out
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    finally:
        _CAPTURE_LOCK.release()
