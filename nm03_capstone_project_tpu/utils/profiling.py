"""In-tree profiling harness.

The reference profiles *outside* the repo with perf + Hotspot
(reference README.md:93-95). The TPU-native equivalent per SURVEY.md
section 5 is ``jax.profiler``: traces viewable in TensorBoard/Perfetto,
captured in-tree via ``--profile-dir`` on any driver, plus named trace
annotations so pipeline stages show up in the timeline.

The obs span API (docs/OBSERVABILITY.md) calls :func:`annotate` for every
span, so sections timed for the metrics histograms and sections visible on
the profiler timeline are the same names by construction.
"""

from __future__ import annotations

import contextlib
from pathlib import Path

from nm03_capstone_project_tpu.utils.reporter import get_logger

_log = get_logger("profiling")


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """Capture a jax.profiler trace into ``trace_dir`` (no-op when None).

    View with ``tensorboard --logdir <dir>`` or upload the .perfetto
    trace to ui.perfetto.dev.
    """
    if not trace_dir:
        yield
        return
    import jax

    Path(trace_dir).mkdir(parents=True, exist_ok=True)
    _log.info("capturing profiler trace to %s", trace_dir)
    with jax.profiler.trace(str(trace_dir)):
        yield
    _log.info("profiler trace written to %s", trace_dir)


def annotate(name: str):
    """Named region that appears on the profiler timeline (host + device)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
