"""The fused 3D volumetric pipeline.

The reference processes every DICOM slice independently in 2D
(``setLoadSeries(false)``, src/test/test_pipeline.cpp:41); its nearest "scale"
axis is slices-per-patient. This module is the framework's volumetric
capability (BASELINE.json config 4): a patient's series is stacked into a
(D, H, W) volume, the per-slice preprocessing runs vmapped over the stack, and
segmentation + morphology run with true 3D connectivity — the lesion grows as
one 6-connected body across slices instead of D unrelated 2D islands.

The z axis is also the framework's sharding axis for long volumes: see
:mod:`nm03_capstone_project_tpu.parallel.zshard` for the halo-exchange
decomposition of this same pipeline over a device mesh.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.config import DEFAULT_CONFIG, PipelineConfig
from nm03_capstone_project_tpu.core.image import valid_mask
from nm03_capstone_project_tpu.ops.elementwise import cast_uint8
from nm03_capstone_project_tpu.ops.seeds import seed_mask
from nm03_capstone_project_tpu.ops.volume import (
    dilate3d,
    region_grow_3d,
    region_grow_jump_3d,
)
from nm03_capstone_project_tpu.pipeline.slice_pipeline import preprocess


def process_volume(
    volume: jax.Array, dims: jax.Array, cfg: PipelineConfig = DEFAULT_CONFIG
) -> Dict[str, jax.Array]:
    """Full volumetric pipeline for one stacked series.

    Args:
      volume: (D, H, W) float raw intensities on the padded canvas; all
        slices of one series share the true in-plane size.
      dims: int32 (2,) true (height, width) of the series' slices.
      cfg: pipeline hyper-parameters (the reference's 2D contract values
        apply unchanged to each slice's preprocessing).

    Returns {'original', 'mask', 'grow_converged'}: input volume, the final
    uint8 3D mask after 6-connected dilation, and a scalar bool that is
    False when the growing fixpoint hit its iteration cap (a truncated,
    under-covering mask — FAST's BFS always completes, so drivers surface
    this per patient; VERDICT r4 item 4).
    """
    # Per-slice 2D preprocessing — identical math to the batch drivers
    # (main_sequential.cpp:194-208), vmapped over the stack. The PR-2 fast
    # paths flow through cfg unchanged: median_impl selects the pruned
    # selection network, and use_pallas + fuse_preprocess route the whole
    # chain through the fused VMEM kernel per slice on TPU.
    pre = jax.vmap(lambda p: preprocess(p, dims, cfg))(volume)

    # The reference's adaptive seed grid (test_pipeline.cpp:79-106) is a pure
    # function of (h, w); the volumetric extension plants the same grid on
    # every slice and lets 3D growth connect them through z.
    canvas_hw = volume.shape[-2:]
    seeds2d = seed_mask(dims, canvas_hw)
    valid2d = valid_mask(dims, canvas_hw)
    d = volume.shape[-3]
    seeds = jnp.broadcast_to(seeds2d, (d,) + seeds2d.shape)
    valid = jnp.broadcast_to(valid2d, (d,) + valid2d.shape)

    if cfg.grow_algorithm == "jump":
        seg, converged = region_grow_jump_3d(
            pre, seeds, cfg.grow_low, cfg.grow_high, valid=valid,
            max_rounds=cfg.grow_max_iters,
        )
    else:
        seg, converged = region_grow_3d(
            pre,
            seeds,
            cfg.grow_low,
            cfg.grow_high,
            valid=valid,
            block_iters=cfg.grow_block_iters,
            max_iters=cfg.grow_max_iters,
        )
    mask = dilate3d(cast_uint8(seg), cfg.morph_size)
    mask = mask * valid.astype(mask.dtype)
    return {"original": volume, "mask": mask, "grow_converged": converged}
