"""The fused 2D slice pipeline.

Where the reference wires FAST ProcessObjects stage-by-stage with an eager
``update()`` after every ``connect`` (src/sequential/main_sequential.cpp:194-252
— each update dispatches a separate OpenCL kernel), this module composes the
whole operator chain as one pure function and lets ``jax.jit`` fuse it into a
single XLA program: elementwise stages melt into their stencil neighbours,
nothing round-trips through HBM between stages, and the same function vmaps
over a padded slice stack (the TPU replacement for the reference's OpenMP
batch loop, main_parallel.cpp:336).

Two variants mirror the reference's drivers (SURVEY.md section 2.4):

* :func:`process_slice` — the batch contract (main_sequential.cpp:170-272,
  main_parallel.cpp:66-170): preprocess, region-grow, uint8 cast, dilation
  only; returns (original, segmentation-after-dilation).
* :func:`process_slice_stages` — the test-pipeline contract
  (src/test/test_pipeline.cpp:53-125): additionally returns every
  intermediate stage, with erosion and dilation as parallel branches off the
  caster (erosion does NOT feed dilation).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.config import DEFAULT_CONFIG, PipelineConfig
from nm03_capstone_project_tpu.core.image import valid_mask
from nm03_capstone_project_tpu.ops.elementwise import cast_uint8, clip_intensity, normalize
from nm03_capstone_project_tpu.ops.pallas_median import median_filter
from nm03_capstone_project_tpu.ops.morphology import dilate, erode
from nm03_capstone_project_tpu.ops.neighborhood import extend_edges
from nm03_capstone_project_tpu.ops.pallas_region_growing import grow_dispatch
from nm03_capstone_project_tpu.ops.seeds import seed_mask
from nm03_capstone_project_tpu.ops.sharpen import sharpen


def preprocess(
    pixels: jax.Array, dims: jax.Array, cfg: PipelineConfig = DEFAULT_CONFIG
) -> jax.Array:
    """Normalize -> clip -> vector median -> sharpen (the preprocessing stage).

    ``pixels`` is (..., H, W) on the static canvas; ``dims`` the true (h, w).
    The slice's true edge is replicated into the canvas padding first so the
    stencil stages see clamp-to-edge boundaries instead of padding zeros.

    On a TPU backend with ``cfg.use_pallas`` and ``cfg.fuse_preprocess``
    the whole chain runs as one VMEM-resident halo-tiled Pallas kernel
    (ops.pallas_median.fused_preprocess_pallas — one HBM read of the image
    instead of four stage round trips); everywhere else the stages compose
    in XLA, which fuses the elementwise ops into the stencils itself.
    """
    x = extend_edges(pixels, dims)
    if cfg.use_pallas and cfg.fuse_preprocess:
        from nm03_capstone_project_tpu.ops.pallas_median import (
            fused_preprocess_pallas,
            pallas_backend_supported,
        )

        if pallas_backend_supported():
            return fused_preprocess_pallas(
                x,
                norm_low=cfg.norm_low,
                norm_high=cfg.norm_high,
                norm_min=cfg.norm_intensity_min,
                norm_max=cfg.norm_intensity_max,
                clip_low=cfg.clip_low,
                clip_high=cfg.clip_high,
                median_window=cfg.median_window,
                sharpen_gain=cfg.sharpen_gain,
                sharpen_sigma=cfg.sharpen_sigma,
                sharpen_kernel=cfg.sharpen_kernel,
            )
    x = normalize(
        x, cfg.norm_low, cfg.norm_high, cfg.norm_intensity_min, cfg.norm_intensity_max
    )
    x = clip_intensity(x, cfg.clip_low, cfg.clip_high)
    x = median_filter(
        x, cfg.median_window, use_pallas=cfg.use_pallas, impl=cfg.median_impl
    )
    x = sharpen(x, cfg.sharpen_gain, cfg.sharpen_sigma, cfg.sharpen_kernel)
    return x


def segment(
    preprocessed: jax.Array, dims: jax.Array, cfg: PipelineConfig = DEFAULT_CONFIG
) -> tuple[jax.Array, jax.Array]:
    """Seeded region growing with the adaptive seed grid.

    Returns ``(mask, converged)``: the uint8 {0,1} mask and a scalar bool
    that is False when the growing fixpoint hit its iteration cap (an
    under-covering mask — see ops.region_growing; VERDICT r4 item 4)."""
    canvas_hw = preprocessed.shape[-2:]
    seeds = seed_mask(dims, canvas_hw)
    valid = valid_mask(dims, canvas_hw)
    return grow_dispatch(
        preprocessed,
        seeds,
        cfg.grow_low,
        cfg.grow_high,
        valid=valid,
        block_iters=cfg.grow_block_iters,
        max_iters=cfg.grow_max_iters,
        use_pallas=cfg.use_pallas,
        algorithm=cfg.grow_algorithm,
    )


def process_slice(
    pixels: jax.Array, dims: jax.Array, cfg: PipelineConfig = DEFAULT_CONFIG
) -> Dict[str, jax.Array]:
    """Full batch-driver pipeline for one slice (or a batch via vmap).

    Returns {'original', 'mask', 'grow_converged'}: the untouched input
    pixels, the final uint8 mask after dilation — the two images the batch
    drivers export per slice (main_sequential.cpp:254-265) — and the
    scalar bool from :func:`segment` (False = the growing cap truncated
    this slice's mask; drivers count and log it per patient).
    """
    pre = preprocess(pixels, dims, cfg)
    seg, converged = segment(pre, dims, cfg)
    mask = dilate(cast_uint8(seg), cfg.morph_size)
    # dilation must not spill into the canvas padding — the reference's
    # Dilation runs on the exact-size image and can never write there
    valid = valid_mask(dims, pixels.shape[-2:])
    mask = mask * valid.astype(mask.dtype)
    return {"original": pixels, "mask": mask, "grow_converged": converged}


def process_slice_stages(
    pixels: jax.Array, dims: jax.Array, cfg: PipelineConfig = DEFAULT_CONFIG
) -> Dict[str, jax.Array]:
    """Test-pipeline variant: every intermediate stage, erosion branch included.

    Mirrors src/test/test_pipeline.cpp:53-125: erosion and dilation both
    branch off the caster output (section 2.4 divergence). Keys match the
    export names of the reference's test driver (test_pipeline.cpp:167-177).
    """
    pre = preprocess(pixels, dims, cfg)
    seg, converged = segment(pre, dims, cfg)
    cast = cast_uint8(seg)
    valid = valid_mask(dims, pixels.shape[-2:])
    dilated = dilate(cast, cfg.morph_size) * valid.astype(jnp.uint8)
    return {
        "original_image": pixels,
        "preprocessed_image": pre,
        "segmentation": cast,
        "erosion_result": erode(cast, cfg.morph_size),
        "final_dilated_result": dilated,
        "grow_converged": converged,
    }


def process_batch(
    pixels: jax.Array, dims: jax.Array, cfg: PipelineConfig = DEFAULT_CONFIG
) -> Dict[str, jax.Array]:
    """vmapped :func:`process_slice` over a (B, H, W) stack.

    This is the TPU-native replacement for the reference's
    ``#pragma omp parallel for`` over a batch (main_parallel.cpp:336): one
    compiled program, batch dimension handled by the compiler, bit-identical
    to the sequential path by construction (the property the reference can
    only check by diffing output directories).
    """
    return jax.vmap(lambda p, d: process_slice(p, d, cfg))(pixels, dims)


def check_min_dims(dims, min_dim: int = DEFAULT_CONFIG.min_dim):
    """Host-side guard mirroring main_sequential.cpp:189-192.

    Returns a bool (array) of slices that pass the reference's minimum
    dimension check; callers skip failures and count them, preserving the
    reference's catch-and-continue contract.
    """
    import numpy as np

    d = np.asarray(dims)
    return (d[..., 0] >= min_dim) & (d[..., 1] >= min_dim)
