"""Fused pipelines (2D slice and 3D volume)."""

from nm03_capstone_project_tpu.pipeline.slice_pipeline import (  # noqa: F401
    check_min_dims,
    preprocess,
    process_batch,
    process_slice,
    process_slice_stages,
    segment,
)
from nm03_capstone_project_tpu.pipeline.volume_pipeline import (  # noqa: F401
    process_volume,
)
