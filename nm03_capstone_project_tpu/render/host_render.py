"""Host-side rendering: the batch drivers' export renderer.

Same contract as :mod:`nm03_capstone_project_tpu.render.render` — FAST's
``RenderToImage(Color::Black(), 512, 512)`` + ``ImageRenderer`` /
``SegmentationRenderer({1: White}, 0.6, 1.0, 2)`` export stack
(reference src/sequential/main_sequential.cpp:49-78) — implemented in NumPy
for the host.

Why a second implementation exists: the batch drivers' device renderer
produces two 512x512 canvases per slice, ~1.5 MB that must cross the
host<->device link per slice just to be JPEG-encoded and discarded. On the
tunneled single-chip setup that transfer dominated end-to-end cohort time
(~690 MB for the 20-patient cohort). Rendering is O(out^2) arithmetic on
data the host already holds — the decoded pixels never needed to come back,
and the mask is 65 KB — so the batch drivers fetch ONLY the mask and render
here, overlapped with the next batch's device compute in the IO pool. The
device renderer remains the canonical implementation (the test-pipeline
driver, the golden suite, and anything that wants the render inside the jit
still use it); ``--render-stage device`` restores it in the batch drivers.

The math mirrors the device renderer's gather formulation line for line
(same f32 separable rows-then-columns lerp, same nearest selection, same
erosion-based border band), so the two paths agree to float rounding:
identical mask renders, and grayscale renders within one 8-bit count at a
handful of interpolated pixels (XLA may contract the lerp into FMAs; NumPy
does not). Sequential and parallel drivers share THIS path, so their outputs
stay bit-identical to each other — the invariant the reference can only
check by diffing output directories (README.md:60-66).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from nm03_capstone_project_tpu.ops.neighborhood import footprint_offsets

_F32 = np.float32


def _letterbox_coords(dims: np.ndarray, out_size: int):
    """NumPy mirror of render._letterbox_coords (same f32 arithmetic)."""
    h = _F32(dims[0])
    w = _F32(dims[1])
    scale = min(_F32(out_size) / h, _F32(out_size) / w)
    dest_h = h * scale
    dest_w = w * scale
    off_y = (_F32(out_size) - dest_h) / _F32(2)
    off_x = (_F32(out_size) - dest_w) / _F32(2)
    o = np.arange(out_size, dtype=np.float32)
    src_y = (o - off_y + _F32(0.5)) / scale - _F32(0.5)
    src_x = (o - off_x + _F32(0.5)) / scale - _F32(0.5)
    inside_y = (o >= np.floor(off_y)) & (o < np.ceil(off_y + dest_h))
    inside_x = (o >= np.floor(off_x)) & (o < np.ceil(off_x + dest_w))
    inside = inside_y[:, None] & inside_x[None, :]
    return src_y, src_x, inside


def _sample_bilinear(img, src_y, src_x, dims):
    h, w = int(dims[0]), int(dims[1])
    y0 = np.clip(np.floor(src_y).astype(np.int32), 0, h - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    fy = np.clip(src_y - y0.astype(np.float32), 0.0, 1.0)[:, None]
    x0 = np.clip(np.floor(src_x).astype(np.int32), 0, w - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    fx = np.clip(src_x - x0.astype(np.float32), 0.0, 1.0)[None, :]
    rows = img[y0, :] * (1 - fy) + img[y1, :] * fy
    return rows[:, x0] * (1 - fx) + rows[:, x1] * fx


def _sample_nearest(img, src_y, src_x, dims):
    h, w = int(dims[0]), int(dims[1])
    yy = np.clip(np.round(src_y).astype(np.int32), 0, h - 1)
    xx = np.clip(np.round(src_x).astype(np.int32), 0, w - 1)
    return img[yy, :][:, xx]


def _erode_disk(m: np.ndarray, size: int) -> np.ndarray:
    """Binary erosion, disk element, background padding (ops.morphology)."""
    out = np.ones_like(m)
    h, w = m.shape
    padded = np.zeros((h + size, w + size), m.dtype)
    r = size // 2
    padded[r : r + h, r : r + w] = m
    for dr, dc in footprint_offsets(size, "disk"):
        out &= padded[r + dr : r + dr + h, r + dc : r + dc + w]
    return out


def host_render_gray(
    pixels: np.ndarray, dims: np.ndarray, out_size: int = 512
) -> np.ndarray:
    """NumPy mirror of render.render_gray: letterboxed auto-windowed uint8."""
    pixels = np.asarray(pixels, np.float32)
    h, w = int(dims[0]), int(dims[1])
    region = pixels[:h, :w]
    vmin = np.float32(region.min())
    rng = np.maximum(np.float32(region.max()) - vmin, np.float32(1e-6))
    src_y, src_x, inside = _letterbox_coords(dims, out_size)
    sampled = _sample_bilinear(pixels, src_y, src_x, dims)
    gray = (sampled - vmin) / rng * np.float32(255.0)
    gray = np.where(inside, gray, np.float32(0.0))
    return np.clip(gray, 0, 255).astype(np.uint8)


def host_render_segmentation(
    mask: np.ndarray,
    dims: np.ndarray,
    out_size: int = 512,
    opacity: float = 0.6,
    border_opacity: float = 1.0,
    border_radius: int = 2,
) -> np.ndarray:
    """NumPy mirror of render.render_segmentation (bit-identical output)."""
    src_y, src_x, inside = _letterbox_coords(dims, out_size)
    m = _sample_nearest((np.asarray(mask) > 0).astype(np.uint8), src_y, src_x, dims)
    m = (m > 0) & inside
    interior = _erode_disk(m, 2 * border_radius + 1)
    border = m & ~interior
    alpha = np.where(
        border, np.float32(border_opacity), np.where(m, np.float32(opacity), np.float32(0))
    )
    return np.clip(alpha * np.float32(255.0), 0, 255).astype(np.uint8)


def host_render_pair(
    pixels: np.ndarray, mask: np.ndarray, dims: np.ndarray, cfg
) -> Tuple[np.ndarray, np.ndarray]:
    """(grayscale render, segmentation render), host-side, per ``cfg``.

    Drop-in counterpart of render.render_pair for the batch-export contract
    (one `_original` + one `_processed` image per slice,
    main_sequential.cpp:61-73).
    """
    gray = host_render_gray(pixels, dims, cfg.render_size)
    seg = host_render_segmentation(
        mask,
        dims,
        cfg.render_size,
        cfg.overlay_opacity,
        cfg.overlay_border_opacity,
        cfg.overlay_border_radius,
    )
    return gray, seg
