"""Render + export: the TPU-native visualization stack."""

from nm03_capstone_project_tpu.render.contact_sheet import contact_sheet  # noqa: F401
from nm03_capstone_project_tpu.render.export import (  # noqa: F401
    clean_directory,
    export_pairs,
    render_export_pairs,
    save_jpeg,
)
from nm03_capstone_project_tpu.render.host_render import (  # noqa: F401
    host_render_gray,
    host_render_pair,
    host_render_segmentation,
)
from nm03_capstone_project_tpu.render.render import (  # noqa: F401
    render_gray,
    render_overlay,
    render_pair,
    render_segmentation,
)
