"""Multi-pane contact sheet — the headless MultiViewWindow.

The reference's test driver shows its 5 stage renders side by side in a
blocking Qt window (``MultiViewWindow::create(5, Color::Black(), 2300, 450,
false)`` then ``run()``, src/test/test_pipeline.cpp:148-158). A TPU batch
job has no display, so the equivalent is a composed image: every pane
resized to a square cell on a black strip, in order, one file a human can
eyeball exactly like the reference's window.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def _resize_nearest(img: np.ndarray, size: int) -> np.ndarray:
    h, w = img.shape
    ys = np.minimum((np.arange(size) * h) // size, h - 1)
    xs = np.minimum((np.arange(size) * w) // size, w - 1)
    return img[np.ix_(ys, xs)]


def contact_sheet(
    panels: Sequence[np.ndarray],
    pane_size: int = 450,
    pad: int = 10,
    background: int = 0,
    labels: Optional[Sequence[str]] = None,
) -> np.ndarray:
    """Compose uint8 grayscale panels into one horizontal strip.

    Mirrors the reference window's geometry: N panes across (5 panes in a
    2300x450 window ≈ 450 px panes + padding). ``labels`` is only
    length-checked — captions are the caller's concern (e.g. a sidecar text
    file); passing it here keeps the two lists in sync.
    """
    if not panels:
        raise ValueError("contact_sheet needs at least one panel")
    if labels is not None and len(labels) != len(panels):
        raise ValueError(f"{len(labels)} labels for {len(panels)} panels")
    cells: List[np.ndarray] = []
    for p in panels:
        arr = np.asarray(p)
        if arr.dtype != np.uint8 or arr.ndim != 2:
            raise ValueError(
                f"panels must be uint8 (H, W), got {arr.dtype} {arr.shape}"
            )
        cells.append(_resize_nearest(arr, pane_size))
    n = len(cells)
    out = np.full(
        (pane_size + 2 * pad, n * pane_size + (n + 1) * pad),
        np.uint8(background),
        np.uint8,
    )
    for i, cell in enumerate(cells):
        x0 = pad + i * (pane_size + pad)
        out[pad : pad + pane_size, x0 : x0 + pane_size] = cell
    return out
