"""Host-side JPEG export.

TPU-native equivalent of FAST ``ImageFileExporter`` (reference
main_sequential.cpp:61-73: two JPEGs per slice, ``<stem>_original.jpg`` and
``<stem>_processed.jpg``). Where the reference must serialize its whole
render+encode path through one shared Qt ``RenderToImage`` (the per-batch
barrier at main_parallel.cpp:172-216), here rendering happened on device and
only JPEG encoding runs on the host — embarrassingly parallel across a small
thread pool that overlaps with the next batch's device compute.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
from pathlib import Path
from typing import List, Sequence, Tuple

import numpy as np

from nm03_capstone_project_tpu.utils.reporter import get_logger

_log = get_logger("export")


def save_jpeg(image: np.ndarray, path: str | os.PathLike, quality: int = 90) -> None:
    """Write a uint8 grayscale (H, W) array as JPEG, atomically.

    Encoding is :func:`encode_jpeg_bytes` (the single home of the
    measured PIL-first / C++-fallback encoder preference — docs/PERF.md,
    and the r5 changelog note in docs/API.md on why the preference order
    changes JPEG bytes).

    Atomic tmp+rename (crash-safe resume contract, docs/RESILIENCE.md):
    a SIGTERM/kill/ENOSPC mid-encode can leave a stray ``.jpg.tmp`` but
    never a torn ``.jpg`` — so ``--resume`` may trust every final-named
    file on disk without re-validating its bytes.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    try:
        tmp.write_bytes(encode_jpeg_bytes(image, quality))
        os.replace(tmp, path)
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise


def encode_jpeg_bytes(image: np.ndarray, quality: int = 90) -> bytes:
    """Encode a uint8 grayscale (H, W) array to JPEG bytes, in memory.

    The ONE home of the encoder preference (PIL first for libjpeg-turbo,
    the C++ encoder as the PIL-less fallback — measured in docs/PERF.md).
    :func:`save_jpeg` composes this for disk exports; the serving path
    builds HTTP bodies from it directly — a response is a fully-encoded
    buffer or nothing, so a torn JPEG can never be served (the online
    analog of save_jpeg's atomic tmp+rename discipline).
    """
    arr = np.asarray(image)
    if arr.dtype != np.uint8:
        raise ValueError(f"expected uint8 image, got {arr.dtype}")
    try:
        from PIL import Image
    except ImportError:
        Image = None
    if Image is not None:
        import io

        buf = io.BytesIO()
        Image.fromarray(arr, mode="L").save(buf, format="JPEG", quality=quality)
        return buf.getvalue()
    from nm03_capstone_project_tpu import native

    if arr.ndim != 2 or not native.available():
        raise RuntimeError("no JPEG encoder available (PIL missing, native failed)")
    return bytes(native.encode_jpeg_gray(arr, quality))


def _write_pair(out: Path, stem: str, orig: np.ndarray, proc: np.ndarray) -> str:
    save_jpeg(orig, out / f"{stem}_original.jpg")
    save_jpeg(proc, out / f"{stem}_processed.jpg")
    return stem


def _export_many(
    write_one,
    items: Sequence,
    out_dir,
    max_workers: int,
    fault_hook=None,
    retry=None,
    success_hook=None,
) -> List[str]:
    """Concurrent per-slice export with containment; the shared scaffold.

    ``write_one(item) -> stem`` runs per slice on a thread pool; failures are
    contained and logged per slice (the reference's catch-and-continue at the
    export stage, main_sequential.cpp:267-271). Returns sorted stems written.

    ``fault_hook(stem)`` is the chaos-injection point (resilience.FaultPlan):
    called before each slice writes, it may raise to simulate export I/O
    failure. ``retry`` (a resilience.RetryPolicy) retries OSError-class
    write failures — the transient-disk case — before declaring the slice
    failed; injected faults are OSErrors too, so persistent fault rules
    exercise the retry path on their way to a contained failure.
    ``success_hook(stem)`` fires the moment a slice's pair is on disk —
    the crash journal's per-slice granularity hook; its own failures are
    contained (a journaling error must not un-succeed a written slice).
    """
    Path(out_dir).mkdir(parents=True, exist_ok=True)

    def attempt(item):
        # the hook fires per ATTEMPT, inside the retry: a count-limited
        # fault rule models a transient disk error (healed by retry), an
        # unlimited rule a persistent one (retries exhaust, slice fails)
        if fault_hook is not None:
            fault_hook(item[0])
        return write_one(item)

    def one(item):
        if retry is not None:
            stem = retry.call(attempt, item, cause="export", retryable=(OSError,))
        else:
            stem = attempt(item)
        if success_hook is not None:
            try:
                success_hook(stem)
            except Exception as e:  # noqa: BLE001 — journal must not cost a slice
                _log.warning("export success hook failed for %s: %s", stem, e)
        return stem

    done: List[str] = []
    with cf.ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = {pool.submit(one, item): item[0] for item in items}
        for fut in cf.as_completed(futures):
            try:
                done.append(fut.result())
            except Exception as e:  # noqa: BLE001 - per-slice containment
                _log.warning("export failed for %s: %s", futures[fut], e)
    return sorted(done)


def export_pairs(
    items: Sequence[Tuple[str, np.ndarray, np.ndarray]],
    out_dir: str | os.PathLike,
    max_workers: int = 8,
    fault_hook=None,
    retry=None,
    success_hook=None,
) -> List[str]:
    """Write (stem, original, processed) triples as JPEG pairs concurrently."""
    out = Path(out_dir)
    return _export_many(
        lambda it: _write_pair(out, it[0], it[1], it[2]),
        items,
        out,
        max_workers,
        fault_hook=fault_hook,
        retry=retry,
        success_hook=success_hook,
    )


def render_export_pairs(
    items: Sequence[Tuple[str, np.ndarray, np.ndarray, np.ndarray]],
    out_dir: str | os.PathLike,
    cfg,
    max_workers: int = 8,
    fault_hook=None,
    retry=None,
    success_hook=None,
) -> List[str]:
    """Render host-side, then write the JPEG pair, per (stem, pixels, mask, dims).

    The batch drivers' default export path: only the 65 KB mask crossed back
    from the device (see render.host_render); the 512x512 renders are computed
    here, in the same thread pool that JPEG-encodes them, overlapped with the
    next batch's device compute.
    """
    from nm03_capstone_project_tpu import native
    from nm03_capstone_project_tpu.render.host_render import host_render_pair

    out = Path(out_dir)
    # the C++ renderer produces byte-identical output to the NumPy one at
    # ~4x less host time (docs/PERF.md) — and releases the GIL, so the
    # export pool actually overlaps on multi-core hosts
    use_native = native.available()

    def write_one(item):
        stem, pixels, mask, dims = item
        if use_native:
            gray, seg = native.render_pair_native(pixels, mask, dims, cfg)
        else:
            gray, seg = host_render_pair(pixels, mask, dims, cfg)
        return _write_pair(out, stem, gray, seg)

    return _export_many(
        write_one,
        items,
        out,
        max_workers,
        fault_hook=fault_hook,
        retry=retry,
        success_hook=success_hook,
    )


def clean_directory(path: str | os.PathLike) -> None:
    """Recreate a directory empty.

    The reference does ``mkdir -p X && cd X && rm -rf *`` via system()
    (main_sequential.cpp:32-47); this is the same destructive clean-recreate
    without a shell.
    """
    import shutil

    p = Path(path)
    if p.exists():
        shutil.rmtree(p)
    p.mkdir(parents=True, exist_ok=True)
