"""Host-side JPEG export.

TPU-native equivalent of FAST ``ImageFileExporter`` (reference
main_sequential.cpp:61-73: two JPEGs per slice, ``<stem>_original.jpg`` and
``<stem>_processed.jpg``). Where the reference must serialize its whole
render+encode path through one shared Qt ``RenderToImage`` (the per-batch
barrier at main_parallel.cpp:172-216), here rendering happened on device and
only JPEG encoding runs on the host — embarrassingly parallel across a small
thread pool that overlaps with the next batch's device compute.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from nm03_capstone_project_tpu.utils.reporter import get_logger

_log = get_logger("export")


def save_jpeg(image: np.ndarray, path: str | os.PathLike, quality: int = 90) -> None:
    """Write a uint8 grayscale (H, W) array as JPEG.

    Encoder preference is MEASURED, not assumed: PIL rides libjpeg-turbo's
    SIMD entropy/DCT and encodes a 512x512 render in ~2.4 ms where the
    in-tree C++ encoder's scalar float DCT takes ~6.6 ms (docs/PERF.md,
    1-core host) — so PIL is first choice and the C++ encoder
    (csrc/nm03native.cpp, the counterpart of the reference's native
    ImageFileExporter, main_sequential.cpp:61-73) is the fallback for
    PIL-less deployments.
    """
    arr = np.asarray(image)
    if arr.dtype != np.uint8:
        raise ValueError(f"expected uint8 image, got {arr.dtype}")
    Path(path).parent.mkdir(parents=True, exist_ok=True)

    try:
        from PIL import Image
    except ImportError:
        Image = None

    if Image is not None:
        Image.fromarray(arr, mode="L").save(path, quality=quality)
        return

    from nm03_capstone_project_tpu import native

    if arr.ndim == 2 and native.available():
        Path(path).write_bytes(native.encode_jpeg_gray(arr, quality))
        return
    raise RuntimeError("no JPEG encoder available (PIL missing, native failed)")


def _write_pair(out: Path, stem: str, orig: np.ndarray, proc: np.ndarray) -> str:
    save_jpeg(orig, out / f"{stem}_original.jpg")
    save_jpeg(proc, out / f"{stem}_processed.jpg")
    return stem


def _export_many(write_one, items: Sequence, out_dir, max_workers: int) -> List[str]:
    """Concurrent per-slice export with containment; the shared scaffold.

    ``write_one(item) -> stem`` runs per slice on a thread pool; failures are
    contained and logged per slice (the reference's catch-and-continue at the
    export stage, main_sequential.cpp:267-271). Returns sorted stems written.
    """
    Path(out_dir).mkdir(parents=True, exist_ok=True)
    done: List[str] = []
    with cf.ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = {pool.submit(write_one, item): item[0] for item in items}
        for fut in cf.as_completed(futures):
            try:
                done.append(fut.result())
            except Exception as e:  # noqa: BLE001 - per-slice containment
                _log.warning("export failed for %s: %s", futures[fut], e)
    return sorted(done)


def export_pairs(
    items: Sequence[Tuple[str, np.ndarray, np.ndarray]],
    out_dir: str | os.PathLike,
    max_workers: int = 8,
) -> List[str]:
    """Write (stem, original, processed) triples as JPEG pairs concurrently."""
    out = Path(out_dir)
    return _export_many(
        lambda it: _write_pair(out, it[0], it[1], it[2]), items, out, max_workers
    )


def render_export_pairs(
    items: Sequence[Tuple[str, np.ndarray, np.ndarray, np.ndarray]],
    out_dir: str | os.PathLike,
    cfg,
    max_workers: int = 8,
) -> List[str]:
    """Render host-side, then write the JPEG pair, per (stem, pixels, mask, dims).

    The batch drivers' default export path: only the 65 KB mask crossed back
    from the device (see render.host_render); the 512x512 renders are computed
    here, in the same thread pool that JPEG-encodes them, overlapped with the
    next batch's device compute.
    """
    from nm03_capstone_project_tpu import native
    from nm03_capstone_project_tpu.render.host_render import host_render_pair

    out = Path(out_dir)
    # the C++ renderer produces byte-identical output to the NumPy one at
    # ~4x less host time (docs/PERF.md) — and releases the GIL, so the
    # export pool actually overlaps on multi-core hosts
    use_native = native.available()

    def write_one(item):
        stem, pixels, mask, dims = item
        if use_native:
            gray, seg = native.render_pair_native(pixels, mask, dims, cfg)
        else:
            gray, seg = host_render_pair(pixels, mask, dims, cfg)
        return _write_pair(out, stem, gray, seg)

    return _export_many(write_one, items, out, max_workers)


def clean_directory(path: str | os.PathLike) -> None:
    """Recreate a directory empty.

    The reference does ``mkdir -p X && cd X && rm -rf *`` via system()
    (main_sequential.cpp:32-47); this is the same destructive clean-recreate
    without a shell.
    """
    import shutil

    p = Path(path)
    if p.exists():
        shutil.rmtree(p)
    p.mkdir(parents=True, exist_ok=True)
