"""On-device rendering: letterboxed grayscale + segmentation overlay.

TPU-native equivalent of the reference's export-side render stack
(SURVEY.md section 2.2): ``RenderToImage::create(Color::Black(), 512, 512)``
(test_pipeline.cpp:164, main_sequential.cpp:258) with an ``ImageRenderer``
for the original and a ``SegmentationRenderer`` (label 1 = white, fill
opacity 0.6, border opacity 1.0, border radius 2; test_pipeline.cpp:136-146)
for the mask.

Rendering is pure array math, so it runs *on device, batched, inside the same
jit* as the pipeline — where the reference must serialize exports through one
shared Qt/OpenGL ``RenderToImage`` (the thread-safety barrier at
main_parallel.cpp:336-346), here the whole batch renders in parallel and only
finished uint8 canvases cross back to the host for JPEG encoding.

Geometry: the slice is scaled (bilinear for grayscale, nearest for masks) by
``min(out/h, out/w)`` and centered on a black canvas — aspect-preserving
letterboxing of arbitrary (traced) slice dims onto the static output size.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.core.image import valid_mask
from nm03_capstone_project_tpu.ops.morphology import erode


def _letterbox_coords(dims: jax.Array, out_size: int):
    """Source sampling coords for each output pixel, plus the in-bounds mask.

    Returns (src_y, src_x, inside) each shaped (out, out), as float32 source
    coordinates; `inside` marks output pixels that fall inside the scaled
    slice. Works with traced dims: the scale is computed at run time, the
    shapes are static.
    """
    h = dims[..., 0].astype(jnp.float32)
    w = dims[..., 1].astype(jnp.float32)
    scale = jnp.minimum(out_size / h, out_size / w)
    dest_h = h * scale
    dest_w = w * scale
    off_y = (out_size - dest_h) / 2.0
    off_x = (out_size - dest_w) / 2.0
    oy = jax.lax.broadcasted_iota(jnp.float32, (out_size, out_size), 0)
    ox = jax.lax.broadcasted_iota(jnp.float32, (out_size, out_size), 1)
    src_y = (oy - off_y + 0.5) / scale - 0.5
    src_x = (ox - off_x + 0.5) / scale - 0.5
    inside = (
        (oy >= jnp.floor(off_y))
        & (oy < jnp.ceil(off_y + dest_h))
        & (ox >= jnp.floor(off_x))
        & (ox < jnp.ceil(off_x + dest_w))
    )
    return src_y, src_x, inside


def _sample_bilinear(img: jax.Array, src_y, src_x, dims) -> jax.Array:
    h = dims[..., 0]
    w = dims[..., 1]
    y0 = jnp.clip(jnp.floor(src_y).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(src_x).astype(jnp.int32), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    fy = jnp.clip(src_y - y0.astype(jnp.float32), 0.0, 1.0)
    fx = jnp.clip(src_x - x0.astype(jnp.float32), 0.0, 1.0)

    def at(yy, xx):
        return img[yy, xx]

    v00 = at(y0, x0)
    v01 = at(y0, x1)
    v10 = at(y1, x0)
    v11 = at(y1, x1)
    top = v00 * (1 - fx) + v01 * fx
    bot = v10 * (1 - fx) + v11 * fx
    return top * (1 - fy) + bot * fy


def _sample_nearest(img: jax.Array, src_y, src_x, dims) -> jax.Array:
    h = dims[..., 0]
    w = dims[..., 1]
    yy = jnp.clip(jnp.round(src_y).astype(jnp.int32), 0, h - 1)
    xx = jnp.clip(jnp.round(src_x).astype(jnp.int32), 0, w - 1)
    return img[yy, xx]


def render_gray(
    pixels: jax.Array, dims: jax.Array, out_size: int = 512
) -> jax.Array:
    """Letterboxed window-normalized grayscale render -> uint8 (out, out).

    Equivalent of ImageRenderer feeding RenderToImage: intensities are
    windowed to the slice's own [min, max] over its true extent (FAST's
    renderer auto-windows from the image's intensity range), scaled to 0..255
    on a black canvas.
    """
    canvas_hw: Tuple[int, int] = (pixels.shape[-2], pixels.shape[-1])
    vmask = valid_mask(dims, canvas_hw)
    big = jnp.float32(3.4e38)
    vmin = jnp.min(jnp.where(vmask, pixels, big))
    vmax = jnp.max(jnp.where(vmask, pixels, -big))
    rng = jnp.maximum(vmax - vmin, 1e-6)
    src_y, src_x, inside = _letterbox_coords(dims, out_size)
    sampled = _sample_bilinear(pixels, src_y, src_x, dims)
    gray = (sampled - vmin) / rng * 255.0
    gray = jnp.where(inside, gray, 0.0)
    return jnp.clip(gray, 0, 255).astype(jnp.uint8)


def render_segmentation(
    mask: jax.Array,
    dims: jax.Array,
    out_size: int = 512,
    opacity: float = 0.6,
    border_opacity: float = 1.0,
    border_radius: int = 2,
) -> jax.Array:
    """Letterboxed white-on-black label render -> uint8 (out, out).

    Equivalent of SegmentationRenderer::create({1: White}, 0.6, 1.0, 2)
    rendered alone into RenderToImage (the reference's batch drivers connect
    only the segmentation renderer for the ``_processed`` export,
    main_sequential.cpp:66-73): label pixels composite white over black at
    ``opacity``; a border band of ``border_radius`` pixels (in render space)
    at the region boundary composites at ``border_opacity``.
    """
    alpha = _mask_alpha(mask, dims, out_size, opacity, border_opacity, border_radius)
    return jnp.clip(alpha * 255.0, 0, 255).astype(jnp.uint8)


def _mask_alpha(
    mask, dims, out_size, opacity, border_opacity, border_radius
) -> jax.Array:
    """Per-pixel overlay alpha in render space: fill opacity inside the
    label, border opacity on the `border_radius`-pixel boundary band."""
    src_y, src_x, inside = _letterbox_coords(dims, out_size)
    m = _sample_nearest((mask > 0).astype(jnp.uint8), src_y, src_x, dims)
    m = (m > 0) & inside
    interior = erode(m, 2 * border_radius + 1, "disk")
    border = m & ~interior
    return jnp.where(border, border_opacity, jnp.where(m, opacity, 0.0))


def render_overlay(
    pixels: jax.Array,
    mask: jax.Array,
    dims: jax.Array,
    out_size: int = 512,
    opacity: float = 0.6,
    border_opacity: float = 1.0,
    border_radius: int = 2,
) -> jax.Array:
    """Grayscale render with the white label composited on top -> uint8.

    The reference's test window stacks ImageRenderer + SegmentationRenderer
    in one view; this produces that composite for anyone who wants the mask
    in anatomical context (not part of the batch export contract).
    """
    gray = render_gray(pixels, dims, out_size).astype(jnp.float32)
    alpha = _mask_alpha(mask, dims, out_size, opacity, border_opacity, border_radius)
    out = gray * (1.0 - alpha) + 255.0 * alpha
    return jnp.clip(out, 0, 255).astype(jnp.uint8)


def render_pair(
    pixels: jax.Array, mask: jax.Array, dims: jax.Array, cfg
) -> Tuple[jax.Array, jax.Array]:
    """(grayscale render, segmentation render) for one slice per ``cfg``.

    The single home of the batch drivers' export contract (one `_original`
    and one `_processed` image per slice, main_sequential.cpp:61-73) so the
    render parameters are threaded from PipelineConfig in exactly one place;
    vmap over a leading axis for stacks.
    """
    gray = render_gray(pixels, dims, cfg.render_size)
    seg = render_segmentation(
        mask,
        dims,
        cfg.render_size,
        cfg.overlay_opacity,
        cfg.overlay_border_opacity,
        cfg.overlay_border_radius,
    )
    return gray, seg
