"""On-device rendering: letterboxed grayscale + segmentation overlay.

TPU-native equivalent of the reference's export-side render stack
(SURVEY.md section 2.2): ``RenderToImage::create(Color::Black(), 512, 512)``
(test_pipeline.cpp:164, main_sequential.cpp:258) with an ``ImageRenderer``
for the original and a ``SegmentationRenderer`` (label 1 = white, fill
opacity 0.6, border opacity 1.0, border radius 2; test_pipeline.cpp:136-146)
for the mask.

Rendering is pure array math, so it runs *on device, batched, inside the same
jit* as the pipeline — where the reference must serialize exports through one
shared Qt/OpenGL ``RenderToImage`` (the thread-safety barrier at
main_parallel.cpp:336-346), here the whole batch renders in parallel and only
finished uint8 canvases cross back to the host for JPEG encoding.

Geometry: the slice is scaled (bilinear for grayscale, nearest for masks) by
``min(out/h, out/w)`` and centered on a black canvas — aspect-preserving
letterboxing of arbitrary (traced) slice dims onto the static output size.

The letterbox transform is axis-aligned, so the source coordinate of an
output pixel separates into a per-row and a per-column 1D coordinate, and
the resample has two equivalent formulations selected per backend:

* on TPU, ``R @ img @ C^T`` with (out, H)/(out, W) interpolation matrices
  holding at most two nonzeros per row (one for nearest) — gathers are the
  slow path on a TPU, matmuls are the MXU's native operation
  (``precision='highest'`` keeps the f32 weights exact, same guard as
  ops.sharpen);
* elsewhere, a separable two-stage gather (lerp rows, then columns), which
  measures faster than the dense matmuls on the CPU backend.

Both formulations share the rows-then-columns lerp structure, so they agree
to the last bit everywhere except clamped-edge pixels, where the matmul
folds the two interpolation weights into one matrix entry ((1-f)+f rounds
once) while the gather adds two products — an ulp-level divergence of at
most one 8-bit count, within the golden suite's tolerance. The nearest/mask
path is exact on both.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.core.image import valid_mask
from nm03_capstone_project_tpu.ops.morphology import erode


def _letterbox_coords(dims: jax.Array, out_size: int):
    """Per-axis source coords for each output row/col, plus in-bounds mask.

    Returns (src_y, src_x, inside): 1D float32 source coordinates shaped
    (out,) for the row and column axes (the letterbox scale is axis-aligned,
    so the 2D sampling grid is their outer product), and the (out, out) bool
    mask of output pixels inside the scaled slice. Works with traced dims:
    the scale is computed at run time, the shapes are static.
    """
    h = dims[..., 0].astype(jnp.float32)
    w = dims[..., 1].astype(jnp.float32)
    scale = jnp.minimum(out_size / h, out_size / w)
    dest_h = h * scale
    dest_w = w * scale
    off_y = (out_size - dest_h) / 2.0
    off_x = (out_size - dest_w) / 2.0
    o = jnp.arange(out_size, dtype=jnp.float32)
    src_y = (o - off_y + 0.5) / scale - 0.5
    src_x = (o - off_x + 0.5) / scale - 0.5
    inside_y = (o >= jnp.floor(off_y)) & (o < jnp.ceil(off_y + dest_h))
    inside_x = (o >= jnp.floor(off_x)) & (o < jnp.ceil(off_x + dest_w))
    inside = inside_y[:, None] & inside_x[None, :]
    return src_y, src_x, inside


def _bilinear_weights(src: jax.Array, n: int, extent: jax.Array) -> jax.Array:
    """(out, n) interpolation matrix: two nonzeros per row, clamp-to-edge.

    ``src`` is the 1D source coordinate per output position; ``extent`` the
    (traced) true size along the axis — canvas columns beyond it get zero
    weight, reproducing the gather path's index clamp.
    """
    i0 = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, extent - 1)
    i1 = jnp.clip(i0 + 1, 0, extent - 1)
    f = jnp.clip(src - i0.astype(jnp.float32), 0.0, 1.0)
    cols = jnp.arange(n, dtype=jnp.int32)[None, :]
    w0 = jnp.where(cols == i0[:, None], 1.0 - f[:, None], 0.0)
    w1 = jnp.where(cols == i1[:, None], f[:, None], 0.0)
    return w0 + w1  # i0 == i1 at the clamped edge: weights still sum to 1


def _nearest_weights(src: jax.Array, n: int, extent: jax.Array) -> jax.Array:
    """(out, n) one-hot selection matrix (round-to-nearest, clamp-to-edge)."""
    idx = jnp.clip(jnp.round(src).astype(jnp.int32), 0, extent - 1)
    cols = jnp.arange(n, dtype=jnp.int32)[None, :]
    return (cols == idx[:, None]).astype(jnp.float32)


def _mxu_backend() -> bool:
    from nm03_capstone_project_tpu.core.backend import is_tpu_backend

    return is_tpu_backend()


def _resample(img: jax.Array, ry: jax.Array, cx: jax.Array) -> jax.Array:
    """R @ img @ C^T with full f32 precision on the MXU."""
    return jnp.matmul(
        jnp.matmul(ry, img, precision="highest"),
        cx.T,
        precision="highest",
    )


def _sample_bilinear(img: jax.Array, src_y, src_x, dims) -> jax.Array:
    if _mxu_backend():
        ry = _bilinear_weights(src_y, img.shape[-2], dims[..., 0])
        cx = _bilinear_weights(src_x, img.shape[-1], dims[..., 1])
        return _resample(img.astype(jnp.float32), ry, cx)
    # separable two-stage gather: lerp rows first (small row gathers), then
    # columns — same rows-then-columns structure as the matmul path (bitwise
    # equal away from clamped edges; see module docstring for the edge case)
    h, w = dims[..., 0], dims[..., 1]
    y0 = jnp.clip(jnp.floor(src_y).astype(jnp.int32), 0, h - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    fy = jnp.clip(src_y - y0.astype(jnp.float32), 0.0, 1.0)[:, None]
    x0 = jnp.clip(jnp.floor(src_x).astype(jnp.int32), 0, w - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    fx = jnp.clip(src_x - x0.astype(jnp.float32), 0.0, 1.0)[None, :]
    rows = img[y0, :] * (1 - fy) + img[y1, :] * fy  # (out, W_canvas)
    return rows[:, x0] * (1 - fx) + rows[:, x1] * fx


def _sample_nearest(img: jax.Array, src_y, src_x, dims) -> jax.Array:
    """One-hot selection — exact for {0,1} masks on either path."""
    if _mxu_backend():
        ry = _nearest_weights(src_y, img.shape[-2], dims[..., 0])
        cx = _nearest_weights(src_x, img.shape[-1], dims[..., 1])
        return _resample(img.astype(jnp.float32), ry, cx)
    h, w = dims[..., 0], dims[..., 1]
    yy = jnp.clip(jnp.round(src_y).astype(jnp.int32), 0, h - 1)
    xx = jnp.clip(jnp.round(src_x).astype(jnp.int32), 0, w - 1)
    return img[yy, :][:, xx]  # two cheap 1D gathers, not one 2D gather


def render_gray(
    pixels: jax.Array, dims: jax.Array, out_size: int = 512
) -> jax.Array:
    """Letterboxed window-normalized grayscale render -> uint8 (out, out).

    Equivalent of ImageRenderer feeding RenderToImage: intensities are
    windowed to the slice's own [min, max] over its true extent (FAST's
    renderer auto-windows from the image's intensity range), scaled to 0..255
    on a black canvas.
    """
    canvas_hw: Tuple[int, int] = (pixels.shape[-2], pixels.shape[-1])
    vmask = valid_mask(dims, canvas_hw)
    big = jnp.float32(3.4e38)
    vmin = jnp.min(jnp.where(vmask, pixels, big))
    vmax = jnp.max(jnp.where(vmask, pixels, -big))
    rng = jnp.maximum(vmax - vmin, 1e-6)
    src_y, src_x, inside = _letterbox_coords(dims, out_size)
    sampled = _sample_bilinear(pixels, src_y, src_x, dims)
    gray = (sampled - vmin) / rng * 255.0
    gray = jnp.where(inside, gray, 0.0)
    return jnp.clip(gray, 0, 255).astype(jnp.uint8)


def render_segmentation(
    mask: jax.Array,
    dims: jax.Array,
    out_size: int = 512,
    opacity: float = 0.6,
    border_opacity: float = 1.0,
    border_radius: int = 2,
) -> jax.Array:
    """Letterboxed white-on-black label render -> uint8 (out, out).

    Equivalent of SegmentationRenderer::create({1: White}, 0.6, 1.0, 2)
    rendered alone into RenderToImage (the reference's batch drivers connect
    only the segmentation renderer for the ``_processed`` export,
    main_sequential.cpp:66-73): label pixels composite white over black at
    ``opacity``; a border band of ``border_radius`` pixels (in render space)
    at the region boundary composites at ``border_opacity``.
    """
    alpha = _mask_alpha(mask, dims, out_size, opacity, border_opacity, border_radius)
    return jnp.clip(alpha * 255.0, 0, 255).astype(jnp.uint8)


def _mask_alpha(
    mask, dims, out_size, opacity, border_opacity, border_radius
) -> jax.Array:
    """Per-pixel overlay alpha in render space: fill opacity inside the
    label, border opacity on the `border_radius`-pixel boundary band."""
    src_y, src_x, inside = _letterbox_coords(dims, out_size)
    m = _sample_nearest((mask > 0).astype(jnp.uint8), src_y, src_x, dims)
    m = (m > 0) & inside
    interior = erode(m, 2 * border_radius + 1, "disk")
    border = m & ~interior
    return jnp.where(border, border_opacity, jnp.where(m, opacity, 0.0))


def render_overlay(
    pixels: jax.Array,
    mask: jax.Array,
    dims: jax.Array,
    out_size: int = 512,
    opacity: float = 0.6,
    border_opacity: float = 1.0,
    border_radius: int = 2,
) -> jax.Array:
    """Grayscale render with the white label composited on top -> uint8.

    The reference's test window stacks ImageRenderer + SegmentationRenderer
    in one view; this produces that composite for anyone who wants the mask
    in anatomical context (not part of the batch export contract).
    """
    gray = render_gray(pixels, dims, out_size).astype(jnp.float32)
    alpha = _mask_alpha(mask, dims, out_size, opacity, border_opacity, border_radius)
    out = gray * (1.0 - alpha) + 255.0 * alpha
    return jnp.clip(out, 0, 255).astype(jnp.uint8)


def _opacity_u8(opacity: float) -> int:
    """The uint8 level ``clip(opacity * 255, 0, 255).astype(uint8)`` yields.

    Computed host-side with the same f32 multiply and truncating cast the
    unfused alpha path performs on device, so the fused integer
    segmentation leg is pixel-identical by construction (e.g. 0.6 ->
    153: f32(0.6) * 255 rounds to 153.000006, truncates to 153).
    """
    import numpy as np

    v = np.float32(opacity) * np.float32(255.0)
    return int(np.clip(v, np.float32(0.0), np.float32(255.0)))


def render_pair_fused(
    pixels: jax.Array, mask: jax.Array, dims: jax.Array, cfg
) -> Tuple[jax.Array, jax.Array]:
    """Both export renders in one fused pass — pixel-identical, less work.

    Work the two independent render calls duplicate or waste, eliminated
    here (the render stage measured HBM/memory-bound at a fraction of a
    GB/s, so dropped intermediates are direct wins):

    * the letterbox geometry (per-axis source coordinates + inside mask)
      is computed once and shared by both legs;
    * the segmentation leg stays in uint8/bool end to end: the overlay
      alpha canvas (f32 multiply + clip + cast per pixel) is replaced by a
      select between the three precomputed uint8 levels of
      :func:`_opacity_u8` — exactly the values the f32 path produces;
    * the border erosion runs on the fused morphology fold (no
      materialized 21-view stack; see ops.morphology).

    The grayscale leg's arithmetic is kept operation-for-operation
    identical to :func:`render_gray` — windowing, resample, scale, cast —
    so both outputs are bitwise equal to the unfused pair on every
    backend; tests assert it.
    """
    out_size = cfg.render_size
    src_y, src_x, inside = _letterbox_coords(dims, out_size)
    # grayscale leg (same ops as render_gray, sharing the coords)
    canvas_hw: Tuple[int, int] = (pixels.shape[-2], pixels.shape[-1])
    vmask = valid_mask(dims, canvas_hw)
    big = jnp.float32(3.4e38)
    vmin = jnp.min(jnp.where(vmask, pixels, big))
    vmax = jnp.max(jnp.where(vmask, pixels, -big))
    rng = jnp.maximum(vmax - vmin, 1e-6)
    sampled = _sample_bilinear(pixels, src_y, src_x, dims)
    gray = (sampled - vmin) / rng * 255.0
    gray = jnp.where(inside, gray, 0.0)
    gray = jnp.clip(gray, 0, 255).astype(jnp.uint8)
    # segmentation leg, integer end to end
    m = _sample_nearest((mask > 0).astype(jnp.uint8), src_y, src_x, dims)
    m = (m > 0) & inside
    interior = erode(m, 2 * cfg.overlay_border_radius + 1, "disk")
    border = m & ~interior
    fill = jnp.uint8(_opacity_u8(cfg.overlay_opacity))
    edge = jnp.uint8(_opacity_u8(cfg.overlay_border_opacity))
    seg = jnp.where(border, edge, jnp.where(m, fill, jnp.uint8(0)))
    return gray, seg


def render_pair(
    pixels: jax.Array, mask: jax.Array, dims: jax.Array, cfg
) -> Tuple[jax.Array, jax.Array]:
    """(grayscale render, segmentation render) for one slice per ``cfg``.

    The single home of the batch drivers' export contract (one `_original`
    and one `_processed` image per slice, main_sequential.cpp:61-73) so the
    render parameters are threaded from PipelineConfig in exactly one place;
    vmap over a leading axis for stacks. ``cfg.render_fused`` (default
    True) routes through :func:`render_pair_fused` — pixel-identical,
    shared geometry, integer mask leg; False keeps the two independent
    render calls (the comparison baseline bench.py times the fused path
    against).
    """
    if getattr(cfg, "render_fused", True):
        return render_pair_fused(pixels, mask, dims, cfg)
    gray = render_gray(pixels, dims, cfg.render_size)
    seg = render_segmentation(
        mask,
        dims,
        cfg.render_size,
        cfg.overlay_opacity,
        cfg.overlay_border_opacity,
        cfg.overlay_border_radius,
    )
    return gray, seg
