"""The online segmentation service: HTTP front end + lifecycle.

``nm03-serve`` turns the batch pipeline into a long-running service:

* ``POST /v1/segment`` — one slice in (DICOM bytes or a raw float32
  array), segmentation out (JPEG pair or mask summary, JSON envelope);
* ``GET /healthz`` — liveness (the process is up);
* ``GET /readyz`` — readiness: 200 while warmed, admitting, and at least
  one replica lane is healthy; the payload carries ``capacity`` (the
  healthy-lane fraction) and ``lanes.quarantined`` so a balancer can
  WEIGH a partially-degraded replica instead of dropping it (ISSUE 8 —
  a 3-of-4-lane replica is 75% of a replica, not zero). 503 only when
  un-warm, draining, or EVERY lane is quarantined (the one-way CPU
  degradation, the last resort) — then the balancer drains the replica
  while its in-flight work still completes;
* ``GET /metrics`` — Prometheus text exposition straight from the PR-1
  obs registry; ``GET /metrics.json`` — the ``nm03.metrics.v1`` snapshot
  (same schema ``check_telemetry.py --metrics`` validates).

Dependency-free by design: stdlib ``ThreadingHTTPServer`` — one daemon
thread per connection doing decode/render/encode host work, all device
dispatch funneled through the single batcher thread. This is deliberately
the same layering as the batch drivers (IO pool around one device stream),
re-derived for open-loop traffic.

Graceful drain (SIGTERM): admissions stop immediately (503 +
``Retry-After``), the batcher finishes every admitted batch, metrics and
events flush through the normal ``RunContext.close`` path, and only then
does the listener exit — reusing the PR-3 discipline that a response, like
an exported JPEG, is either complete or not sent at all.
"""

from __future__ import annotations

import argparse
import base64
import hashlib
import json
import os
import signal
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlsplit

import numpy as np

from nm03_capstone_project_tpu.cache import (
    InflightIndex,
    ResultStore,
    etag_matches,
    parse_bytes,
    result_key,
)
from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.obs.trace import (
    SERVE_TRACE_EVENT,
    TraceContext,
    new_trace_id,
    sanitize_trace_id,
)
from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
from nm03_capstone_project_tpu.serving.executor import DEFAULT_BUCKETS, WarmExecutor
from nm03_capstone_project_tpu.serving.metrics import (
    COMPILE_CACHE_HITS_TOTAL,
    COMPILE_CACHE_LOAD_SECONDS,
    COMPILE_CACHE_MISSES_TOTAL,
    COMPILE_SECONDS,
    EXECUTABLE_FLOPS,
    EXECUTABLE_HBM_BYTES,
    LATENCY_BUCKETS,
    SERVING_DEGRADED,
    SERVING_INFLIGHT,
    SERVING_READY,
    SERVING_REQUESTS_TOTAL,
    SERVING_REQUEST_SECONDS,
    SERVING_RESULT_CACHE_BYTES,
    SERVING_RESULT_CACHE_EVICT_TOTAL,
    SERVING_RESULT_CACHE_FILL_TOTAL,
    SERVING_RESULT_CACHE_HIT_TOTAL,
    SERVING_RESULT_CACHE_MISS_TOTAL,
    SERVING_SHED_TOTAL,
)
from nm03_capstone_project_tpu.serving.queue import (
    AdmissionQueue,
    QueueClosed,
    QueueFull,
    ServeRequest,
)
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("serving")

RETRY_AFTER_S = 1  # the shed hint: capacity problems clear in ~one window


class RequestRejected(ValueError):
    """A request refused before admission; carries the HTTP status."""

    def __init__(self, http_status: int, message: str, status_label: str = "invalid"):
        super().__init__(message)
        self.http_status = http_status
        self.status_label = status_label


def _cache_fault_hook(fault_plan, obs):
    """The compile cache's chaos hook (FaultPlan site ``cache``), or None.

    Fired with the entry filename before each store; an ``io_error`` rule
    aborts that write — the drill that proves a failed persist degrades
    to a plain recompile on the next start, never a torn or missing-but-
    claimed entry.
    """
    if fault_plan is None or not fault_plan.has_site("cache"):
        return None
    from nm03_capstone_project_tpu.resilience import InjectedExportError

    def hook(entry_name: str) -> None:
        rule = fault_plan.fire(
            "cache", obs=obs, stem=entry_name, kinds=("io_error",)
        )
        if rule is not None:
            raise InjectedExportError(
                f"injected compile-cache io error ({entry_name})"
            )

    return hook


def _result_corrupt_hook(fault_plan, obs):
    """The result store's chaos hook (site ``cache``/``corrupt_entry``).

    Consulted by ``ResultStore.lookup`` with the result-key digest; a
    firing rule hands the verifier a payload with one flipped byte — the
    drill that proves verify-on-read evicts and recomputes, so a corrupt
    entry is a miss, never a wrong mask (docs/RESILIENCE.md).
    """
    if fault_plan is None or not fault_plan.has_site("cache"):
        return None

    def hook(digest: str) -> bool:
        return fault_plan.fire(
            "cache", obs=obs, stem=digest, kinds=("corrupt_entry",)
        ) is not None

    return hook


# the response fields a result entry stores, per algo: everything derived
# from the INPUT (and so covered by the content-addressed key), nothing
# per-execution (request ids, queue waits, lane numbers, device seconds —
# a hit merges fresh values for those). Keeping the stored subset
# execution-free is what makes the ETag stable across evict/recompute
# cycles: the bit-identity gate in tests/bench rides on it.
_CACHEABLE_SEGMENT_FIELDS = (
    "shape",
    "grow_converged",
    "mask_pixels",
    "mask_sha256",
    "original_jpeg_b64",
    "processed_jpeg_b64",
)
_CACHEABLE_VOLUME_FIELDS = (
    "shape",
    "grow_converged",
    "mask_voxels",
    "mask_sha256",
    "mask_b64",
    "mhd_header_b64",
    "mhd_data_b64",
    "mhd_data_file",
)


class ServingApp:
    """Everything behind the HTTP handler: queue, batcher, executor, state."""

    def __init__(
        self,
        cfg: PipelineConfig = None,
        queue_capacity: int = 64,
        buckets=DEFAULT_BUCKETS,
        max_wait_s: float = 0.01,
        max_batch: Optional[int] = None,
        request_timeout_s: float = 60.0,
        jpeg_quality: int = 90,
        resilience=None,
        fault_plan=None,
        obs=None,
        lanes: Optional[int] = None,
        lane_probe_interval_s: Optional[float] = None,
        compile_cache_dir: Optional[str] = None,
        slo=None,
        volume_serving: bool = False,
        volume_depth_buckets=None,
        volume_queue_capacity: int = 4,
        volume_timeout_s: float = 300.0,
        distributed_init: bool = False,
        ledger_profile_interval_s: float = 0.0,
        ledger_profile_ms: int = 200,
        result_cache_bytes: int = 0,
    ):
        from nm03_capstone_project_tpu.obs import RunContext
        from nm03_capstone_project_tpu.serving.executor import (
            DEFAULT_LANE_PROBE_INTERVAL_S,
        )

        self.cfg = cfg if cfg is not None else PipelineConfig()
        self.obs = obs if obs is not None else RunContext.create(driver="serve")
        self.compile_cache_dir = compile_cache_dir
        self._attached_cache = None
        # the stable replica identity block (ISSUE 13): what the fleet
        # router's per-replica metrics and the rolling-restart log name
        # this process by. `id` is per-incarnation (a restart mints a new
        # one — that is the point: the restart drill proves the pid AND
        # id changed); `relaunch_argv`/`cwd` are filled by the CLI path
        # (main()) only — an in-process app is not restartable
        self.replica_identity = {
            "id": uuid.uuid4().hex[:12],
            "pid": os.getpid(),
            "start_unix": round(time.time(), 3),
        }
        self.queue = AdmissionQueue(queue_capacity)
        # efficiency telemetry (obs.saturation, ISSUE 10): lane busy/idle,
        # padding waste, occupancy and MFU over a sliding window — fed by
        # the executor/batcher, pull-refreshed on every scrape
        from nm03_capstone_project_tpu.obs.saturation import SaturationMonitor

        self.saturation = SaturationMonitor(registry=self.obs.registry)
        # device-time ledger (obs.ledger, ISSUE 16): per-request cost
        # attribution, the live stage-share pie, and the per-bucket HBM
        # table — fed by the executor/batcher, pull-refreshed on every
        # scrape like the saturation monitor. The sampler thread takes
        # short profiler captures on a cadence (0 = disabled, the
        # in-process/test default; the CLI turns it on) and NEVER queues
        # behind a client GET /debug/profile pull — it skips and counts.
        from nm03_capstone_project_tpu.obs.ledger import (
            DeviceTimeLedger,
            ProfileSampler,
        )

        self.ledger = DeviceTimeLedger(registry=self.obs.registry)
        self._ledger_sampler = ProfileSampler(
            self.ledger,
            interval_s=float(ledger_profile_interval_s),
            duration_ms=int(ledger_profile_ms),
        )
        # the SLO plane (ISSUE 14): burn rates/budget computed from the
        # request histogram/counters this app already maintains; created
        # only when an objective was declared, pull-refreshed on every
        # scrape like the saturation monitor
        self.slo = None
        if slo is not None:
            from nm03_capstone_project_tpu.obs.slo import SLOMonitor

            self.slo = SLOMonitor(
                self.obs.registry, slo,
                SERVING_REQUESTS_TOTAL, SERVING_REQUEST_SECONDS,
            )
        self.executor = WarmExecutor(
            self.cfg,
            buckets=tuple(buckets),
            resilience=resilience,
            obs=self.obs,
            fault_plan=fault_plan,
            lanes=lanes,
            lane_probe_interval_s=(
                lane_probe_interval_s
                if lane_probe_interval_s is not None
                else DEFAULT_LANE_PROBE_INTERVAL_S
            ),
            saturation=self.saturation,
            ledger=self.ledger,
        )
        self.batcher = DynamicBatcher(
            self.queue,
            self.executor,
            max_wait_s=max_wait_s,
            max_batch=max_batch,
            obs=self.obs,
        )
        # whole-volume serving (ISSUE 15): the gang lane behind
        # POST /v1/segment-volume — its own bounded admission queue, the
        # batcher's gang gate, the z-sharded mesh program per depth
        # bucket. Opt-in (--volume-serving): warmup compiles one mesh
        # executable per depth bucket, which a slice-only replica must
        # not pay.
        self.volumes = None
        self.volume_timeout_s = float(volume_timeout_s)
        if volume_serving:
            from nm03_capstone_project_tpu.serving.volumes import (
                DEFAULT_VOLUME_DEPTH_BUCKETS,
                VolumeGang,
            )

            self.volumes = VolumeGang(
                self.cfg,
                self.executor,
                self.batcher,
                obs=self.obs,
                queue_capacity=volume_queue_capacity,
                depth_buckets=(
                    tuple(volume_depth_buckets)
                    if volume_depth_buckets
                    else DEFAULT_VOLUME_DEPTH_BUCKETS
                ),
                fault_plan=fault_plan,
                distributed=distributed_init,
            )
        # the content-addressed result tier (ISSUE 19): replica-side store
        # in front of the batcher, bounded by bytes (0 = disabled). The
        # in-flight index exists whenever the tier does — it is what lets
        # an idempotent volume retry coalesce onto a running gang instead
        # of dispatching a second mesh-wide program.
        self.result_store = None
        self.volume_inflight = None
        if result_cache_bytes and int(result_cache_bytes) > 0:
            self.result_store = ResultStore(
                int(result_cache_bytes),
                corrupt_hook=_result_corrupt_hook(fault_plan, self.obs),
                on_evict=self._on_result_evict,
            )
            self.volume_inflight = InflightIndex()
            # the bytes gauge exists (at 0) from startup on any
            # tier-enabled process: its presence IS nm03-top's
            # tier-enabled signal, and a clean run's snapshot proves
            # "nothing resident" instead of saying nothing
            self._publish_result_bytes()
        # the program-version half of every result key: resolved lazily
        # (compilehub.persist imports jax) and then pinned for the
        # process's lifetime — the key contract, not a per-request cost
        self._rv_lock = threading.Lock()
        self._rv_value = None
        self.request_timeout_s = float(request_timeout_s)
        self.jpeg_quality = int(jpeg_quality)
        self.draining = False
        self._drain_lock = threading.Lock()
        self._drained = threading.Event()
        self._t0 = time.monotonic()
        self.registry = self.obs.registry
        if compile_cache_dir:
            # attach LAST (after every fallible construction above, so a
            # raising __init__ cannot strand the cache — and its
            # obs-capturing fault hook — on the process-global hub with no
            # close() ever coming) but still BEFORE warmup, so the lane
            # executables load from (and populate) the persistent cache;
            # an explicit dir wins over whatever $NM03_COMPILE_CACHE_DIR
            # may have auto-attached
            from nm03_capstone_project_tpu.compilehub import (
                ExecutableCache,
                get_hub,
            )

            try:
                self._attached_cache = ExecutableCache(
                    compile_cache_dir,
                    fault_hook=_cache_fault_hook(fault_plan, self.obs),
                )
            except OSError as e:
                # best-effort optimization, never a crash loop: one
                # replica with a bad mount serves (slowly) instead of
                # dying — same degrade get_hub() applies to the env path
                log.warning(
                    "compile cache dir %s unusable (%s); serving without "
                    "the persistent cache", compile_cache_dir, e,
                )
            else:
                get_hub().attach_cache(self._attached_cache)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> dict:
        """Warm every bucket, start the batcher; {bucket: warmup seconds}."""
        timings = self.executor.warmup()
        if self.volumes is not None:
            # after the executor's warmup (lanes resolved), before /readyz
            # flips: the first volume request must find warm mesh
            # executables, never a trace+compile stall
            timings["volume"] = self.volumes.warmup()
            self.volumes.start()
            from nm03_capstone_project_tpu.serving.metrics import (
                SERVING_VOLUME_ZSHARDS,
            )

            self.registry.gauge(
                SERVING_VOLUME_ZSHARDS,
                help="z-shards the last served volume spanned (the gang's "
                "mesh width; full fleet width from warmup)",
            ).set(self.volumes.z_shards)
        self.batcher.start()
        self.registry.gauge(
            SERVING_READY, help="1 = warmed and admitting, 0 otherwise"
        ).set(1)
        self._publish_compile_cost()
        # warmup filled the ledger's HBM table and stage map: publish the
        # per-bucket serving_executable_hbm_bytes gauges now, then start
        # the cadence sampler (no-op at interval 0)
        self.ledger.publish()
        self._ledger_sampler.start()
        self.obs.events.emit(
            "serving_ready",
            buckets=list(self.executor.buckets),
            lanes=self.executor.lane_count,
            warmup_s=timings,
        )
        return timings

    def _publish_compile_cost(self) -> None:
        """Surface the hub's per-spec compile/cost accounting as gauges.

        Runs after warmup (the spec set is complete then, and fixed for
        the process's lifetime — no unbounded label cardinality). The
        flops/HBM series only exist where the jaxlib exposes
        ``cost_analysis()``/``memory_analysis()`` on AOT executables.
        """
        from nm03_capstone_project_tpu.compilehub import get_hub

        hub = get_hub()
        # compile_seconds comes from the hub's own per-label map (labels
        # that collide — two cfg variants of one family — SUM there), so
        # the gauge and the /readyz compile_hub.compile_seconds map can
        # never disagree for the same label
        for spec, seconds in hub.compile_seconds().items():
            self.registry.gauge(
                COMPILE_SECONDS,
                help="compile wall-time per hub spec (AOT lower+compile; "
                "deferred specs pay at first call, see "
                "serving_warmup_seconds)",
                spec=spec,
            ).set(seconds)
        # flops/HBM are per-executable alternatives, not additive: on a
        # label collision keep the max (the conservative roofline
        # denominator), never last-sorted-wins
        flops: dict = {}
        hbm: dict = {}
        for entry in hub.cost_report():
            spec = entry["label"]
            if entry.get("flops") is not None:
                flops[spec] = max(flops.get(spec, 0.0), entry["flops"])
            if entry.get("peak_hbm_bytes") is not None:
                hbm[spec] = max(hbm.get(spec, 0.0), entry["peak_hbm_bytes"])
        for spec, v in flops.items():
            self.registry.gauge(
                EXECUTABLE_FLOPS,
                help="XLA cost_analysis flops per executable",
                spec=spec,
            ).set(v)
        for spec, v in hbm.items():
            self.registry.gauge(
                EXECUTABLE_HBM_BYTES,
                help="XLA memory_analysis resident bytes "
                "(arguments+outputs+temps-aliased) per executable",
                spec=spec,
            ).set(v)
        # persistent-cache accounting (ISSUE 9): published when THIS app
        # attached a cache — zeros included, so a cache-enabled cold
        # start is distinguishable from a run without the cache, and
        # check_telemetry can assert hits EXACTLY (compile_cache_hits_total==0
        # on the cold start, ==spec-count on the warm restart). Read from
        # our own cache object, not hub.stats(): a cache some OTHER
        # component attached to the shared hub must not bleed its hits
        # into this app's registry
        if self._attached_cache is not None:
            stats = self._attached_cache.readyz_stats()
            self.registry.counter(
                COMPILE_CACHE_HITS_TOTAL,
                help="warm executables deserialized from --compile-cache-dir "
                "instead of compiled",
            ).inc(stats["cache_hits"])
            self.registry.counter(
                COMPILE_CACHE_MISSES_TOTAL,
                help="persistent-cache lookups that fell through to a real "
                "compile (absent, corrupt or stale entries)",
            ).inc(stats["cache_misses"])
            self.registry.gauge(
                COMPILE_CACHE_LOAD_SECONDS,
                help="total executable deserialization wall — what the warm "
                "start paid instead of total_compile_seconds",
            ).set(stats["cache_load_seconds"])

    @property
    def ready(self) -> bool:
        """Warm, admitting, and not (fully) degraded.

        ``executor.degraded`` flips only when the LAST healthy lane is
        quarantined (serving/lanes.py): a replica with quarantined-but-
        not-all lanes stays ready at reduced ``capacity`` — pulling it
        out of the balancer would throw away its healthy chips, which is
        exactly the PR-6 policy ISSUE 8 replaces.
        """
        return (
            self.executor.warm and not self.draining and not self.executor.degraded
        )

    def status(self) -> dict:
        from nm03_capstone_project_tpu.compilehub import get_hub

        lane_count = self.executor.lane_count
        cache_stats = (
            self._attached_cache.readyz_stats()
            if self._attached_cache is not None else None
        )
        return {
            "ready": self.ready,
            # who is answering (ISSUE 13): id (per-incarnation), pid,
            # start time, warmup cache hits — the fields the fleet
            # router's metrics and the rolling-restart log key on;
            # relaunch_argv/cwd appear on CLI-launched replicas only
            "replica": {
                **self.replica_identity,
                "compile_cache_hits": (
                    cache_stats["cache_hits"] if cache_stats else None
                ),
            },
            "warm": self.executor.warm,
            "draining": self.draining,
            "degraded": self.executor.degraded,
            "degraded_cause": self.executor.degraded_cause,
            "queue_depth": len(self.queue),
            "queue_capacity": self.queue.capacity,
            # the request-size guards (ISSUE 13): what a fleet front-end's
            # probation canary must fit inside to be admissible here
            "canvas": self.cfg.canvas,
            "min_dim": self.cfg.min_dim,
            "buckets": list(self.executor.buckets),
            "batcher": self.batcher.stats(),
            # the sharded fleet: per-lane warm/inflight state, the replica
            # mesh shape, and the compile hub's registry accounting
            "lanes": {
                "count": lane_count,
                "ready": self.executor.lanes_ready,
                "quarantined": self.executor.quarantined_count,
                "per_lane": self.executor.lane_state(),
            },
            # healthy-lane fraction (None before lane resolution): what a
            # capacity-weighted balancer feeds on while ready stays 200
            "capacity": self.executor.capacity,
            "mesh_shape": [lane_count] if lane_count else None,
            # whole-volume serving (ISSUE 15): the gang lane's shape —
            # depth buckets, mesh width, its own queue, and the
            # default_cost the fleet router weighs unsized volume
            # requests by. {enabled: false} when not serving volumes.
            "volumes": (
                self.volumes.status()
                if self.volumes is not None
                else {"enabled": False}
            ),
            # the result tier (ISSUE 19). program_version is published
            # even with the tier off: it is the replica's result-key
            # identity, and the FLEET router's store keys on it — the
            # router only enables its tier when every healthy replica
            # agrees on one value (a mixed fleet mid-rolling-restart
            # bypasses the tier by construction, never serves stale).
            "result_cache": {
                "program_version": (
                    self.result_version() if self.executor.warm else None
                ),
                **(
                    {
                        **self.result_store.stats(),
                        "inflight": self.volume_inflight.stats(),
                    }
                    if self.result_store is not None
                    else {"enabled": False}
                ),
            },
            # stats() carries the total_compile_seconds rollup; the per-spec
            # map makes warmup cost visible without grepping logs (ISSUE 7)
            "compile_hub": {
                **get_hub().stats(),
                "compile_seconds": get_hub().compile_seconds(),
            },
            # the efficiency view (ISSUE 10): per-lane busy fractions and
            # MFU, padding waste, window occupancy — publish() also
            # refreshes the serving_* saturation gauges, so a /readyz
            # probe and a /metrics scrape can never disagree
            "saturation": self.saturation.publish(),
            # the cost view (ISSUE 16): device-seconds by account, the
            # sampled stage-share pie, per-bucket executable HBM —
            # publish() refreshes the ledger gauges for the same
            # never-disagree contract as the saturation block
            "ledger": self.ledger.publish(),
            # the SLO verdict (ISSUE 14): burn rates + budget against the
            # declared objective (null when none was declared)
            "slo": self.slo.publish() if self.slo is not None else None,
            # the clock handshake (ISSUE 14): this process's monotonic and
            # wall clocks at answer time, so the fleet router (and any
            # cross-process tooling) can recover this replica's
            # monotonic→wall offset — the datum the multi-log trace merge
            # normalizes span times with
            "clock": {
                "mono_s": round(time.monotonic(), 6),
                "ts_unix": round(time.time(), 6),
            },
            "uptime_s": round(time.monotonic() - self._t0, 3),
        }

    def begin_drain(self, reason: str = "sigterm", timeout_s: float = 120.0) -> bool:
        """Stop admissions, finish in-flight work, flush telemetry.

        Idempotent; safe from a signal-spawned thread. Returns True when
        the batcher fully drained inside ``timeout_s``.
        """
        with self._drain_lock:
            if self.draining:
                return self._drained.wait(timeout=timeout_s)
            self.draining = True
        self.registry.gauge(
            SERVING_READY, help="1 = warmed and admitting, 0 otherwise"
        ).set(0)
        self.obs.events.emit(
            "serving_drain", level="WARNING", reason=reason,
            queue_depth=len(self.queue),
        )
        self.queue.close()
        if self.volumes is not None:
            # same close-the-door-finish-the-room contract as the slice
            # queue: admitted volumes complete, later ones shed
            self.volumes.queue.close()
        drained = self.batcher.join(timeout_s=timeout_s)
        if self.volumes is not None:
            gang_drained = self.volumes.join(timeout_s=timeout_s)
            if not gang_drained:
                for r in self.volumes.queue.drain_pending():
                    r.fail(RuntimeError("server drain timed out"))
                log.warning(
                    "drain: volume gang did not finish inside %.0fs",
                    timeout_s,
                )
            drained = drained and gang_drained
        # final gauge refresh BEFORE the snapshot flush: the --metrics-out
        # artifact must carry the run's last efficiency window (the
        # subprocess drills gate on these gauges post-drain)
        try:
            self.saturation.publish()
        except Exception as e:  # noqa: BLE001 — telemetry never blocks a drain
            log.warning("drain: saturation publish failed: %s", e)
        # stop the ledger sampler first (a capture mid-drain would race
        # the profiler teardown), then refresh the ledger gauges so the
        # snapshot carries the run's final accounts/pie/HBM table
        try:
            self._ledger_sampler.stop()
            self.ledger.publish()
        except Exception as e:  # noqa: BLE001 — telemetry never blocks a drain
            log.warning("drain: ledger publish failed: %s", e)
        if self.slo is not None:
            try:
                self.slo.publish()  # the final SLO verdict rides the snapshot
            except Exception as e:  # noqa: BLE001 — never blocks a drain
                log.warning("drain: SLO publish failed: %s", e)
        if not drained:
            # a wedged drain still must answer whoever is parked on wait():
            # fail the un-popped tail so handler threads return 500, not 504
            for r in self.queue.drain_pending():
                r.fail(RuntimeError("server drain timed out"))
            log.warning("drain: batcher did not finish inside %.0fs", timeout_s)
        # flush the artifacts through the normal path (atomic snapshot
        # write); the event stream stays open until close() so the final
        # run_finished record remains the stream's last
        try:
            self.obs.write_metrics()
        except Exception as e:  # noqa: BLE001 — telemetry never blocks a drain
            log.warning("drain: metrics flush failed: %s", e)
        self._drained.set()
        return drained

    def close(self, status: str = "ok") -> None:
        with self._drain_lock:
            cache, self._attached_cache = self._attached_cache, None
        if cache is not None:
            # detach OUR cache from the process-global hub: a later app in
            # this process without a cache dir must not inherit this app's
            # fault hook, which closes over this app's (now closed) obs.
            # Identity-checked: if someone attached a different cache
            # after us, it is theirs to manage. Detaching re-arms the
            # hub's one-shot $NM03_COMPILE_CACHE_DIR check, so an
            # env-requested cache (a process-wide request that must
            # survive one serving app's lifecycle) comes back — hook-free
            # — at the next get_hub().
            from nm03_capstone_project_tpu.compilehub import get_hub

            hub = get_hub()
            if hub.persistent_cache() is cache:
                hub.attach_cache(None)
        self.obs.close(status=status)

    # -- request plumbing (HTTP-free, directly testable) -------------------

    def _count_request(self, status: str) -> None:
        self.registry.counter(
            SERVING_REQUESTS_TOTAL,
            help="terminal serving request outcomes by status",
            status=status,
        ).inc()

    # -- the result tier (ISSUE 19, HTTP-free) -----------------------------

    def _on_result_evict(self, n: int) -> None:
        # fired from inside the store's lock — a counter bump only (the
        # bytes gauge is refreshed outside the lock, see
        # _publish_result_bytes)
        self.registry.counter(
            SERVING_RESULT_CACHE_EVICT_TOTAL,
            help="result-tier entries evicted by tier (LRU pressure, "
            "explicit evict, or a failed verify-on-read)",
            tier="replica",
        ).inc(n)

    def _publish_result_bytes(self) -> None:
        # called once from __init__ (before self.registry is aliased), so
        # reach through self.obs directly
        if self.result_store is not None:
            self.obs.registry.gauge(
                SERVING_RESULT_CACHE_BYTES,
                help="resident bytes in the replica result store",
            ).set(self.result_store.bytes)

    def result_version(self) -> str:
        """The program-identity half of every result key, pinned once.

        Resolved lazily (``compilehub.persist`` imports jax) under its
        own lock, then constant for the process's lifetime — versions
        cannot change under a running server, and a restart with a new
        algorithm mints a new value, which is the whole invalidation
        story.
        """
        with self._rv_lock:
            if self._rv_value is None:
                from nm03_capstone_project_tpu.compilehub.persist import (
                    result_version,
                )

                self._rv_value = result_version(self.cfg)
            return self._rv_value

    def result_digest(self, body: bytes, algo: str, params: dict):
        """ResultKey digest for one request body, or None (tier off)."""
        if self.result_store is None:
            return None
        return result_key(body, algo, params, self.result_version()).digest()

    def result_lookup(self, digest: str):
        """Replica-tier store lookup + hit/miss accounting."""
        entry = self.result_store.lookup(digest)
        self.registry.counter(
            SERVING_RESULT_CACHE_HIT_TOTAL if entry is not None
            else SERVING_RESULT_CACHE_MISS_TOTAL,
            help="result-tier lookups served from cache, by tier"
            if entry is not None
            else "result-tier lookups that fell through to compute, by tier",
            tier="replica",
        ).inc()
        return entry

    def result_fill(self, digest: str, payload: dict, algo: str, fields):
        """Store the cacheable subset of ``payload``; ('fill'|'miss', etag).

        'miss' is the honest ``X-Nm03-Cache`` value for computed-but-not-
        stored (an oversize payload): the work was done, nothing cached.
        Only input-derived fields are stored (never request ids, waits or
        lane numbers) so the entry's ETag is stable across evict/
        recompute cycles — the bit-identity contract the tests gate.
        """
        stored = {k: payload[k] for k in fields if k in payload}
        raw = json.dumps(stored, sort_keys=True).encode()
        entry, created = self.result_store.fill(digest, raw, algo)
        if entry is None:
            return "miss", None
        if created:
            self.registry.counter(
                SERVING_RESULT_CACHE_FILL_TOTAL,
                help="computed results stored into the tier, by tier",
                tier="replica",
            ).inc()
            self._publish_result_bytes()
        return "fill", entry.etag

    def _payload_from_entry(self, entry, trace_id, volume: bool = False):
        """A served-from-store response: stored fields + fresh identity.

        Execution-scoped fields are minted per response: batch_size 0 /
        lane None / z_shards 0 and device_seconds 0.0 are the honest
        values for work the device never saw.
        """
        payload = dict(json.loads(entry.payload.decode()))
        payload.update(
            request_id=uuid.uuid4().hex[:12],
            trace_id=trace_id,
            queue_wait_s=0.0,
            requeues=0,
            device_seconds=0.0,
            cached=True,
        )
        if volume:
            payload.update(z_shards=0, gang_wait_s=0.0)
        else:
            payload.update(
                batch_size=0, lane=None, degraded=self.executor.degraded
            )
        return payload

    def _account_cached_hit(
        self, trace_id, request_id, volume: bool, t_start: float
    ) -> None:
        """A hit is a served request: counted, traced, and charged ZERO
        device-seconds — the falling ``device_seconds/request`` mean on a
        repeat-heavy replay is the tier's provable win."""
        self.ledger.observe_request(0.0)
        extra = {"volume": True, "z_shards": 0} if volume else {}
        self.obs.events.emit(
            SERVE_TRACE_EVENT,
            trace_id=trace_id,
            request_id=request_id,
            lane=None,
            batch_size=0,
            queue_wait_s=0.0,
            probe=False,
            cached=True,
            spans=[],
            **extra,
        )
        if volume:
            self._count_volume_request("ok")
        else:
            self.registry.histogram(
                SERVING_REQUEST_SECONDS,
                help="end-to-end request latency (admission to payload "
                "built)",
                buckets=LATENCY_BUCKETS,
            ).observe(time.monotonic() - t_start)
            self._count_request("ok")

    def segment_cached(
        self,
        body: bytes,
        pixels: np.ndarray,
        render: bool = True,
        trace_id: Optional[str] = None,
        probe: bool = False,
        if_none_match: Optional[str] = None,
    ):
        """:meth:`segment` behind the result tier; (payload, state, etag).

        ``state`` None = tier off or probe traffic (plain compute path);
        'hit' with payload None = 304 Not Modified; 'fill' = computed and
        stored; 'miss' = computed, not stored. Probes bypass the tier both
        ways — a canary must exercise the real dispatch path, and its
        result must not warm the cache for real traffic.
        """
        params = {"render": bool(render)}
        if render:
            params["jpeg_quality"] = self.jpeg_quality
        digest = (
            None if probe else self.result_digest(body, "segment", params)
        )
        if digest is None:
            return (
                self.segment(
                    pixels, render=render, trace_id=trace_id, probe=probe
                ),
                None,
                None,
            )
        t_start = time.monotonic()
        entry = self.result_lookup(digest)
        if entry is not None:
            if etag_matches(if_none_match, entry.etag):
                self._account_cached_hit(
                    trace_id, uuid.uuid4().hex[:12], False, t_start
                )
                return None, "hit", entry.etag
            payload = self._payload_from_entry(entry, trace_id)
            self._account_cached_hit(
                trace_id, payload["request_id"], False, t_start
            )
            return payload, "hit", entry.etag
        payload = self.segment(
            pixels, render=render, trace_id=trace_id, probe=probe,
            digest=digest,
        )
        state, etag = self.result_fill(
            digest, payload, "segment", _CACHEABLE_SEGMENT_FIELDS
        )
        return payload, state, etag

    def segment_volume_cached(
        self,
        body: bytes,
        volume: np.ndarray,
        trace_id: Optional[str] = None,
        mhd: bool = False,
        mhd_compressed: bool = False,
        include_mask: bool = True,
        if_none_match: Optional[str] = None,
        idempotency_key: Optional[str] = None,
    ):
        """:meth:`segment_volume` behind the tier; (payload, state, etag).

        The idempotency contract (``X-Nm03-Idempotency-Key``): the key is
        an alias for the first content digest it arrived with, recorded
        in a map that OUTLIVES the in-flight window — a client retry
        after a fleet failover resolves the key to the original digest
        and either coalesces onto the still-running gang ('hit', the
        in-flight path inside segment_volume) or returns the stored
        result ('hit', the store path). A 32-plane gang program is never
        re-dispatched for a retry.
        """
        output = "mhd" if mhd else ("mask" if include_mask else "summary")
        params = {"output": output, "compressed": bool(mhd_compressed)}
        digest = self.result_digest(body, "segment-volume", params)
        if digest is None:
            return (
                self.segment_volume(
                    volume, trace_id=trace_id, mhd=mhd,
                    mhd_compressed=mhd_compressed, include_mask=include_mask,
                ),
                None,
                None,
            )
        alias = f"idem:{idempotency_key}" if idempotency_key else None
        lookup_digest = digest
        if alias is not None:
            aliased = self.volume_inflight.resolve(alias)
            if aliased is not None:
                lookup_digest = aliased
        t_start = time.monotonic()
        entry = self.result_lookup(lookup_digest)
        if entry is not None:
            if etag_matches(if_none_match, entry.etag):
                self._account_cached_hit(
                    trace_id, uuid.uuid4().hex[:12], True, t_start
                )
                return None, "hit", entry.etag
            payload = self._payload_from_entry(entry, trace_id, volume=True)
            self._account_cached_hit(
                trace_id, payload["request_id"], True, t_start
            )
            return payload, "hit", entry.etag
        payload = self.segment_volume(
            volume, trace_id=trace_id, mhd=mhd,
            mhd_compressed=mhd_compressed, include_mask=include_mask,
            digest=digest, idem_alias=alias,
        )
        if payload.pop("_coalesced", False):
            # rode an in-flight gang (counted tier=inflight inside): the
            # leader's own fill covers the store, nothing for us to store
            return payload, "hit", None
        state, etag = self.result_fill(
            digest, payload, "segment-volume", _CACHEABLE_VOLUME_FIELDS
        )
        return payload, state, etag

    def _join_volume_leader(
        self, leader, trace_id, include_mask, mhd, mhd_compressed
    ) -> dict:
        """Ride an identical in-flight volume: wait on ITS gang, answer
        from ITS mask — the retry path that never dispatches a second
        mesh-wide program. The payload is built from the same mask array
        the leader returns, so the two responses are bit-identical."""
        from nm03_capstone_project_tpu.serving.volumes import GangUnavailable

        self.registry.counter(
            SERVING_RESULT_CACHE_HIT_TOTAL,
            help="result-tier lookups served from cache, by tier",
            tier="inflight",
        ).inc()
        self.registry.gauge(
            SERVING_INFLIGHT, help="admitted requests not yet responded"
        ).inc()
        try:
            if not leader.wait(self.volume_timeout_s):
                self._count_volume_request("timeout")
                raise TimeoutError(
                    f"coalesced volume request (leader {leader.request_id}) "
                    f"timed out after {self.volume_timeout_s:.0f}s"
                )
            if leader.error is not None:
                # the rider shares the leader's fate — recomputing here
                # would defeat the whole point of coalescing
                self._count_volume_request(
                    "shed" if isinstance(leader.error, GangUnavailable)
                    else "error"
                )
                raise leader.error
        finally:
            self.registry.gauge(
                SERVING_INFLIGHT, help="admitted requests not yet responded"
            ).dec()
        mask = np.ascontiguousarray(leader.mask)
        payload = {
            "request_id": uuid.uuid4().hex[:12],
            "trace_id": trace_id,
            "shape": [int(s) for s in mask.shape],
            "z_shards": leader.z_shards,
            "gang_wait_s": 0.0,
            "queue_wait_s": 0.0,
            "requeues": leader.requeues,
            "grow_converged": leader.converged,
            "mask_voxels": int(np.count_nonzero(mask)),
            "mask_sha256": hashlib.sha256(mask.tobytes()).hexdigest(),
            "cached": True,
            "_coalesced": True,
        }
        if include_mask:
            payload["mask_b64"] = base64.b64encode(mask.tobytes()).decode(
                "ascii"
            )
        if mhd:
            payload.update(self._mhd_payload(mask, mhd_compressed))
        self.ledger.observe_request(0.0)
        self._count_volume_request("ok")
        return payload

    def decode_request(self, body: bytes, content_type: str) -> np.ndarray:
        """Body -> float32 (h, w) raw-intensity slice, or RequestRejected.

        ``application/dicom`` bodies go through the REAL parser
        (``dicomlite.read_dicom_bytes``); anything else is treated as a raw
        little-endian float32 array described by X-Nm03-Height/Width (the
        loadgen's cheap path). Decode runs on the handler thread so a
        malformed body is a 400 before any batch slot is spent on it.
        """
        ct = (content_type or "").split(";")[0].strip().lower()
        if ct == "application/dicom":
            from nm03_capstone_project_tpu.data.dicomlite import read_dicom_bytes

            try:
                return np.asarray(read_dicom_bytes(body).pixels, np.float32)
            except Exception as e:  # noqa: BLE001 — parser rejection -> 400
                raise RequestRejected(400, f"DICOM parse failed: {e}") from e
        raise RequestRejected(
            415,
            f"unsupported content type {ct!r} (want application/dicom or "
            "application/octet-stream with X-Nm03-Height/X-Nm03-Width)",
        )

    def decode_raw(self, body: bytes, height: int, width: int) -> np.ndarray:
        expected = height * width * 4
        if len(body) != expected:
            raise RequestRejected(
                400,
                f"raw body is {len(body)} bytes; {height}x{width} float32 "
                f"needs {expected}",
            )
        return (
            np.frombuffer(body, dtype="<f4").reshape(height, width).astype(np.float32)
        )

    def guard_pixels(self, pixels: np.ndarray) -> Tuple[int, int]:
        h, w = int(pixels.shape[0]), int(pixels.shape[1])
        if h < self.cfg.min_dim or w < self.cfg.min_dim:
            raise RequestRejected(
                400,
                f"image {w}x{h} below the minimum dimension {self.cfg.min_dim}",
            )
        if h > self.cfg.canvas or w > self.cfg.canvas:
            raise RequestRejected(
                413,
                f"image {w}x{h} exceeds the serving canvas {self.cfg.canvas} "
                "(start the server with a larger --canvas)",
            )
        return h, w

    def submit(
        self, pixels: np.ndarray, trace_id: Optional[str] = None,
        probe: bool = False, digest: Optional[str] = None,
    ) -> ServeRequest:
        """Admit one decoded slice; QueueFull/QueueClosed shed at the door.

        ``trace_id`` is the request-scoped trace identity (an honored
        inbound ``X-Nm03-Request-Id``, or minted here): the request's
        :class:`TraceContext` carries it through every hop and it is
        echoed back on the response. ``probe`` marks a fleet probation
        canary (``X-Nm03-Probe``): served and traced like any request,
        excluded from the request metrics (ISSUE 14).
        """
        h, w = self.guard_pixels(pixels)
        req = ServeRequest(
            request_id=uuid.uuid4().hex[:12],
            pixels=pixels,
            dims=(h, w),
            trace=TraceContext(trace_id or new_trace_id()),
            probe=bool(probe),
            digest=digest,
        )
        self.queue.put(req)  # raises QueueFull / QueueClosed
        self.registry.gauge(
            SERVING_INFLIGHT, help="admitted requests not yet responded"
        ).inc()
        return req

    def segment(
        self,
        pixels: np.ndarray,
        render: bool = True,
        trace_id: Optional[str] = None,
        probe: bool = False,
        digest: Optional[str] = None,
    ) -> dict:
        """The full request path minus HTTP: admit, wait, build the payload.

        Raises RequestRejected (guards), QueueFull/QueueClosed (shed), or
        TimeoutError; any executor error raises as-is. Always settles the
        inflight gauge and the status counter.

        A ``probe`` request (a fleet probation canary, ISSUE 14) takes
        the same path but every terminal status lands under
        ``status="probe"`` and the latency histogram is never observed —
        the canary cadence is excluded from the series the SLO layer
        reads, while the request stays fully traced (``serve_trace``
        carries ``probe: true``).
        """

        def status_class(s: str) -> str:
            return "probe" if probe else s

        t_start = time.monotonic()
        try:
            req = self.submit(
                pixels, trace_id=trace_id, probe=probe, digest=digest
            )
        except (QueueFull, QueueClosed):
            if not probe:
                self.registry.counter(
                    SERVING_SHED_TOTAL,
                    help="admissions refused by backpressure (full or "
                    "draining)",
                ).inc()
            self._count_request(status_class("shed"))
            raise
        except RequestRejected:
            self._count_request(status_class("invalid"))  # admission guard
            raise
        try:
            if not req.wait(self.request_timeout_s):
                self._count_request(status_class("timeout"))
                raise TimeoutError(
                    f"request {req.request_id} timed out after "
                    f"{self.request_timeout_s:.0f}s"
                )
            if req.error is not None:
                self._count_request(status_class("error"))
                raise req.error
        finally:
            self.registry.gauge(
                SERVING_INFLIGHT, help="admitted requests not yet responded"
            ).dec()
        payload = {
            "request_id": req.request_id,
            "trace_id": req.trace_id,
            "shape": [req.dims[0], req.dims[1]],
            "grow_converged": req.converged,
            "batch_size": req.batch_size,
            "queue_wait_s": round(req.queue_wait_s, 6),
            "lane": req.lane,
            # >0: the rider's chunk outlived a lane quarantine (re-dispatch)
            "requeues": req.requeues,
            # what THIS request cost the device (ISSUE 16): its prorated
            # row share of the chunk's busy seconds — 0.0 when the chunk
            # was served by the CPU fallback (it ran on no device lane)
            "device_seconds": round(req.device_seconds, 9),
            "degraded": self.executor.degraded,
            "mask_pixels": int(np.count_nonzero(req.mask)),
        }
        if self.result_store is not None and not probe:
            # the mask's content identity rides the payload when the
            # result tier is on: it is what the bit-identity gates (bench
            # result_cache leg, the subprocess drill) compare — a cached
            # hit must reproduce it exactly
            payload["mask_sha256"] = hashlib.sha256(
                np.ascontiguousarray(req.mask).tobytes()
            ).hexdigest()
            payload["cached"] = False
        if render:
            from nm03_capstone_project_tpu.render.export import encode_jpeg_bytes
            from nm03_capstone_project_tpu.render.host_render import host_render_pair

            dims = np.asarray(req.dims, np.int32)
            with req.trace.span("encode"):
                gray, seg = host_render_pair(pixels, req.mask, dims, self.cfg)
                payload["original_jpeg_b64"] = base64.b64encode(
                    encode_jpeg_bytes(gray, self.jpeg_quality)
                ).decode("ascii")
                payload["processed_jpeg_b64"] = base64.b64encode(
                    encode_jpeg_bytes(seg, self.jpeg_quality)
                ).decode("ascii")
        # one serve_trace event per completed request: the span tree the
        # nm03-trace exporter turns into a Perfetto timeline (probes stay
        # traced — labeled, never dropped)
        self.obs.events.emit(
            SERVE_TRACE_EVENT,
            trace_id=req.trace_id,
            request_id=req.request_id,
            lane=req.lane,
            batch_size=req.batch_size,
            queue_wait_s=round(req.queue_wait_s, 6),
            probe=probe,
            spans=req.trace.snapshot(),
        )
        if not probe:
            self.registry.histogram(
                SERVING_REQUEST_SECONDS,
                help="end-to-end request latency (admission to payload "
                "built)",
                buckets=LATENCY_BUCKETS,
            ).observe(time.monotonic() - t_start)
        self._count_request(status_class("ok"))
        self.registry.gauge(
            SERVING_DEGRADED, help="1 = one-way CPU degradation tripped"
        ).set(1 if self.executor.degraded else 0)
        return payload

    # -- whole-volume request plumbing (ISSUE 15, HTTP-free) ---------------

    def _count_volume_request(self, status: str) -> None:
        from nm03_capstone_project_tpu.serving.metrics import (
            SERVING_VOLUME_REQUESTS_TOTAL,
        )

        self.registry.counter(
            SERVING_VOLUME_REQUESTS_TOTAL,
            help="terminal whole-volume request outcomes by status "
            "(POST /v1/segment-volume)",
            status=status,
        ).inc()

    def decode_volume_raw(
        self, body: bytes, depth: int, height: int, width: int
    ) -> np.ndarray:
        """Raw stacked study: little-endian float32 (depth, height, width)."""
        if depth < 1:
            raise RequestRejected(400, f"depth must be >= 1, got {depth}")
        expected = depth * height * width * 4
        if len(body) != expected:
            raise RequestRejected(
                400,
                f"raw volume body is {len(body)} bytes; "
                f"{depth}x{height}x{width} float32 needs {expected}",
            )
        return (
            np.frombuffer(body, dtype="<f4")
            .reshape(depth, height, width)
            .astype(np.float32)
        )

    def decode_volume_dicom(self, body: bytes, content_type: str) -> np.ndarray:
        """DICOM study body -> (depth, h, w) float32 stack.

        ``application/dicom`` is ONE Part-10 file whose frames are the
        z-planes (multi-frame series — the format
        ``data.dicomlite.read_dicom_frames`` already decodes for the
        drivers); ``application/x-nm03-dicom-parts`` is the concatenated
        form: each part is a 4-byte little-endian length prefix followed
        by one Part-10 file (explicit framing — scanning raw
        concatenation for the DICM magic could split inside pixel data).
        Every plane must decode and share one in-plane size: a partial
        volume is never silently served.
        """
        import tempfile

        from nm03_capstone_project_tpu.data.dicomlite import (
            read_dicom_bytes,
            read_dicom_frames,
        )

        ct = (content_type or "").split(";")[0].strip().lower()
        planes: list = []
        try:
            if ct == "application/x-nm03-dicom-parts":
                off = 0
                while off < len(body):
                    if off + 4 > len(body):
                        raise ValueError("truncated part length prefix")
                    n = int.from_bytes(body[off:off + 4], "little")
                    off += 4
                    if n <= 0 or off + n > len(body):
                        raise ValueError(f"part length {n} overruns the body")
                    planes.append(
                        np.asarray(
                            read_dicom_bytes(body[off:off + n]).pixels,
                            np.float32,
                        )
                    )
                    off += n
                if not planes:
                    raise ValueError("no DICOM parts in body")
            else:  # application/dicom: one (possibly multi-frame) file
                with tempfile.NamedTemporaryFile(suffix=".dcm") as f:
                    f.write(body)
                    f.flush()
                    slices = read_dicom_frames(f.name, strict=True)
                planes = [np.asarray(s.pixels, np.float32) for s in slices]
        except RequestRejected:
            raise
        except Exception as e:  # noqa: BLE001 — parser rejection -> 400
            raise RequestRejected(400, f"DICOM study parse failed: {e}") from e
        if not planes:
            # a parseable file with zero frames is still an empty study —
            # a 400, never an unhandled IndexError below
            raise RequestRejected(400, "DICOM study contains no image planes")
        hw = planes[0].shape
        if any(p.shape != hw for p in planes):
            raise RequestRejected(
                400,
                "study planes disagree on in-plane size "
                f"({sorted({p.shape for p in planes})})",
            )
        return np.stack(planes)

    def guard_volume(self, volume: np.ndarray) -> Tuple[int, int, int]:
        """Admission guards for one decoded study; (depth, h, w)."""
        if self.volumes is None:
            raise RequestRejected(
                404,
                "volume serving is not enabled on this replica "
                "(start nm03-serve with --volume-serving)",
                status_label="invalid",
            )
        d = int(volume.shape[0])
        h, w = self.guard_pixels(volume[0])
        if d > self.volumes.max_depth:
            raise RequestRejected(
                413,
                f"study of {d} planes exceeds the largest volume depth "
                f"bucket {self.volumes.max_depth} (start the server with "
                "deeper --volume-depth-buckets)",
            )
        return d, h, w

    def segment_volume(
        self,
        volume: np.ndarray,
        trace_id: Optional[str] = None,
        mhd: bool = False,
        mhd_compressed: bool = False,
        include_mask: bool = True,
        digest: Optional[str] = None,
        idem_alias: Optional[str] = None,
    ) -> dict:
        """The whole-volume request path minus HTTP (ISSUE 15).

        Admit to the gang's own queue, wait for the mesh-wide dispatch,
        build the payload carrying the full mask volume (base64 raw
        uint8, C-order) plus — with ``mhd`` — the MetaImage pair the
        driver's ``--export-mhd`` writes. Raises RequestRejected
        (guards), QueueFull/QueueClosed (volume-queue shed),
        GangUnavailable (no servable mesh — the honest shed), or
        TimeoutError. Counts every terminal outcome under
        ``serving_volume_requests_total`` and publishes the gang-wait /
        z-shard gauges.
        """
        from nm03_capstone_project_tpu.serving.metrics import (
            SERVING_VOLUME_GANG_WAIT_SECONDS,
            SERVING_VOLUME_ZSHARDS,
        )
        from nm03_capstone_project_tpu.serving.volumes import GangUnavailable

        try:
            d, h, w = self.guard_volume(volume)
        except RequestRejected:
            self._count_volume_request("invalid")  # admission guard
            raise
        if digest is not None and self.volume_inflight is not None:
            # the in-flight window: an identical volume already riding a
            # gang answers this request too — join it, never dispatch
            leader = self.volume_inflight.claim(digest)
            if leader is not None:
                return self._join_volume_leader(
                    leader, trace_id, include_mask, mhd, mhd_compressed
                )
        try:
            req = self.volumes.submit(volume, (h, w), trace_id=trace_id)
        except (QueueFull, QueueClosed):
            self.registry.counter(
                SERVING_SHED_TOTAL,
                help="admissions refused by backpressure (full or "
                "draining)",
            ).inc()
            self._count_volume_request("shed")
            raise
        except ValueError as e:  # depth guard inside the gang
            self._count_volume_request("invalid")
            raise RequestRejected(413, str(e)) from e
        registered = False
        if digest is not None and self.volume_inflight is not None:
            # first-wins leadership: a racing duplicate that registered
            # between our claim and here keeps the slot, and our already-
            # admitted request computes normally (the fill is idempotent
            # on digest — both produce the same bytes)
            owner = self.volume_inflight.register(
                digest, req, alias=idem_alias
            )
            registered = owner is req
        self.registry.gauge(
            SERVING_INFLIGHT, help="admitted requests not yet responded"
        ).inc()
        try:
            if not req.wait(self.volume_timeout_s):
                self._count_volume_request("timeout")
                raise TimeoutError(
                    f"volume request {req.request_id} timed out after "
                    f"{self.volume_timeout_s:.0f}s"
                )
            if req.error is not None:
                self._count_volume_request(
                    "shed" if isinstance(req.error, GangUnavailable)
                    else "error"
                )
                raise req.error
        finally:
            if registered:
                # release only after done is set: any rider that claimed
                # us meanwhile finds the event already fired and proceeds
                self.volume_inflight.release(digest)
            self.registry.gauge(
                SERVING_INFLIGHT, help="admitted requests not yet responded"
            ).dec()
        payload = {
            "request_id": req.request_id,
            "trace_id": req.trace_id,
            "shape": [d, h, w],
            "z_shards": req.z_shards,
            "gang_wait_s": round(req.gang_wait_s, 6),
            "queue_wait_s": round(req.queue_wait_s, 6),
            # >0: the gang re-meshed onto surviving lanes mid-volume
            "requeues": req.requeues,
            "grow_converged": req.converged,
            "mask_voxels": int(np.count_nonzero(req.mask)),
        }
        if self.result_store is not None:
            payload["mask_sha256"] = hashlib.sha256(
                np.ascontiguousarray(req.mask).tobytes()
            ).hexdigest()
            payload["cached"] = False
        if include_mask:
            payload["mask_b64"] = base64.b64encode(
                np.ascontiguousarray(req.mask).tobytes()
            ).decode("ascii")
        if mhd:
            payload.update(self._mhd_payload(req.mask, mhd_compressed))
        self.obs.events.emit(
            SERVE_TRACE_EVENT,
            trace_id=req.trace_id,
            request_id=req.request_id,
            lane=None,
            batch_size=1,
            queue_wait_s=round(req.queue_wait_s, 6),
            probe=False,
            volume=True,
            z_shards=req.z_shards,
            spans=req.trace.snapshot(),
        )
        self.registry.gauge(
            SERVING_VOLUME_GANG_WAIT_SECONDS,
            help="gang-wait of the last served volume: how long it waited "
            "for the slice batcher to park the lanes",
        ).set(round(req.gang_wait_s, 6))
        self.registry.gauge(
            SERVING_VOLUME_ZSHARDS,
            help="z-shards the last served volume spanned (the gang's "
            "mesh width; full fleet width from warmup)",
        ).set(req.z_shards)
        self._count_volume_request("ok")
        return payload

    def _mhd_payload(self, mask: np.ndarray, compressed: bool) -> dict:
        """The driver's ``--export-mhd`` artifact pair, base64 over the wire."""
        import tempfile
        from pathlib import Path

        from nm03_capstone_project_tpu.data.imageio import write_metaimage

        with tempfile.TemporaryDirectory() as td:
            write_metaimage(mask, Path(td) / "mask.mhd", compressed=compressed)
            header = (Path(td) / "mask.mhd").read_bytes()
            data_name = "mask.zraw" if compressed else "mask.raw"
            data = (Path(td) / data_name).read_bytes()
        return {
            "mhd_header_b64": base64.b64encode(header).decode("ascii"),
            "mhd_data_b64": base64.b64encode(data).decode("ascii"),
            "mhd_data_file": data_name,
        }


# -- the HTTP layer ---------------------------------------------------------


def make_handler(app: ServingApp):
    class Handler(BaseHTTPRequestHandler):
        server_version = "nm03-serve/1.0"
        protocol_version = "HTTP/1.1"

        # route per-request chatter to the package logger at DEBUG, not
        # stderr — a load test must not serialize on console writes
        def log_message(self, fmt, *args):  # noqa: A003
            log.debug("%s %s", self.address_string(), fmt % args)

        def _reply(self, status: int, body: dict, headers=()):
            data = json.dumps(body).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _reply_not_modified(self, headers=()):
            # 304 carries no body by RFC 7232 — Content-Length 0, headers
            # only (the ETag rides along so the client can re-validate)
            self.send_response(304)
            self.send_header("Content-Length", "0")
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()

        def _reply_text(self, status: int, text: str, content_type: str):
            data = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            path = urlsplit(self.path).path
            if path == "/healthz":
                self._reply(
                    200,
                    {"status": "alive",
                     "uptime_s": round(time.monotonic() - app._t0, 3)},
                )
            elif path == "/readyz":
                st = app.status()
                self._reply(200 if st["ready"] else 503, st)
            elif path == "/metrics":
                app.saturation.publish()  # pull-refresh the sliding window
                app.ledger.publish()  # pull-refresh the cost/pie gauges
                if app.slo is not None:
                    app.slo.publish()  # pull-refresh the burn-rate windows
                self._reply_text(
                    200, app.registry.to_prometheus(), "text/plain; version=0.0.4"
                )
            elif path == "/metrics.json":
                app.saturation.publish()  # pull-refresh the sliding window
                app.ledger.publish()  # pull-refresh the cost/pie gauges
                if app.slo is not None:
                    app.slo.publish()  # pull-refresh the burn-rate windows
                self._reply_text(
                    200,
                    json.dumps(app.obs.metrics_snapshot(), indent=1),
                    "application/json",
                )
            elif path == "/debug/flightrec":
                # remote debug pull (ISSUE 14): the PR-7 flight rings over
                # HTTP, so a wedged fleet can be post-mortemed without
                # SIGUSR2 shell access (`nm03-fleet flightrec` fans this
                # across every replica)
                from nm03_capstone_project_tpu.obs import flightrec

                snap = flightrec.get_recorder().snapshot(reason="debug_pull")
                self._reply_text(
                    200, json.dumps(snap, default=str), "application/json"
                )
            elif path == "/debug/profile":
                # remote debug pull (ISSUE 14): an on-demand jax.profiler
                # capture (?ms=N, 400 outside [10, 10000]), returned as a
                # zipped trace directory — the TensorBoard/Perfetto
                # post-mortem without shell access
                from nm03_capstone_project_tpu.utils.profiling import (
                    ProfileBusy,
                    capture_profile,
                )

                query = parse_qs(urlsplit(self.path).query)
                try:
                    ms = int(query.get("ms", ["500"])[0])
                except ValueError:
                    self._reply(400, {"error": "ms must be an integer"})
                    return
                try:
                    result = capture_profile(ms)
                except ProfileBusy as e:
                    self._reply(
                        409, {"error": str(e)},
                        headers=[("Retry-After", "1")],
                    )
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — profiler unavailable
                    log.warning("debug profile capture failed: %s", e)
                    self._reply(
                        500,
                        {"error": str(e), "error_class": type(e).__name__},
                    )
                else:
                    self._reply(200, result)
            elif path == "/debug/result-cache":
                # the result tier's admin surface (ISSUE 19): stats +
                # entries hot-to-cold, the rows `nm03-cache result ls`
                # renders. {"enabled": false} when the tier is off — an
                # honest null, not an empty store.
                if app.result_store is None:
                    self._reply(200, {"enabled": False})
                else:
                    self._reply(
                        200,
                        {
                            **app.result_store.stats(),
                            "program_version": (
                                app.result_version()
                                if app.executor.warm else None
                            ),
                            "inflight": app.volume_inflight.stats(),
                            "ls": app.result_store.ls(),
                        },
                    )
            else:
                self._reply(404, {"error": f"unknown path {path}"})

        def do_POST(self):  # noqa: N802
            split = urlsplit(self.path)
            if split.path == "/v1/segment-volume":
                self._post_volume(split)
                return
            if split.path == "/debug/result-cache/evict":
                # admin evict (?digest=D for one entry, none for all);
                # the invalidation-triage escape hatch, though the key
                # contract makes routine invalidation automatic
                if app.result_store is None:
                    self._reply(404, {"error": "result tier not enabled"})
                    return
                query = parse_qs(split.query)
                digest = query.get("digest", [None])[0]
                dropped = app.result_store.evict(digest)
                app._publish_result_bytes()
                self._reply(200, {"evicted": dropped})
                return
            if split.path != "/v1/segment":
                self._reply(404, {"error": f"unknown path {split.path}"})
                return
            query = parse_qs(split.query)
            render = query.get("output", ["jpeg"])[0] != "mask"
            # request-scoped trace identity: honor a sane inbound
            # X-Nm03-Request-Id, mint one otherwise; echoed on EVERY
            # response (errors included) so clients can correlate
            trace_id = sanitize_trace_id(
                self.headers.get("X-Nm03-Request-Id")
            ) or new_trace_id()
            echo = [("X-Nm03-Request-Id", trace_id)]
            # a fleet probation canary (ISSUE 14): served and traced like
            # any request, excluded from request metrics/SLO accounting
            is_probe = self.headers.get("X-Nm03-Probe") == "1"
            # decode phase: every rejection here is counted "invalid" ONCE
            # (segment() owns counting from admission onward)
            try:
                length = int(self.headers.get("Content-Length", 0))
                cap = app.cfg.canvas * app.cfg.canvas * 4 + 65536
                if length <= 0:
                    raise RequestRejected(400, "empty body")
                if length > cap:
                    raise RequestRejected(
                        413, f"body of {length} bytes exceeds the {cap} cap"
                    )
                body = self.rfile.read(length)
                h_hdr = self.headers.get("X-Nm03-Height")
                w_hdr = self.headers.get("X-Nm03-Width")
                if h_hdr is not None and w_hdr is not None:
                    pixels = app.decode_raw(body, int(h_hdr), int(w_hdr))
                else:
                    pixels = app.decode_request(
                        body, self.headers.get("Content-Type", "")
                    )
            except RequestRejected as e:
                app._count_request("probe" if is_probe else "invalid")
                self._reply(e.http_status, {"error": str(e)}, headers=echo)
                return
            except (ValueError, OverflowError) as e:  # bad int headers etc.
                app._count_request("probe" if is_probe else "invalid")
                self._reply(400, {"error": str(e)}, headers=echo)
                return
            try:
                payload, cache_state, etag = app.segment_cached(
                    body, pixels, render=render, trace_id=trace_id,
                    probe=is_probe,
                    if_none_match=self.headers.get("If-None-Match"),
                )
            except RequestRejected as e:  # guard failures (counted inside)
                self._reply(e.http_status, {"error": str(e)}, headers=echo)
            except (QueueFull, QueueClosed) as e:
                self._reply(
                    503,
                    {"error": str(e), "draining": app.draining},
                    headers=[("Retry-After", str(RETRY_AFTER_S)), *echo],
                )
            except TimeoutError as e:
                self._reply(504, {"error": str(e)}, headers=echo)
            except Exception as e:  # noqa: BLE001 — per-request containment
                log.warning("request failed: %s", e)
                self._reply(
                    500,
                    {"error": str(e), "error_class": type(e).__name__},
                    headers=echo,
                )
            else:
                cache_headers = []
                if cache_state is not None:
                    cache_headers.append(("X-Nm03-Cache", cache_state))
                if etag is not None:
                    cache_headers.append(("ETag", etag))
                if payload is None:  # If-None-Match matched: 304, no body
                    self._reply_not_modified(headers=[*cache_headers, *echo])
                    return
                # the echoed trace id plus the per-request attribution
                # headers nm03-loadgen records (queue wait / serving lane)
                self._reply(
                    200,
                    payload,
                    headers=[
                        ("X-Nm03-Batch-Size", str(payload["batch_size"])),
                        ("X-Nm03-Request-Id", payload["trace_id"]),
                        ("X-Nm03-Lane", str(payload["lane"])),
                        (
                            "X-Nm03-Queue-Wait-Ms",
                            f"{payload['queue_wait_s'] * 1e3:.3f}",
                        ),
                        *cache_headers,
                    ],
                )

        def _post_volume(self, split):
            """``POST /v1/segment-volume`` (ISSUE 15): one whole study in,
            the full mask volume out — the gang-lane request path."""
            query = parse_qs(split.query)
            output = query.get("output", ["mask"])[0]
            trace_id = sanitize_trace_id(
                self.headers.get("X-Nm03-Request-Id")
            ) or new_trace_id()
            echo = [("X-Nm03-Request-Id", trace_id)]
            try:
                length = int(self.headers.get("Content-Length", 0))
                max_depth = (
                    app.volumes.max_depth if app.volumes is not None else 1
                )
                cap = max_depth * app.cfg.canvas * app.cfg.canvas * 4 + 65536
                if length <= 0:
                    raise RequestRejected(400, "empty body")
                if length > cap:
                    raise RequestRejected(
                        413,
                        f"body of {length} bytes exceeds the {cap} volume cap",
                    )
                body = self.rfile.read(length)
                d_hdr = self.headers.get("X-Nm03-Depth")
                h_hdr = self.headers.get("X-Nm03-Height")
                w_hdr = self.headers.get("X-Nm03-Width")
                if d_hdr is not None and h_hdr is not None and w_hdr is not None:
                    volume = app.decode_volume_raw(
                        body, int(d_hdr), int(h_hdr), int(w_hdr)
                    )
                else:
                    volume = app.decode_volume_dicom(
                        body, self.headers.get("Content-Type", "")
                    )
            except RequestRejected as e:
                app._count_volume_request("invalid")
                self._reply(e.http_status, {"error": str(e)}, headers=echo)
                return
            except (ValueError, OverflowError) as e:  # bad int headers etc.
                app._count_volume_request("invalid")
                self._reply(400, {"error": str(e)}, headers=echo)
                return
            from nm03_capstone_project_tpu.serving.volumes import (
                GangUnavailable,
            )

            try:
                payload, cache_state, etag = app.segment_volume_cached(
                    body,
                    volume,
                    trace_id=trace_id,
                    mhd=output == "mhd",
                    mhd_compressed=query.get("compressed", ["0"])[0] == "1",
                    include_mask=output != "summary",
                    if_none_match=self.headers.get("If-None-Match"),
                    idempotency_key=self.headers.get(
                        "X-Nm03-Idempotency-Key"
                    ),
                )
            except RequestRejected as e:  # guards (counted inside)
                self._reply(e.http_status, {"error": str(e)}, headers=echo)
            except (QueueFull, QueueClosed, GangUnavailable) as e:
                # volume-queue backpressure AND the gang's honest no-mesh
                # shed: the client retries, the mask is never guessed
                self._reply(
                    503,
                    {"error": str(e), "draining": app.draining},
                    headers=[("Retry-After", str(RETRY_AFTER_S)), *echo],
                )
            except TimeoutError as e:
                self._reply(504, {"error": str(e)}, headers=echo)
            except Exception as e:  # noqa: BLE001 — per-request containment
                log.warning("volume request failed: %s", e)
                self._reply(
                    500,
                    {"error": str(e), "error_class": type(e).__name__},
                    headers=echo,
                )
            else:
                cache_headers = []
                if cache_state is not None:
                    cache_headers.append(("X-Nm03-Cache", cache_state))
                if etag is not None:
                    cache_headers.append(("ETag", etag))
                if payload is None:  # If-None-Match matched: 304, no body
                    self._reply_not_modified(headers=[*cache_headers, *echo])
                    return
                self._reply(
                    200,
                    payload,
                    headers=[
                        ("X-Nm03-Request-Id", payload["trace_id"]),
                        ("X-Nm03-Z-Shards", str(payload["z_shards"])),
                        (
                            "X-Nm03-Gang-Wait-Ms",
                            f"{payload['gang_wait_s'] * 1e3:.3f}",
                        ),
                        *cache_headers,
                    ],
                )

    return Handler


def make_http_server(
    app: ServingApp, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral); ``.server_address`` carries the real port."""
    httpd = ThreadingHTTPServer((host, port), make_handler(app))
    httpd.daemon_threads = True
    return httpd


def serve_in_thread(app: ServingApp, host: str = "127.0.0.1", port: int = 0):
    """Start + warm a server on a daemon thread; (httpd, thread, port).

    The loadgen's self-serve mode and the loopback tests use this; the CLI
    path (:func:`main`) serves on the main thread instead.
    """
    httpd = make_http_server(app, host, port)
    app.start()
    t = threading.Thread(
        target=httpd.serve_forever, name="nm03-serve-http", daemon=True
    )
    t.start()
    return httpd, t, httpd.server_address[1]


# -- CLI ---------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    from nm03_capstone_project_tpu.cli import common

    p = argparse.ArgumentParser(
        prog="nm03-serve", description=__doc__.strip().splitlines()[0]
    )
    g = p.add_argument_group("serving", "online service knobs (docs/OPERATIONS.md)")
    g.add_argument("--host", default="127.0.0.1", help="bind address")
    g.add_argument(
        "--port", type=int, default=8077, help="bind port (0 = ephemeral)"
    )
    g.add_argument(
        "--port-file",
        default=None,
        metavar="PATH",
        help="write the bound port here once listening (ephemeral-port "
        "orchestration; written atomically)",
    )
    g.add_argument(
        "--queue-capacity",
        type=int,
        default=64,
        help="bounded admission queue; past this, requests shed with 503 + "
        "Retry-After instead of waiting",
    )
    g.add_argument(
        "--max-wait-ms",
        type=float,
        default=10.0,
        help="dynamic-batching window: how long the first request of a "
        "batch waits for riders (the latency/throughput knob)",
    )
    g.add_argument(
        "--buckets",
        default=",".join(str(b) for b in DEFAULT_BUCKETS),
        help="comma list of warm batch-size buckets (each is one compiled "
        "executable; a coalesced batch pads to the smallest that fits)",
    )
    g.add_argument(
        "--lanes",
        type=int,
        default=0,
        metavar="N",
        help="replica lanes (chips) this process serves across; each lane "
        "holds its own warm per-bucket executables pinned to one device "
        "and the batcher fans coalesced batches out over them "
        "(0 = every local device; docs/OPERATIONS.md multi-chip runbook)",
    )
    g.add_argument(
        "--request-timeout-s",
        type=float,
        default=60.0,
        help="per-request wall budget from admission to response",
    )
    g.add_argument(
        "--lane-probe-interval-s",
        type=float,
        default=None,
        metavar="S",
        help="probation probe cadence: how often quarantined lanes get a "
        "supervised canary re-execution off the request path (default 5s; "
        "the reinstatement-latency/probe-load knob — docs/OPERATIONS.md "
        "quarantine triage)",
    )
    g.add_argument(
        "--compile-cache-dir",
        default=None,
        metavar="DIR",
        help="persistent AOT executable cache: warmup serializes every "
        "per-lane compiled executable here and a restart against the same "
        "dir deserializes instead of compiling — /readyz in milliseconds, "
        "not compile-minutes (default: $NM03_COMPILE_CACHE_DIR; unset = "
        "compile every start; docs/OPERATIONS.md compile-cache runbook, "
        "nm03-cache for ls/verify/gc)",
    )
    g.add_argument(
        "--result-cache-bytes",
        default="0",
        metavar="BYTES",
        help="content-addressed result tier budget (ISSUE 19): completed "
        "segment/segment-volume responses are stored under their "
        "(input-digest, algo, params, program-version) key and repeats "
        "are served from memory — LRU by bytes, verify-on-read, "
        "invalidated by construction when the program version changes. "
        "Accepts k/m/g suffixes ('512m'); 0 disables the tier "
        "(docs/OPERATIONS.md 'Running the result tier')",
    )
    g.add_argument(
        "--jpeg-quality", type=int, default=90, help="JPEG encoder quality"
    )
    g.add_argument(
        "--volume-serving",
        action="store_true",
        help="serve POST /v1/segment-volume (ISSUE 15): whole studies in "
        "one request through a gang lane spanning every healthy lane's "
        "chip — warmup additionally compiles one z-sharded mesh executable "
        "per depth bucket (persisted by --compile-cache-dir); "
        "docs/OPERATIONS.md 'Serving whole studies'",
    )
    g.add_argument(
        "--volume-depth-buckets",
        default=None,
        metavar="D1,D2,...",
        help="comma list of warm volume depth buckets (each is one "
        "AOT-compiled mesh executable; a study pads to the smallest that "
        "fits; default 8,16,32). The largest bucket is the served depth "
        "cap",
    )
    g.add_argument(
        "--volume-queue-capacity",
        type=int,
        default=4,
        help="bounded volume admission queue — separate from the slice "
        "queue by design, so bulk volumes shed on their own capacity and "
        "never occupy slice-admission slots",
    )
    g.add_argument(
        "--volume-timeout-s",
        type=float,
        default=300.0,
        help="per-volume wall budget from admission to response (a "
        "mesh-wide study is minutes of work where a slice is "
        "milliseconds)",
    )
    g.add_argument(
        "--distributed-init",
        action="store_true",
        help="join this replica into a jax.distributed job before warmup "
        "(compat.ensure_cpu_multiprocess_collectives + "
        "jax.distributed autodetection) so the volume gang's mesh spans "
        "the GLOBAL device set — a replica whose mesh crosses processes "
        "(ROADMAP item 3)",
    )
    from nm03_capstone_project_tpu.obs.slo import add_slo_args

    add_slo_args(g)  # --slo-availability/--slo-p99-ms/window flags (ISSUE 14)
    g.add_argument(
        "--flight-dir",
        default=None,
        metavar="DIR",
        help="flight-recorder dump directory (default: $NM03_FLIGHTREC_DIR "
        "or the cwd); dumps fire on SIGUSR2, on one-way CPU degradation, "
        "and on an unhandled crash — docs/OPERATIONS.md post-mortem triage",
    )
    g.add_argument(
        "--ledger-profile-interval-s",
        type=float,
        default=15.0,
        metavar="S",
        help="device-time ledger sampling cadence (ISSUE 16): every S "
        "seconds a short on-device profile is captured and reduced into "
        "the serving_device_time_share{stage} pie; 0 disables the "
        "sampler (per-request device-seconds attribution still runs — "
        "it costs nothing and needs no profiler)",
    )
    g.add_argument(
        "--ledger-profile-ms",
        type=int,
        default=200,
        metavar="MS",
        help="duration of each ledger profile capture (short by design: "
        "the sampler shares utils.profiling's one-capture-at-a-time lock "
        "with GET /debug/profile and must never hold it long)",
    )
    g.add_argument(
        "--device",
        choices=["auto", "tpu", "cpu"],
        default="auto",
        help="compute backend (cpu uses the host XLA backend)",
    )
    g.add_argument("--verbose", action="store_true", help="enable INFO logging")
    common.add_pipeline_args(p)
    common.add_resilience_args(p)
    common.add_observability_args(p)
    return p


def app_from_args(args: argparse.Namespace, obs=None) -> ServingApp:
    from nm03_capstone_project_tpu.cli import common
    from nm03_capstone_project_tpu.compilehub.persist import cache_dir_from_env
    from nm03_capstone_project_tpu.obs.slo import objective_from_args
    from nm03_capstone_project_tpu.resilience import FaultPlan

    cfg = common.pipeline_config_from_args(args)
    res = common.resilience_config_from_args(args)
    plan = res.fault_plan if res.fault_plan is not None else FaultPlan.from_env()
    buckets = tuple(int(b) for b in str(args.buckets).split(",") if b.strip())
    volume_buckets = None
    if getattr(args, "volume_depth_buckets", None):
        volume_buckets = tuple(
            int(b) for b in str(args.volume_depth_buckets).split(",")
            if b.strip()
        )
    if getattr(args, "distributed_init", False):
        # join the jax.distributed job BEFORE any backend work (the
        # ROADMAP item-3 leftover, minimal form): gloo collectives for a
        # CPU-backend mesh, then jax's own cluster autodetection; a
        # single-process start is a documented no-op
        from nm03_capstone_project_tpu.compilehub import (
            ensure_cpu_multiprocess_collectives,
        )
        from nm03_capstone_project_tpu.parallel import distributed

        ensure_cpu_multiprocess_collectives()
        distributed.initialize()
    return ServingApp(
        cfg=cfg,
        queue_capacity=args.queue_capacity,
        buckets=buckets,
        max_wait_s=args.max_wait_ms / 1000.0,
        request_timeout_s=args.request_timeout_s,
        jpeg_quality=args.jpeg_quality,
        resilience=res,
        fault_plan=plan,
        obs=obs,
        lanes=args.lanes or None,
        lane_probe_interval_s=args.lane_probe_interval_s,
        compile_cache_dir=args.compile_cache_dir or cache_dir_from_env(),
        slo=objective_from_args(args),
        volume_serving=getattr(args, "volume_serving", False),
        volume_depth_buckets=volume_buckets,
        volume_queue_capacity=getattr(args, "volume_queue_capacity", 4),
        volume_timeout_s=getattr(args, "volume_timeout_s", 300.0),
        distributed_init=getattr(args, "distributed_init", False),
        ledger_profile_interval_s=getattr(
            args, "ledger_profile_interval_s", 0.0
        ),
        ledger_profile_ms=getattr(args, "ledger_profile_ms", 200),
        result_cache_bytes=parse_bytes(
            getattr(args, "result_cache_bytes", "0") or "0"
        ),
    )


def _relaunch_recipe(effective_argv, port: int):
    """The ``-m``-form argv a fleet orchestrator relaunches us with.

    The BOUND port is substituted for whatever ``--port`` said (an
    ephemeral ``--port 0`` republished verbatim would relaunch the
    replica on a different random port and the orchestrator's warm-wait
    against the old address could never succeed), and added explicitly
    when the flag was defaulted — the recipe must be reproducible on its
    own, not relative to this build's default.
    """
    argv = list(effective_argv)
    out = []
    i = 0
    saw_port = False
    while i < len(argv):
        arg = argv[i]
        if arg == "--port":
            out += ["--port", str(port)]
            saw_port = True
            i += 2
        elif arg.startswith("--port="):
            out.append(f"--port={port}")
            saw_port = True
            i += 1
        else:
            out.append(arg)
            i += 1
    if not saw_port:
        out += ["--port", str(port)]
    return [
        sys.executable, "-m", "nm03_capstone_project_tpu.serving.server",
        *out,
    ]


def _write_port_file(path: str, port: int) -> None:
    import os

    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(f"{port}\n")
    os.replace(tmp, path)


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    from nm03_capstone_project_tpu.cli import common
    from nm03_capstone_project_tpu.obs.slo import objective_from_args
    from nm03_capstone_project_tpu.utils.reporter import configure_reporting

    try:
        objective_from_args(args)  # a bad --slo-* is a usage error, not
    except ValueError as e:        # a traceback mid-startup
        parser.error(str(e))

    common.apply_device_env(args.device)
    configure_reporting(verbose=args.verbose)
    # NM03_LOCKDEP=1: instrument every lock the app is ABOUT to create
    # (docs/STATIC_ANALYSIS.md, NM421/NM422 runtime twin) — must run
    # before any serving object exists, since only post-install creation
    # sites are wrapped; a no-op (zero overhead) without the env gate
    from nm03_capstone_project_tpu.utils import lockdep

    lockdep.install_from_env()
    # arm the flight recorder before any backend work: SIGUSR2 dumps,
    # degradation auto-dumps, and crash dumps all come through here
    from nm03_capstone_project_tpu.obs import flightrec

    flightrec.install(dump_dir=args.flight_dir)
    from nm03_capstone_project_tpu.obs import RunContext

    run_ctx = RunContext.create(
        "serve",
        metrics_out=args.metrics_out,
        log_json=args.log_json,
        heartbeat_s=args.heartbeat_s or 0.0,
        argv=argv,
    )
    app = app_from_args(args, obs=run_ctx)
    httpd = make_http_server(app, args.host, args.port)
    port = httpd.server_address[1]
    # the relaunch recipe for `nm03-fleet restart` (ISSUE 13): always the
    # `-m` module form (console-script and `python -m` launches converge
    # on it) plus the flags THIS process was started with — with the
    # BOUND port substituted — and the cwd they resolve against;
    # published on /readyz so the orchestrator needs no side-channel
    # deploy manifest
    effective_argv = list(argv) if argv is not None else list(sys.argv[1:])
    app.replica_identity["relaunch_argv"] = _relaunch_recipe(
        effective_argv, port
    )
    app.replica_identity["cwd"] = os.getcwd()
    timings = app.start()
    if args.port_file:
        _write_port_file(args.port_file, port)
    print(
        f"nm03-serve: listening on {args.host}:{port} "
        f"(lanes {app.executor.lane_count}, buckets "
        f"{list(app.executor.buckets)}, warmup {timings})",
        flush=True,
    )

    def _drain_and_stop(signum, frame):
        # the handler must return fast; drain on a helper thread, then
        # stop the accept loop so serve_forever returns on the main thread
        def work():
            app.begin_drain(reason=signal.Signals(signum).name.lower())
            httpd.shutdown()

        threading.Thread(target=work, name="nm03-serve-drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _drain_and_stop)
    signal.signal(signal.SIGINT, _drain_and_stop)
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        app.begin_drain(reason="exit")  # idempotent; no-op after a signal drain
        app.close(status="ok")
    print("nm03-serve: drained and stopped", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
