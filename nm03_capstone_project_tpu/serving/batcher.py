"""Dynamic batcher: coalesce in-flight requests into warm-bucket batches.

The TPU pipeline is vmapped and compiled per batch shape; a single-slice
request uses a sliver of the chip. The batcher closes that gap the way
continuous-batching servers do (PAPERS.md — Orca/vLLM insight, applied to
a fixed-shape vision pipeline): requests that arrive within one short wait
window ride the SAME executable call, padded up to the smallest warm
bucket. Under load, batches fill to the cap and the window never waits;
at low load, a request waits at most ``max_wait_s`` before running alone —
the standard latency/throughput knob.

One batcher thread owns all device dispatch. That is a design choice, not
a limitation: the pipeline saturates a single accelerator per batch, so a
second in-flight batch would only queue behind the first on the device
stream — keeping dispatch single-threaded makes supervision (PR 3) and
accounting trivially race-free while costing nothing.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from nm03_capstone_project_tpu.serving.executor import WarmExecutor
from nm03_capstone_project_tpu.serving.metrics import (
    BATCH_SIZE_BUCKETS,
    LATENCY_BUCKETS,
    SERVING_BATCHES_TOTAL,
    SERVING_BATCH_SIZE,
    SERVING_QUEUE_WAIT_SECONDS,
)
from nm03_capstone_project_tpu.serving.queue import AdmissionQueue, ServeRequest
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("serving")


class DynamicBatcher:
    """The single consumer of the admission queue.

    Lifecycle: ``start()`` spawns the daemon thread; ``join()`` (after the
    queue is closed) blocks until every admitted request has been answered
    — the graceful-drain contract: close the door, finish the room.
    """

    def __init__(
        self,
        queue: AdmissionQueue,
        executor: WarmExecutor,
        max_wait_s: float = 0.01,
        max_batch: Optional[int] = None,
        obs=None,
    ):
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.queue = queue
        self.executor = executor
        self.max_wait_s = float(max_wait_s)
        self.max_batch = int(max_batch or executor.max_batch)
        if self.max_batch > executor.max_batch:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the largest warm "
                f"bucket {executor.max_batch}"
            )
        self.obs = obs
        self._thread = threading.Thread(
            target=self._run, name="nm03-serve-batcher", daemon=True
        )
        # written by the batcher thread, read by handler threads via
        # stats() (the /readyz status payload) — lock-guarded (NM331)
        self._lock = threading.Lock()
        self._stats = {"batches": 0, "requests": 0, "max_coalesced": 0}
        # nm03-lint: disable=NM331 written by the owner thread before _thread.start() and read only from that same thread in join(); the Thread.start() fence orders it for the batcher thread
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DynamicBatcher":
        # nm03-lint: disable=NM331 owner-thread write, sequenced before _thread.start(); see __init__
        self._started = True
        self._thread.start()
        return self

    def join(self, timeout_s: Optional[float] = None) -> bool:
        """Wait for the batcher to drain (queue must be closed first)."""
        if not self._started:
            return True
        self._thread.join(timeout=timeout_s)
        return not self._thread.is_alive()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def stats(self) -> dict:
        """Cumulative dispatch accounting (batches, riders, max coalesce).

        Served in the ``/readyz`` status payload: the mean riders-per-batch
        (requests/batches) is the one number that says whether the batching
        window is actually coalescing under current traffic.
        """
        with self._lock:
            return dict(self._stats)

    def _run(self) -> None:
        while True:
            batch = self.queue.get_batch(self.max_batch, self.max_wait_s)
            if not batch:  # closed and empty: drain complete
                return
            try:
                self.execute(batch)
            except BaseException as e:  # noqa: BLE001 — the loop must survive
                # execute() already failed the requests; a raise escaping it
                # is a batcher bug — log, answer anything still waiting, and
                # keep serving (one poisoned batch must not kill the loop)
                log.warning("batcher: batch execution raised: %s", e)
                for r in batch:
                    if not r.done.is_set():
                        r.fail(e)

    # -- the batch path ----------------------------------------------------

    def pad_batch(self, reqs: List[ServeRequest]):
        """Pad ``reqs`` into the smallest warm bucket's canvas stack.

        Same layout contract as the batch drivers' ``_pad_stack``: slices
        compacted into the leading rows, dead lanes zero with ``min_dim``
        dims (their outputs are simply never read back out).
        """
        cfg = self.executor.cfg
        bucket = self.executor.bucket_for(len(reqs))
        c = cfg.canvas
        pixels = np.zeros((bucket, c, c), np.float32)
        dims = np.full((bucket, 2), cfg.min_dim, np.int32)
        for i, r in enumerate(reqs):
            h, w = r.dims
            pixels[i, :h, :w] = r.pixels
            dims[i] = (h, w)
        return pixels, dims

    def execute(self, reqs: List[ServeRequest]) -> None:
        """Run one coalesced batch and answer every request in it."""
        now = time.monotonic()
        reg = self.obs.registry if self.obs is not None else None
        for r in reqs:
            r.queue_wait_s = max(now - r.t_admitted, 0.0)
        if reg is not None:
            wait_h = reg.histogram(
                SERVING_QUEUE_WAIT_SECONDS,
                help="admission-to-dispatch wait per request",
                buckets=LATENCY_BUCKETS,
            )
            for r in reqs:
                wait_h.observe(r.queue_wait_s)
            reg.histogram(
                SERVING_BATCH_SIZE,
                help="coalesced (pre-padding) batch sizes",
                buckets=BATCH_SIZE_BUCKETS,
            ).observe(len(reqs))
            reg.counter(
                SERVING_BATCHES_TOTAL,
                help="device batches dispatched by the serving batcher",
            ).inc()
        with self._lock:
            self._stats["batches"] += 1
            self._stats["requests"] += len(reqs)
            self._stats["max_coalesced"] = max(
                self._stats["max_coalesced"], len(reqs)
            )
        pixels, dims = self.pad_batch(reqs)
        try:
            mask_b, conv_b = self.executor.run_batch(pixels, dims)
        except BaseException as e:  # noqa: BLE001 — per-batch containment
            # the PR-3 ladder is exhausted (deterministic failure, or
            # degraded with --no-fallback-cpu): every rider fails with the
            # same cause; the HTTP layer maps it to a 500
            log.warning("serve dispatch failed for %d request(s): %s", len(reqs), e)
            for r in reqs:
                r.fail(e)
            return
        for i, r in enumerate(reqs):
            h, w = r.dims
            # run_batch already fetched host-side arrays inside the
            # supervised primary; these asarray calls are zero-copy crops
            # nm03-lint: disable=NM322 mask_b/conv_b are host ndarrays (fetched under supervision in WarmExecutor.run_batch); no device sync happens here
            r.mask = np.asarray(mask_b[i][:h, :w])
            r.converged = bool(np.asarray(conv_b[i]))  # nm03-lint: disable=NM322 host ndarray, see above
            r.batch_size = len(reqs)
            r.done.set()
