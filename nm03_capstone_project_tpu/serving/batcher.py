"""Dynamic batcher: coalesce in-flight requests, fan out across lanes.

The TPU pipeline is vmapped and compiled per batch shape; a single-slice
request uses a sliver of one chip. The batcher closes that gap the way
continuous-batching servers do (PAPERS.md — Orca/vLLM insight, applied to
a fixed-shape vision pipeline): requests that arrive within one short wait
window coalesce, then split into per-lane chunks that ride the compile
hub's per-chip executables CONCURRENTLY — the sharded serving fleet.
Under load, the window fills to ``lanes x largest bucket`` and every chip
computes a full bucket at once; at low load, a request waits at most
``max_wait_s`` before running alone on one lane — the standard
latency/throughput knob, now multiplied by chips.

One batcher thread still owns the admission queue (coalescing needs one
consumer); device dispatch is no longer single-threaded — each coalesced
batch's chunks run on a lane-sized worker pool, one supervised dispatch
per lane, and the batcher waits for the slowest chunk before popping the
next window. With one lane this degenerates to exactly the PR-4 behavior:
no pool, inline dispatch, identical accounting.

Per-lane fault domains (ISSUE 8): the fan-out targets are the *currently
healthy* lanes, not all lanes — the coalescing window's capacity shrinks
and grows with the healthy-lane count, and a chunk whose lane quarantines
mid-dispatch (:class:`~nm03_capstone_project_tpu.serving.lanes.LaneQuarantined`)
is re-dispatched to a remaining healthy lane (span ``requeue``) instead
of failing its riders — the request-level analog of the source paper's
per-image error recovery. Only when no healthy lane remains does the
chunk ride the executor's process-wide degraded path (CPU fallback, or a
hard failure with ``--no-fallback-cpu``).
"""

from __future__ import annotations

import concurrent.futures as cf
import contextlib
import itertools
import math
import threading
import time
from typing import List, Optional

import numpy as np

from nm03_capstone_project_tpu.obs.trace import ChunkTrace
from nm03_capstone_project_tpu.serving.executor import WarmExecutor
from nm03_capstone_project_tpu.serving.lanes import LaneQuarantined
from nm03_capstone_project_tpu.serving.metrics import (
    BATCH_SIZE_BUCKETS,
    LATENCY_BUCKETS,
    SERVING_BATCHES_TOTAL,
    SERVING_BATCH_SIZE,
    SERVING_QUEUE_WAIT_SECONDS,
    SERVING_REQUEUES_TOTAL,
    SERVING_RESULT_CACHE_HIT_TOTAL,
)
from nm03_capstone_project_tpu.serving.queue import AdmissionQueue, ServeRequest
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("serving")


class DynamicBatcher:
    """The single consumer of the admission queue.

    Lifecycle: ``start()`` spawns the daemon thread; ``join()`` (after the
    queue is closed) blocks until every admitted request has been answered
    — the graceful-drain contract: close the door, finish the room.
    """

    def __init__(
        self,
        queue: AdmissionQueue,
        executor: WarmExecutor,
        max_wait_s: float = 0.01,
        max_batch: Optional[int] = None,
        obs=None,
    ):
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.queue = queue
        self.executor = executor
        self.max_wait_s = float(max_wait_s)
        # None = lane-unaware executor (tests' fakes): single-lane semantics
        self._lane_aware = hasattr(executor, "lane_count")
        self.max_batch = int(max_batch) if max_batch else None
        self._validate_max_batch()
        self.obs = obs
        self._thread = threading.Thread(
            target=self._run, name="nm03-serve-batcher", daemon=True
        )
        # lane worker pool, created on first multi-chunk batch (a 1-lane
        # process never pays the threads)
        self._pool: Optional[cf.ThreadPoolExecutor] = None
        # round-robin cursor spreading requeued chunks over healthy lanes
        self._requeue_seq = itertools.count()
        # written by the batcher thread, read by handler threads via
        # stats() (the /readyz status payload) — lock-guarded (NM331)
        self._lock = threading.Lock()
        self._stats = {
            "batches": 0,
            "requests": 0,
            "max_coalesced": 0,
            "lane_batches": {},
        }
        # the gang gate (ISSUE 15): the batcher holds this around every
        # window's dispatch, and the volume gang holds it for a whole
        # mesh-wide program — so "park the slice lanes" is one lock
        # acquisition that naturally waits for the in-flight window's
        # slowest chunk and blocks the next window from dispatching
        self._gang_lock = threading.Lock()
        # nm03-lint: disable=NM331 written by the owner thread before _thread.start() and read only from that same thread in join(); the Thread.start() fence orders it for the batcher thread
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def _validate_max_batch(self) -> None:
        """Reject an explicit ``max_batch`` above the fleet's capacity.

        Runs at construction AND again at :meth:`start`: on the normal
        server path the lane count is still unresolved when the batcher is
        built (resolving it would initialize a backend in ``__init__``),
        but by ``start()`` warmup has resolved it — so an operator typo
        like ``--max-batch 64`` on a 1-chip host fails fast at startup
        (the PR-4 contract), never silently clamps.
        """
        if self.max_batch is None:
            return
        lanes_known = (
            getattr(self.executor, "lane_count", None)
            if self._lane_aware
            else 1
        )
        if not lanes_known:
            return  # lanes unresolved: start() re-validates
        fleet = self.executor.max_batch * lanes_known
        if self.max_batch > fleet:
            if lanes_known == 1 and not self._lane_aware:
                raise ValueError(
                    f"max_batch {self.max_batch} exceeds the largest warm "
                    f"bucket {self.executor.max_batch}"
                )
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the fleet capacity "
                f"{fleet} ({lanes_known} lane(s) x largest warm bucket "
                f"{self.executor.max_batch})"
            )

    def start(self) -> "DynamicBatcher":
        self._validate_max_batch()  # lanes are resolved by now (warmup ran)
        # nm03-lint: disable=NM331 owner-thread write, sequenced before _thread.start(); see __init__
        self._started = True
        self._thread.start()
        return self

    def join(self, timeout_s: Optional[float] = None) -> bool:
        """Wait for the batcher to drain (queue must be closed first)."""
        if not self._started:
            return True
        self._thread.join(timeout=timeout_s)
        return not self._thread.is_alive()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    @contextlib.contextmanager
    def gang_parked(self):
        """Park the per-lane slice fleet for one mesh-wide program.

        Acquiring waits for the in-flight coalescing window's slowest
        chunk (the batcher holds the same lock around every window's
        dispatch) and holds new windows back until release — the volume
        gang's "drain the lanes, run the mesh, return the lanes"
        construct (ISSUE 15). Admissions keep flowing into the bounded
        queue throughout, so slice traffic sheds only on the queue's own
        capacity contract, never because a volume was in flight.
        """
        with self._gang_lock:
            yield

    def lanes(self) -> int:
        """The lane count dispatch fans out over (1 until lanes resolve)."""
        if not self._lane_aware:
            return 1
        return self.executor.lane_count or 1

    def healthy_lanes(self) -> List[int]:
        """Lane ids currently taking traffic (the fan-out targets).

        Falls back to every lane when the executor predates fault domains
        (tests' fakes) or when nothing is healthy — in the latter case the
        executor is degraded and any lane id reaches the CPU fallback.
        """
        if self._lane_aware:
            healthy = getattr(self.executor, "healthy_lanes", None)
            if callable(healthy):
                ids = healthy()
                if ids:
                    return ids
        return list(range(self.lanes()))

    def effective_max_batch(self) -> int:
        """The coalescing window's cap: *healthy* fleet capacity, or the
        explicit ``max_batch`` when smaller. Computed per window because
        the lane count resolves at warmup (after construction) and the
        healthy set shrinks/grows with quarantine and reinstatement — a
        3-of-4-lane replica must not coalesce 4 lanes' worth of riders
        onto 3 chips' executables."""
        fleet = self.executor.max_batch * len(self.healthy_lanes())
        if self.max_batch is not None:
            return min(self.max_batch, fleet)
        return fleet

    def stats(self) -> dict:
        """Cumulative dispatch accounting (batches, riders, max coalesce,
        per-lane device batches).

        Served in the ``/readyz`` status payload: the mean riders-per-batch
        (requests/batches) says whether the batching window is coalescing,
        and ``lane_batches`` growing on every lane (not just "0") is the
        fan-out evidence under current traffic.
        """
        with self._lock:
            out = dict(self._stats)
            out["lane_batches"] = dict(self._stats["lane_batches"])
            return out

    def _run(self) -> None:
        while True:
            batch = self.queue.get_batch(
                self.effective_max_batch(), self.max_wait_s
            )
            if not batch:  # closed and empty: drain complete
                return
            try:
                # the gang gate: slice windows dispatch under the lock a
                # volume request parks the fleet with (gang_parked). While
                # a mesh program runs, popped riders wait HERE — inside
                # their existing request deadline — and the admission
                # queue keeps coalescing behind them, so slice traffic
                # resumes at full fan-out the moment the lanes return.
                with self._gang_lock:
                    self.execute(batch)
            except BaseException as e:  # noqa: BLE001 — the loop must survive
                # execute() already failed the requests; a raise escaping it
                # is a batcher bug — log, answer anything still waiting, and
                # keep serving (one poisoned batch must not kill the loop)
                log.warning("batcher: batch execution raised: %s", e)
                for r in batch:
                    if not r.done.is_set():
                        r.fail(e)

    # -- the batch path ----------------------------------------------------

    def pad_batch(self, reqs: List[ServeRequest]):
        """Pad ``reqs`` into the smallest warm bucket's canvas stack.

        Same layout contract as the batch drivers' ``_pad_stack``: slices
        compacted into the leading rows, dead lanes zero with ``min_dim``
        dims (their outputs are simply never read back out).
        """
        cfg = self.executor.cfg
        bucket = self.executor.bucket_for(len(reqs))
        c = cfg.canvas
        pixels = np.zeros((bucket, c, c), np.float32)
        dims = np.full((bucket, 2), cfg.min_dim, np.int32)
        for i, r in enumerate(reqs):
            h, w = r.dims
            pixels[i, :h, :w] = r.pixels
            dims[i] = (h, w)
        return pixels, dims

    def _chunk(
        self, reqs: List[ServeRequest], n_lanes: int
    ) -> List[List[ServeRequest]]:
        """Split one coalesced window into per-lane device chunks.

        Chunk size is the smallest warm bucket holding an even share
        (``ceil(len/n_lanes)``): 12 requests over 8 lanes ride 6 chunks of
        bucket 2 — wide fan-out, minimal padding waste — while 128 over 8
        fill every lane's largest bucket. ``n_lanes`` is the HEALTHY lane
        count: a shrunken fleet packs bigger chunks onto fewer chips
        rather than queueing chunks behind a quarantined lane.
        """
        per = max(1, math.ceil(len(reqs) / max(n_lanes, 1)))
        per = self.executor.bucket_for(min(per, self.executor.max_batch))
        return [reqs[i : i + per] for i in range(0, len(reqs), per)]

    def _dispatch(self, reqs, pixels, dims, lane: int, trace):
        """One dispatch attempt on one lane (trace-aware when supported)."""
        if self._lane_aware and getattr(self.executor, "supports_trace", False):
            # nm03-lint: disable=NM422 the gang gate parks the batcher ACROSS slice dispatch by design — a volume request must wait out the in-flight batch (ISSUE 15), so the hold covers the device call
            return self.executor.run_batch(pixels, dims, lane=lane, trace=trace)
        if self._lane_aware:
            with trace.span("device_dispatch"):
                # nm03-lint: disable=NM422 same deliberate gang-gate hold as above: the dispatch IS the window the gate exists to cover
                return self.executor.run_batch(pixels, dims, lane=lane)
        with trace.span("device_dispatch"):
            # nm03-lint: disable=NM422 same deliberate gang-gate hold as above: the dispatch IS the window the gate exists to cover
            return self.executor.run_batch(pixels, dims)

    def _execute_chunk(self, reqs: List[ServeRequest], lane: int) -> None:
        """Run one chunk on one lane and answer its riders.

        When the lane quarantines mid-dispatch (``LaneQuarantined``), the
        chunk is re-dispatched to a remaining healthy lane under a
        ``requeue`` span — the riders never see the sick chip, they just
        wait one more dispatch inside their existing request deadline.
        Each requeue hop burns one lane from the healthy set, so the loop
        is bounded by the fleet size; when no healthy lane remains the
        executor's process-wide degraded path (CPU fallback) answers.
        """
        # one shared trace for the chunk: every span it records carries all
        # riders' trace ids — a coalesced batch IS one dispatch on one lane
        trace = ChunkTrace([r.trace for r in reqs], lane=lane)
        with trace.span("pad_stack"):
            pixels, dims = self.pad_batch(reqs)
        sat = getattr(self.executor, "saturation", None)
        if sat is not None:
            # goodput accounting (ISSUE 10): real riders vs the bucket rows
            # they were padded into — the dead-row fraction the padding
            # waste gauge reports
            sat.record_chunk(len(reqs), int(pixels.shape[0]))
        # flight-recorder marker BEFORE the dispatch that may wedge: a
        # post-mortem dump must carry the in-flight trace ids even when
        # the dispatch span never closes
        trace.mark("chunk_dispatch", batch=len(reqs), bucket=pixels.shape[0])
        # requeue budget: one hop per lane the fleet started with, plus one
        # final hop for the degraded path — a racing reinstatement cannot
        # make the chunk ping-pong forever
        hops_left = self.lanes() + 1
        while True:
            try:
                mask_b, conv_b = self._dispatch(reqs, pixels, dims, lane, trace)
                break
            except LaneQuarantined as q:
                hops_left -= 1
                if hops_left <= 0:
                    log.warning(
                        "serve chunk exhausted its requeue budget "
                        "(%d riders, last lane %d)", len(reqs), q.lane,
                    )
                    # LaneQuarantined is batcher-internal by contract
                    # (serving/lanes.py): riders get an operator-readable
                    # wrapper, not the routing signal — this only happens
                    # when lanes FLAP (quarantine/reinstate churn faster
                    # than the hop budget) without the fleet ever settling
                    # into the degraded CPU path
                    err = RuntimeError(
                        f"request dispatched {self.lanes() + 1} times "
                        f"({self.lanes()} re-dispatches) across "
                        "quarantining lanes without completing; the "
                        "replica's lanes are flapping (see "
                        "serving_lane_quarantines_total and the "
                        "quarantine-triage runbook)"
                    )
                    err.__cause__ = q
                    for r in reqs:
                        r.fail(err)
                    return
                healthy = [
                    ln for ln in self.healthy_lanes() if ln != q.lane
                ] or [0]  # no healthy lane: the executor is (going) degraded
                # and any lane id reaches the CPU fallback
                # shared round-robin, NOT a function of chunk size: several
                # same-size chunks fleeing one quarantined lane must spread
                # over the survivors, not herd onto one chip
                next_lane = healthy[next(self._requeue_seq) % len(healthy)]
                if self.obs is not None:
                    # the counter twin of the requeue span: nm03-top reads
                    # a requeue RATE from scrape deltas of this series
                    self.obs.registry.counter(
                        SERVING_REQUEUES_TOTAL,
                        help="chunks re-dispatched off a quarantined lane "
                        "(each is one extra supervised dispatch for its "
                        "riders)",
                    ).inc()
                with trace.span(
                    "requeue", from_lane=q.lane, to_lane=next_lane,
                    cause=q.cause,
                ):
                    for r in reqs:
                        r.requeues += 1
                trace.lane = next_lane
                lane = next_lane
            except BaseException as e:  # noqa: BLE001 — per-chunk containment
                # the PR-3 ladder is exhausted (deterministic failure, or
                # degraded with --no-fallback-cpu): every rider of THIS
                # chunk fails with the same cause; the HTTP layer maps it
                # to a 500. Sibling chunks on other lanes are unaffected.
                log.warning(
                    "serve dispatch failed for %d request(s) on lane %d: %s",
                    len(reqs), lane, e,
                )
                for r in reqs:
                    r.fail(e)
                return
        # credit the lane that ACTUALLY ran the chunk (after any requeue
        # hops) — /readyz's lane_batches must agree with the executor's
        # serving_lane_batches_total for the same traffic. A chunk the
        # process-wide CPU fallback served ran on NO lane: neither series
        # counts it. The executor flags that case on the chunk's OWN trace
        # — re-reading `degraded` here would race a concurrent last-lane
        # quarantine and miscount a chunk that DID run on its lane.
        served_on_lane = not getattr(trace, "served_by_fallback", False)
        if self._lane_aware and not getattr(
            self.executor, "supports_trace", False
        ):
            # the trace never reached the executor (lane-aware test fake):
            # the degraded re-read is the only signal available
            served_on_lane = not getattr(self.executor, "degraded", False)
        if served_on_lane:
            with self._lock:
                lane_key = str(lane)
                self._stats["lane_batches"][lane_key] = (
                    self._stats["lane_batches"].get(lane_key, 0) + 1
                )
        # device-time ledger (ISSUE 16): prorate the chunk's accumulated
        # busy seconds (every attempt, requeues included) across its canvas
        # rows — real riders to the `request` account, fleet probation
        # canaries to `probe`, dead rows to `padding`. The per-row share is
        # each rider's cost; a fallback-served chunk accumulated no busy
        # (it ran on no device lane), so its share is an honest 0.0.
        ledger = getattr(self.executor, "ledger", None)
        share = 0.0
        if ledger is not None:
            probes = sum(1 for r in reqs if getattr(r, "probe", False))
            share = ledger.charge_chunk(
                getattr(trace, "device_busy_s", 0.0),
                int(pixels.shape[0]),
                len(reqs) - probes,
                probe_rows=probes,
            )
        for i, r in enumerate(reqs):
            h, w = r.dims
            # run_batch already fetched host-side arrays inside the
            # supervised primary; these asarray calls are zero-copy crops
            # nm03-lint: disable=NM322 mask_b/conv_b are host ndarrays (fetched under supervision in WarmExecutor.run_batch); no device sync happens here
            r.mask = np.asarray(mask_b[i][:h, :w])
            r.converged = bool(np.asarray(conv_b[i]))  # nm03-lint: disable=NM322 host ndarray, see above
            r.batch_size = len(reqs)
            # a fallback-served chunk ran on NO lane: the payload/header
            # report null, matching the lane accounting both series skip
            r.lane = lane if served_on_lane else None
            # the rider's prorated device cost (echoed in the payload);
            # probe canaries carry it too but are excluded from the
            # per-request histogram below, the PR 14 contract
            r.device_seconds = share
            if ledger is not None and not getattr(r, "probe", False):
                ledger.observe_request(share)
            r.done.set()

    def execute(self, reqs: List[ServeRequest]) -> None:
        """Run one coalesced window — fanned across lanes — and answer it."""
        now = time.monotonic()
        reg = self.obs.registry if self.obs is not None else None
        for r in reqs:
            r.queue_wait_s = max(now - r.t_admitted, 0.0)
            if r.trace is not None:
                # retrospective spans from the stamps the queue left:
                # admission -> pop (queue_wait), pop -> window close
                # (coalesce) — together they are the reported queue_wait_s
                popped = r.t_popped or now
                r.trace.add_span("queue_wait", r.t_admitted, popped)
                r.trace.add_span("coalesce", popped, now)
        # the in-flight dedup window (ISSUE 19): identical content-
        # addressed slices in one window ride a SINGLE dispatch — the
        # first of each digest becomes the leader, the rest become
        # riders that copy its mask after the barrier. A zipfian replay
        # that lands 8 copies of one study in a window spends one batch
        # row on it, not eight.
        leaders: List[ServeRequest] = []
        dup_riders: dict = {}
        leader_by_digest: dict = {}
        for r in reqs:
            d = getattr(r, "digest", None)
            if d is None or getattr(r, "probe", False):
                leaders.append(r)
                continue
            if d in leader_by_digest:
                dup_riders.setdefault(d, []).append(r)
            else:
                leader_by_digest[d] = r
                leaders.append(r)
        # fan over the lanes that are actually taking traffic: a window
        # coalesced while lane 2 sat in quarantine splits across the other
        # three and never waits on the sick chip
        targets = self.healthy_lanes()
        chunks = self._chunk(leaders, len(targets))
        sat = getattr(self.executor, "saturation", None)
        if sat is not None:
            # occupancy: this window's riders against what the HEALTHY
            # fleet could have carried (largest bucket x healthy lanes) —
            # a persistently low ratio means the fleet is oversized for
            # the offered load, not that batching is broken
            # deduped rows are real capacity headroom: occupancy counts
            # what was actually dispatched, not the rider count
            sat.record_window(
                len(leaders), self.executor.max_batch * len(targets)
            )
        if reg is not None:
            wait_h = reg.histogram(
                SERVING_QUEUE_WAIT_SECONDS,
                help="admission-to-dispatch wait per request",
                buckets=LATENCY_BUCKETS,
            )
            for r in reqs:
                # probe riders (fleet canaries, ISSUE 14) are served and
                # traced but never observed into the request metrics
                if not getattr(r, "probe", False):
                    wait_h.observe(r.queue_wait_s)
            reg.histogram(
                SERVING_BATCH_SIZE,
                help="coalesced (pre-padding) batch sizes",
                buckets=BATCH_SIZE_BUCKETS,
            ).observe(len(leaders))
            reg.counter(
                SERVING_BATCHES_TOTAL,
                help="device batches dispatched by the serving batcher",
            ).inc(len(chunks))
        # chunk ci rides HEALTHY lane targets[ci % len(targets)] — never a
        # quarantined one (the executor would only bounce it back)
        assign = [targets[ci % len(targets)] for ci in range(len(chunks))]
        with self._lock:
            self._stats["batches"] += len(chunks)
            self._stats["requests"] += len(reqs)
            self._stats["max_coalesced"] = max(
                self._stats["max_coalesced"], len(reqs)
            )
        if len(chunks) == 1:
            self._execute_chunk(chunks[0], assign[0])
        else:
            with self._lock:
                if self._pool is None:
                    # sized to the FULL fleet: reinstated lanes must not
                    # queue behind a pool sized during a quarantine dip
                    self._pool = cf.ThreadPoolExecutor(
                        max_workers=self.lanes(),
                        thread_name_prefix="nm03-serve-lane",
                    )
                pool = self._pool
            futures = [
                pool.submit(self._execute_chunk, chunk, assign[ci])
                for ci, chunk in enumerate(chunks)
            ]
            for f in futures:
                # nm03-lint: disable=NM422 the barrier IS the gang contract: gang_parked() must not return lanes until every in-flight chunk lands (_execute_chunk never raises)
                f.result()
        if dup_riders:
            self._fan_out_duplicates(leader_by_digest, dup_riders, reg)

    def _fan_out_duplicates(self, leader_by_digest, dup_riders, reg) -> None:
        """Answer dedup riders from their leader's filled result.

        Runs after the window's dispatch barrier, so every leader's
        ``done`` has fired. Riders share the leader's mask ARRAY (the
        HTTP layer only reads it), its convergence verdict and — on the
        sad path — its error; they charge the ledger ZERO device-seconds,
        which is exactly the dedup win the ledger must show.
        """
        hit = None
        if reg is not None:
            hit = reg.counter(
                SERVING_RESULT_CACHE_HIT_TOTAL,
                help="result-tier lookups served from cache, by tier",
                tier="inflight",
            )
        ledger = getattr(self.executor, "ledger", None)
        for d, riders in dup_riders.items():
            leader = leader_by_digest[d]
            for r in riders:
                if leader.error is not None:
                    r.fail(leader.error)
                    continue
                r.mask = leader.mask
                r.converged = leader.converged
                r.batch_size = leader.batch_size
                r.lane = leader.lane
                r.requeues = leader.requeues
                r.device_seconds = 0.0
                if ledger is not None and not getattr(r, "probe", False):
                    ledger.observe_request(0.0)
                if hit is not None:
                    hit.inc()
                r.done.set()
