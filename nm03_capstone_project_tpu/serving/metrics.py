"""Serving metric names and bucket layouts (docs/OBSERVABILITY.md).

One module owns every serving series name so the producer (server/batcher/
executor), the validator invocations in tests, and the docs cannot drift
apart. All series live in the ordinary PR-1 obs registry — ``/metrics`` is
just ``MetricsRegistry.to_prometheus()`` over the run's registry, so the
batch-era counters (``resilience_retries_total``, ``pipeline_degraded_total``
...) appear next to these when serving traffic exercises those paths.
"""

from __future__ import annotations

# saturation/goodput series (ISSUE 10) are DEFINED in obs.metrics — the
# SaturationMonitor lives in jax-/numpy-free obs/ and cannot import this
# package — and re-exported here so serving-side callers keep one import
# home for every serving series name (NM392 counts the definition site).
from nm03_capstone_project_tpu.obs.metrics import (  # noqa: F401
    LEDGER_PROFILE_SKIPPED_TOTAL,
    SERVING_BATCH_ROWS_TOTAL,
    SERVING_BUCKET_FILL_RATIO,
    SERVING_BUSY_FRACTION,
    SERVING_DEVICE_SECONDS_PER_REQUEST,
    SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN,
    SERVING_DEVICE_SECONDS_TOTAL,
    SERVING_DEVICE_TIME_SHARE,
    SERVING_EXECUTABLE_HBM_BYTES,
    SERVING_LANE_BUSY_FRACTION,
    SERVING_LANE_IDLE_GAP_SECONDS,
    SERVING_LANE_MFU,
    SERVING_LANE_PEAK_FLOPS,
    SERVING_MFU,
    SERVING_PADDING_WASTE_RATIO,
    SERVING_RESULT_CACHE_BYTES,
    SERVING_RESULT_CACHE_EVICT_TOTAL,
    SERVING_RESULT_CACHE_FILL_TOTAL,
    SERVING_RESULT_CACHE_HIT_TOTAL,
    SERVING_RESULT_CACHE_MISS_TOTAL,
    SERVING_WINDOW_OCCUPANCY_RATIO,
    SLO_BURN_RATE_FAST,
    SLO_BURN_RATE_SLOW,
    SLO_ERROR_BUDGET_REMAINING,
    SLO_OBJECTIVE_INFO,
)

# -- counters ---------------------------------------------------------------
# terminal request outcomes by status: ok | error | shed | invalid | timeout
SERVING_REQUESTS_TOTAL = "serving_requests_total"
# admissions refused by backpressure (queue full or draining); also counted
# in serving_requests_total{status="shed"} — this unlabeled counter is the
# single number capacity alerts watch
SERVING_SHED_TOTAL = "serving_shed_total"
# dispatched device batches (post-coalescing; requests/batches = mean batch)
SERVING_BATCHES_TOTAL = "serving_batches_total"
# device batches per replica lane ({lane}): the fan-out evidence — under
# load every lane's series grows, not just lane 0's
SERVING_LANE_BATCHES_TOTAL = "serving_lane_batches_total"
# lane quarantine transitions ({lane, cause}); cause is deadline /
# device_lost (the supervised-dispatch outcomes) or probe_failed (a
# probation canary failed and the lane went back to quarantine)
SERVING_LANE_QUARANTINES_TOTAL = "serving_lane_quarantines_total"
# probation probes that passed and returned the lane to traffic ({lane})
SERVING_LANE_REINSTATED_TOTAL = "serving_lane_reinstated_total"
# persistent compile cache (ISSUE 9): executables deserialized from /
# missed in --compile-cache-dir during warmup. Published once after
# warmup from the hub's cache stats (presence marks a cache-enabled run;
# a warm restart's acceptance gate is hits == warm spec count AND the
# builds stat at 0). compile_cache_load_seconds is the gauge twin:
# total deserialization wall — what the warm start paid INSTEAD of
# total_compile_seconds.
COMPILE_CACHE_HITS_TOTAL = "compile_cache_hits_total"
COMPILE_CACHE_MISSES_TOTAL = "compile_cache_misses_total"
# chunks re-dispatched off a quarantined lane (ISSUE 8's requeue span,
# counted so nm03-top can show a requeue RATE from scrape deltas)
SERVING_REQUEUES_TOTAL = "serving_requeues_total"
# whole-volume serving (ISSUE 15): terminal POST /v1/segment-volume
# outcomes by status (ok | error | shed | invalid | timeout) — the gang
# lane's request accounting, separate from the per-slice series because
# one volume request is a whole-mesh dispatch, not one slice
SERVING_VOLUME_REQUESTS_TOTAL = "serving_volume_requests_total"

# -- gauges -----------------------------------------------------------------
# compile-cost accounting (ISSUE 7; labels: spec = CompileSpec.label()):
# what each warm executable cost to build and what it costs to run — the
# denominators the perf trajectory was missing. Published from the hub's
# cost report after serving warmup; flops/hbm series exist only where the
# jaxlib version exposes cost_analysis()/memory_analysis().
COMPILE_SECONDS = "compile_seconds"
EXECUTABLE_FLOPS = "executable_flops"
EXECUTABLE_HBM_BYTES = "executable_hbm_bytes"
COMPILE_CACHE_LOAD_SECONDS = "compile_cache_load_seconds"
SERVING_INFLIGHT = "serving_inflight"  # admitted, not yet responded
SERVING_READY = "serving_ready"  # 1 = warmed + admitting, 0 otherwise
SERVING_DEGRADED = "serving_degraded"  # 1 = one-way CPU degradation tripped
# warm replica lanes (chips): rises lane-by-lane through warmup; the
# multi-chip readiness signal check_telemetry's --expect-gauge asserts
SERVING_LANES_READY = "serving_lanes_ready"
SERVING_LANE_INFLIGHT = "serving_lane_inflight"  # {lane}: batches in flight
# per-lane fault-domain state ({lane}); values from LANE_STATE_VALUES —
# the series a chaos drill asserts with check_telemetry's labeled
# --expect-gauge form (serving_lane_state{lane=2}=0)
SERVING_LANE_STATE = "serving_lane_state"
LANE_STATE_VALUES = {"healthy": 0, "probation": 1, "quarantined": 2}
# startup compile+first-execute per lane and bucket (set by warmup)
SERVING_WARMUP_SECONDS = "serving_warmup_seconds"
# whole-volume serving gauges (ISSUE 15): z-shards the LAST served volume
# actually spanned (the gang's mesh width — shrinks when the gang fails
# over onto a surviving mesh) and the last request's gang-wait: how long
# the volume waited for the per-lane slice batcher to park (the
# scheduling cost of borrowing the whole mesh; gauge, not histogram, so
# check_telemetry's --expect-gauge-range can gate it directly)
SERVING_VOLUME_ZSHARDS = "serving_volume_zshards"
SERVING_VOLUME_GANG_WAIT_SECONDS = "serving_volume_gang_wait_seconds"

# -- histograms -------------------------------------------------------------
SERVING_QUEUE_WAIT_SECONDS = "serving_queue_wait_seconds"
SERVING_BATCH_SIZE = "serving_batch_size"
SERVING_REQUEST_SECONDS = "serving_request_seconds"  # end-to-end, admission->response built

# Online latencies live in the millisecond-to-seconds band, not the
# multi-minute cohort band DEFAULT_LATENCY_BUCKETS covers.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
# Coalesced batch sizes; bucketed at the warm-executable sizes.
BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

# `probe` (ISSUE 14): every terminal status of a fleet probation canary
# (X-Nm03-Probe) lands here — visible, and excluded from SLO accounting
# (neither the good nor the bad status set contains it)
REQUEST_STATUSES = ("ok", "error", "shed", "invalid", "timeout", "probe")
