"""``nm03-loadgen``: closed/open-loop load generator for ``nm03-serve``.

The bench evidence chain (BENCH_r*.json, docs/PERF.md) measures the batch
pipeline; this tool measures the SERVING path — queue wait, coalescing,
shed behavior — with the numbers capacity planning needs: p50/p95/p99
end-to-end latency, sustained throughput, status mix, and the observed
batch-size distribution (from the server's ``X-Nm03-Batch-Size`` header,
the direct evidence that dynamic batching coalesced anything). Every
request carries a unique ``X-Nm03-Request-Id`` the server honors as its
trace id and echoes back; the per-request records in ``--results-json``
(sent/echoed id, server-reported queue-wait and lane) join client-side
latencies to the server-side span trees ``nm03-trace`` exports (ISSUE 7).

Two traffic models:

* **closed loop** (default): ``--concurrency`` workers, each with one
  request outstanding — throughput is offered-load-limited, the classic
  saturation probe;
* **open loop** (``--rate R``): requests fire on a fixed schedule no
  matter how the server is doing — the model that actually exposes queue
  growth and shedding (closed loops self-throttle and hide both).

``--targets URL[,URL...]`` is the multi-target mode (ISSUE 13): drive an
``nm03-fleet`` front-end (or replicas directly) with request *i* going to
``targets[i % n]``; the summary gains ``replicas_observed`` /
``failovers_observed`` / ``fleet_capacity_min_observed`` from the fleet
payload's truth fields and its ``/readyz`` — a chaos run's throughput dip
comes explained.

``--self-serve`` brings up an in-process server (ephemeral port) first —
the zero-setup smoke: ``nm03-loadgen --self-serve --requests 40``. Pure
stdlib HTTP client; payloads are synthetic phantom slices sent as raw
float32 arrays (``--dicom`` sends real Part-10 bytes through the full
parser path instead).
"""

from __future__ import annotations

import argparse
import collections
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import List, Optional

import numpy as np

from nm03_capstone_project_tpu.serving.metrics import (
    SERVING_BATCHES_TOTAL,
    SERVING_BUSY_FRACTION,
    SERVING_MFU,
    SERVING_PADDING_WASTE_RATIO,
)


def parse_slo_spec(spec: str) -> dict:
    """``availability=99.5,p99_ms=500`` -> {availability, p99_ms} (ISSUE 14).

    Either key may be omitted (at least one required); values are floats.
    Raises ValueError on malformed input (the CLI maps it to a usage
    error).
    """
    out: dict = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        k, eq, v = part.partition("=")
        k = k.strip()
        if not eq or k not in ("availability", "p99_ms"):
            raise ValueError(
                f"--expect-slo wants availability=PCT and/or p99_ms=MS, "
                f"got {part!r}"
            )
        try:
            out[k] = float(v.strip())
        except ValueError:
            raise ValueError(
                f"--expect-slo value for {k} must be a number, got "
                f"{v.strip()!r}"
            ) from None
    if not out:
        raise ValueError("--expect-slo needs at least one objective")
    if "availability" in out and not 0.0 < out["availability"] <= 100.0:
        raise ValueError(
            f"--expect-slo availability must be in (0, 100], got "
            f"{out['availability']}"
        )
    return out


def evaluate_slo(summary: dict, expect: dict) -> dict:
    """The client-side SLO gate verdict over one run's summary.

    Availability is judged on the CLIENT's view — ok requests over total
    — and p99 on the client-observed end-to-end latency, so the gate
    measures what users saw, not what any one process published. Returns
    ``{pass, checks: {...}}`` (each check: expected/observed/pass).
    """
    checks: dict = {}
    if "availability" in expect:
        total = summary.get("requests_total") or 0
        ok = summary.get("requests_ok") or 0
        observed = (ok / total * 100.0) if total else 0.0
        checks["availability"] = {
            "expected_pct": expect["availability"],
            "observed_pct": round(observed, 4),
            "pass": observed >= expect["availability"],
        }
    if "p99_ms" in expect:
        observed = (summary.get("latency_ms") or {}).get("p99")
        checks["p99_ms"] = {
            "expected_ms": expect["p99_ms"],
            "observed_ms": observed,
            "pass": observed is not None and observed <= expect["p99_ms"],
        }
    return {
        "pass": all(c["pass"] for c in checks.values()),
        "checks": checks,
    }


def _percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1, int(round(p / 100.0 * len(sorted_vals))) - 1))
    return sorted_vals[k]


class LoadResult:
    """Thread-safe accumulator for per-request observations."""

    # per-request records kept for --results-json; bounded so a very long
    # soak cannot balloon the artifact
    MAX_REQUEST_RECORDS = 10000

    def __init__(self):
        self._lock = threading.Lock()
        self.latencies_s: List[float] = []
        self.statuses: collections.Counter = collections.Counter()
        self.batch_sizes: collections.Counter = collections.Counter()
        self.queue_waits_s: List[float] = []
        self.lanes: collections.Counter = collections.Counter()
        # fleet attribution (ISSUE 13): which replica answered (the
        # fleet payload's `replica`, or the target URL when driving
        # replicas directly) and how many failover hops riders took
        self.replicas: collections.Counter = collections.Counter()
        self.failovers = 0
        self.requests_dropped = 0
        self.requests: List[dict] = []
        self.echo_mismatches = 0
        self.errors: List[str] = []
        # whole-volume attribution (ISSUE 15): per-request z-shard counts
        # and gang-waits from the /v1/segment-volume response payload
        self.zshards: collections.Counter = collections.Counter()
        self.gang_waits_s: List[float] = []
        # device-cost attribution (ISSUE 16): each ok response's prorated
        # `device_seconds` payload field — the client-side view of the
        # serving_device_seconds_per_request histogram
        self.device_seconds: List[float] = []
        # result-tier attribution (ISSUE 19): each ok response's
        # X-Nm03-Cache verdict (hit | miss | fill), with the latency and
        # device-seconds distributions split served-from-cache vs
        # computed — the zipfian replay's evidence columns
        self.cache_states: collections.Counter = collections.Counter()
        self.latencies_hit_s: List[float] = []
        self.latencies_miss_s: List[float] = []
        self.device_seconds_hit: List[float] = []
        self.device_seconds_miss: List[float] = []

    def record(self, status: str, latency_s: float, batch_size: int = 0,
               error: str = "", sent_id: str = "", echoed_id: str = "",
               queue_wait_s: Optional[float] = None,
               lane: Optional[int] = None,
               replica: Optional[str] = None,
               replica_hops: Optional[int] = None,
               z_shards: Optional[int] = None,
               gang_wait_s: Optional[float] = None,
               device_s: Optional[float] = None,
               cache_state: Optional[str] = None) -> None:
        with self._lock:
            self.statuses[status] += 1
            if status == "ok":
                self.latencies_s.append(latency_s)
                if batch_size:
                    self.batch_sizes[batch_size] += 1
                if queue_wait_s is not None:
                    self.queue_waits_s.append(queue_wait_s)
                if lane is not None:
                    self.lanes[lane] += 1
                if replica is not None:
                    self.replicas[replica] += 1
                if replica_hops:
                    self.failovers += 1
                if z_shards is not None:
                    self.zshards[int(z_shards)] += 1
                if gang_wait_s is not None:
                    self.gang_waits_s.append(gang_wait_s)
                if device_s is not None:
                    self.device_seconds.append(device_s)
                if cache_state is not None:
                    self.cache_states[cache_state] += 1
                    if cache_state == "hit":
                        self.latencies_hit_s.append(latency_s)
                        if device_s is not None:
                            self.device_seconds_hit.append(device_s)
                    else:  # miss and fill both computed
                        self.latencies_miss_s.append(latency_s)
                        if device_s is not None:
                            self.device_seconds_miss.append(device_s)
            elif error and len(self.errors) < 20:
                self.errors.append(error)
            if sent_id and echoed_id and sent_id != echoed_id:
                self.echo_mismatches += 1
            if len(self.requests) < self.MAX_REQUEST_RECORDS:
                rec = {
                    "id": sent_id,
                    "echoed_id": echoed_id,
                    "status": status,
                    "latency_ms": round(latency_s * 1e3, 3),
                }
                if queue_wait_s is not None:
                    rec["queue_wait_ms"] = round(queue_wait_s * 1e3, 3)
                if lane is not None:
                    rec["lane"] = lane
                if batch_size:
                    rec["batch_size"] = batch_size
                if replica is not None:
                    rec["replica"] = replica
                if replica_hops is not None:
                    rec["replica_hops"] = replica_hops
                if z_shards is not None:
                    rec["z_shards"] = int(z_shards)
                if gang_wait_s is not None:
                    rec["gang_wait_ms"] = round(gang_wait_s * 1e3, 3)
                if device_s is not None:
                    rec["device_seconds"] = round(device_s, 9)
                if cache_state is not None:
                    rec["cache"] = cache_state
                self.requests.append(rec)
            else:
                # counted, not silent: a soak past the cap must say so in
                # the artifact, or a server-side join reads the missing
                # tail as requests with no client record
                self.requests_dropped += 1

    def summary(self, wall_s: float, mode: str) -> dict:
        lat = sorted(self.latencies_s)
        n_ok = len(lat)
        total = sum(self.statuses.values())
        out = {
            "schema": "nm03.loadgen.v1",
            "mode": mode,
            "requests_total": total,
            "requests_ok": n_ok,
            "statuses": dict(sorted(self.statuses.items())),
            "wall_s": round(wall_s, 3),
            "throughput_rps": round(n_ok / wall_s, 2) if wall_s > 0 else 0.0,
            "latency_ms": {
                "p50": round(_percentile(lat, 50) * 1e3, 2),
                "p95": round(_percentile(lat, 95) * 1e3, 2),
                "p99": round(_percentile(lat, 99) * 1e3, 2),
                "mean": round(sum(lat) / n_ok * 1e3, 2) if n_ok else 0.0,
                "max": round(lat[-1] * 1e3, 2) if n_ok else 0.0,
            },
            # {batch_size: ok-request count}: >1 keys = coalescing happened
            "batch_sizes": {str(k): v for k, v in sorted(self.batch_sizes.items())},
            "max_observed_batch": max(self.batch_sizes) if self.batch_sizes else 0,
        }
        # server-reported attribution (X-Nm03-Queue-Wait-Ms / X-Nm03-Lane):
        # the queue-wait distribution separates "the server was slow" from
        # "the request waited", and lanes_observed is the client-side view
        # of the fleet fan-out
        qw = sorted(self.queue_waits_s)
        out["queue_wait_ms"] = {
            "p50": round(_percentile(qw, 50) * 1e3, 2),
            "p95": round(_percentile(qw, 95) * 1e3, 2),
            "p99": round(_percentile(qw, 99) * 1e3, 2),
            "mean": round(sum(qw) / len(qw) * 1e3, 2) if qw else 0.0,
        }
        out["lanes_observed"] = {str(k): v for k, v in sorted(self.lanes.items())}
        # fleet attribution (ISSUE 13): ok-request counts by answering
        # replica (>1 keys = the fleet really spread the load) and the
        # riders that outlived a replica via failover (replica_hops >= 1)
        out["replicas_observed"] = {
            str(k): v for k, v in sorted(self.replicas.items())
        }
        out["failovers_observed"] = self.failovers
        # whole-volume evidence (ISSUE 15): which mesh widths served the
        # volumes and the gang-wait distribution — the request-level view
        # of the serving_volume_* gauges the acceptance drill gates
        if self.zshards:
            gw = sorted(self.gang_waits_s)
            out["volume"] = {
                "zshards_observed": {
                    str(k): v for k, v in sorted(self.zshards.items())
                },
                "gang_wait_ms": {
                    "p50": round(_percentile(gw, 50) * 1e3, 3),
                    "p95": round(_percentile(gw, 95) * 1e3, 3),
                    "max": round(gw[-1] * 1e3, 3) if gw else 0.0,
                    "mean": round(sum(gw) / len(gw) * 1e3, 3) if gw else 0.0,
                },
            }
        # device-cost evidence (ISSUE 16): the prorated device-seconds
        # distribution clients were billed — the request-level view of
        # serving_device_seconds_total{account="request"}. Milliseconds,
        # like every other latency block in this summary.
        if self.device_seconds:
            ds = sorted(self.device_seconds)
            out["device_seconds_ms"] = {
                "p50": round(_percentile(ds, 50) * 1e3, 3),
                "p95": round(_percentile(ds, 95) * 1e3, 3),
                "mean": round(sum(ds) / len(ds) * 1e3, 3),
                "max": round(ds[-1] * 1e3, 3),
                "sum_s": round(sum(ds), 6),
            }
            # the result-tier split (ISSUE 19): what a hit is worth —
            # hit_mean must read ~0.0 (a hit charges no device time),
            # miss_mean is what each cold study actually cost
            if self.device_seconds_hit or self.device_seconds_miss:
                dh, dm = self.device_seconds_hit, self.device_seconds_miss
                out["device_seconds_ms"]["hit_mean"] = (
                    round(sum(dh) / len(dh) * 1e3, 6) if dh else None
                )
                out["device_seconds_ms"]["miss_mean"] = (
                    round(sum(dm) / len(dm) * 1e3, 6) if dm else None
                )
        # result-tier evidence (ISSUE 19): the hit ratio clients saw
        # (X-Nm03-Cache: hit over every response that carried the
        # header) and the latency split that prices a repeat study
        if self.cache_states:
            total_states = sum(self.cache_states.values())
            hits = self.cache_states.get("hit", 0)
            lh = sorted(self.latencies_hit_s)
            lm = sorted(self.latencies_miss_s)
            out["cache_hit_ratio"] = round(hits / total_states, 4)
            out["cache"] = {
                "states": dict(sorted(self.cache_states.items())),
                "hit_latency_ms": {
                    "p50": round(_percentile(lh, 50) * 1e3, 3),
                    "p95": round(_percentile(lh, 95) * 1e3, 3),
                },
                "miss_latency_ms": {
                    "p50": round(_percentile(lm, 50) * 1e3, 3),
                    "p95": round(_percentile(lm, 95) * 1e3, 3),
                },
            }
        out["trace_echo_mismatches"] = self.echo_mismatches
        if self.requests_dropped:
            out["requests_record_cap"] = self.MAX_REQUEST_RECORDS
            out["requests_records_dropped"] = self.requests_dropped
        if self.errors:
            out["error_sample"] = self.errors[:5]
        return out


def _make_payloads(height: int, width: int, n_distinct: int, dicom: bool):
    """Pre-build request bodies (payload build must not pollute latency).

    Raw mode sends little-endian float32 with the dims in headers; DICOM
    mode writes real Part-10 bytes so the server exercises the actual
    parser. A few distinct phantoms (lesion radius varies with seed) keep
    the server from serving one memoized answer shape.
    """
    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    payloads = []
    for i in range(n_distinct):
        img = phantom_slice(height, width, seed=i)
        if dicom:
            from nm03_capstone_project_tpu.data.dicomlite import write_dicom

            import os
            import tempfile

            fd, path = tempfile.mkstemp(suffix=".dcm")
            os.close(fd)
            try:
                write_dicom(path, np.clip(img, 0, 65535).astype(np.uint16))
                with open(path, "rb") as f:
                    body = f.read()
            finally:
                os.unlink(path)
            headers = {"Content-Type": "application/dicom"}
        else:
            body = img.astype("<f4").tobytes()
            headers = {
                "Content-Type": "application/octet-stream",
                "X-Nm03-Height": str(height),
                "X-Nm03-Width": str(width),
            }
        payloads.append((body, headers))
    return payloads


def _make_volume_payloads(
    depth: int, height: int, width: int, n_distinct: int, dicom: bool
):
    """Pre-build whole-study request bodies (``--volume`` mode, ISSUE 15).

    Raw mode stacks ``depth`` phantom slices as little-endian float32
    with the dims in X-Nm03-Depth/Height/Width; DICOM mode writes one
    Part-10 file per plane and concatenates them under the length-
    prefixed ``application/x-nm03-dicom-parts`` framing the server
    decodes (docs/API.md).
    """
    from nm03_capstone_project_tpu.data.synthetic import phantom_volume

    payloads = []
    for i in range(n_distinct):
        vol = np.asarray(
            phantom_volume(n_slices=depth, height=height, width=width, seed=i),
            np.float32,
        )
        if dicom:
            import os
            import tempfile

            from nm03_capstone_project_tpu.data.dicomlite import write_dicom

            parts = []
            fd, path = tempfile.mkstemp(suffix=".dcm")
            os.close(fd)
            try:
                for plane in vol:
                    write_dicom(
                        path, np.clip(plane, 0, 65535).astype(np.uint16)
                    )
                    with open(path, "rb") as f:
                        raw = f.read()
                    parts.append(len(raw).to_bytes(4, "little") + raw)
            finally:
                os.unlink(path)
            body = b"".join(parts)
            headers = {"Content-Type": "application/x-nm03-dicom-parts"}
        else:
            body = vol.astype("<f4").tobytes()
            headers = {
                "Content-Type": "application/octet-stream",
                "X-Nm03-Depth": str(depth),
                "X-Nm03-Height": str(height),
                "X-Nm03-Width": str(width),
            }
        payloads.append((body, headers))
    return payloads


def _zipf_schedule(payloads, n_requests: int, s: float):
    """Expand ``payloads`` into a per-request zipfian replay (ISSUE 19).

    Request *i* sends ``schedule[i % n]`` — ``run_load``'s round-robin
    indexing — so pre-drawing the whole schedule turns study REUSE into
    plain list repetition with zero change to the send path (the entries
    alias the same body bytes; nothing is copied). Rank *r* is drawn
    with P(r) ∝ 1/r^s over the keyspace; at s ≈ 1.1 over 32 studies the
    hottest study is roughly a quarter of all traffic — the skew a
    hospital's repeat-read workload actually shows, and the one the
    result tier is priced against. The seed is fixed: two runs replay
    the identical request stream, so a cold-vs-warm comparison differs
    only in cache state.
    """
    ranks = np.arange(1, len(payloads) + 1, dtype=np.float64)
    probs = ranks ** -float(s)
    probs /= probs.sum()
    rng = np.random.default_rng(20260807)
    draws = rng.choice(len(payloads), size=max(1, int(n_requests)), p=probs)
    return [payloads[int(i)] for i in draws]


def _one_request(url: str, body: bytes, headers: dict, timeout_s: float,
                 result: LoadResult, req_id: str = "") -> None:
    t0 = time.monotonic()
    if req_id:
        headers = {**headers, "X-Nm03-Request-Id": req_id}
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            data = resp.read()
            bs = int(resp.headers.get("X-Nm03-Batch-Size", 0))
            echoed = resp.headers.get("X-Nm03-Request-Id", "")
            qw_hdr = resp.headers.get("X-Nm03-Queue-Wait-Ms")
            lane_hdr = resp.headers.get("X-Nm03-Lane")
            try:
                qw = float(qw_hdr) / 1e3 if qw_hdr is not None else None
            except ValueError:
                qw = None
            try:
                lane = int(lane_hdr) if lane_hdr not in (None, "None") else None
            except ValueError:
                lane = None
            # fleet attribution (ISSUE 13): the payload's replica /
            # replica_hops (the fleet front-end's truth fields), header
            # then target-host:port fallback — so replicas_observed is
            # meaningful whether --targets drives a fleet or replicas
            # directly (a bare replica names no replica itself)
            replica = (
                resp.headers.get("X-Nm03-Replica")
                or urllib.parse.urlsplit(url).netloc
            )
            # result-tier verdict (ISSUE 19): hit | miss | fill, absent
            # when neither tier is enabled on the serving side
            cache_state = resp.headers.get("X-Nm03-Cache")
            hops = None
            z_shards = gang_wait = device_s = None
            try:
                payload = json.loads(data)
                if isinstance(payload, dict):
                    replica = payload.get("replica") or replica
                    hops = payload.get("replica_hops")
                    # whole-volume truth fields (ISSUE 15): present only
                    # on /v1/segment-volume responses
                    z_shards = payload.get("z_shards")
                    gang_wait = payload.get("gang_wait_s")
                    # prorated device cost (ISSUE 16)
                    device_s = payload.get("device_seconds")
            except (json.JSONDecodeError, UnicodeDecodeError):
                pass
            result.record(
                "ok", time.monotonic() - t0, batch_size=bs, sent_id=req_id,
                echoed_id=echoed, queue_wait_s=qw, lane=lane,
                replica=replica, replica_hops=hops,
                z_shards=z_shards, gang_wait_s=gang_wait,
                device_s=device_s, cache_state=cache_state,
            )
    except urllib.error.HTTPError as e:
        echoed = e.headers.get("X-Nm03-Request-Id", "") if e.headers else ""
        e.read()
        status = {503: "shed", 504: "timeout"}.get(e.code, f"http_{e.code}")
        result.record(status, time.monotonic() - t0, error=f"HTTP {e.code}",
                      sent_id=req_id, echoed_id=echoed)
    except Exception as e:  # noqa: BLE001 — a load test records, never dies
        result.record("error", time.monotonic() - t0, error=str(e),
                      sent_id=req_id)


def run_load(
    url,
    payloads,
    n_requests: int,
    concurrency: int,
    rate_rps: float,
    timeout_s: float,
    result: Optional[LoadResult] = None,
) -> dict:
    """Drive the load; returns the summary dict.

    ``url`` is one endpoint or a list of them (``--targets`` multi-target
    mode, ISSUE 13): request *i* goes to ``urls[i % len(urls)]`` — an
    even spread whether the targets are one fleet front-end or the
    replicas driven directly. Every request carries a unique
    ``X-Nm03-Request-Id`` (``lg-<run>-<n>``) that the server honors as
    the trace id and echoes back — the handle that joins a loadgen
    record to its server-side span tree (``nm03-trace``) and
    flight-recorder entries.
    """
    urls = [url] if isinstance(url, str) else list(url)
    result = result if result is not None else LoadResult()
    run_tag = uuid.uuid4().hex[:6]

    def req_id(i: int) -> str:
        return f"lg-{run_tag}-{i:06d}"

    t_start = time.monotonic()
    if rate_rps and rate_rps > 0:
        # open loop: fixed schedule, one thread per in-flight request —
        # send times never wait on responses, so queue growth is visible
        threads = []
        interval = 1.0 / rate_rps
        for i in range(n_requests):
            target = t_start + i * interval
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            body, headers = payloads[i % len(payloads)]
            t = threading.Thread(
                target=_one_request,
                args=(urls[i % len(urls)], body, headers, timeout_s, result,
                      req_id(i)),
                daemon=True,
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=timeout_s + 5)
        mode = f"open_loop@{rate_rps}rps"
    else:
        # closed loop: `concurrency` workers pulling a shared counter
        counter = iter(range(n_requests))
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    i = next(counter, None)
                if i is None:
                    return
                body, headers = payloads[i % len(payloads)]
                _one_request(urls[i % len(urls)], body, headers, timeout_s,
                             result, req_id(i))

        workers = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(max(1, concurrency))
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=n_requests * (timeout_s + 5))
        mode = f"closed_loop@c{concurrency}"
    return result.summary(time.monotonic() - t_start, mode)


def probe_server_topology(url: str, timeout_s: float = 5.0) -> dict:
    """Best-effort ``/readyz`` probe for the serving topology fields.

    Returns ``{lanes, mesh_shape, buckets, degraded}`` (values None when
    the server is unreachable or predates the fleet fields). The body is
    parsed whatever the status code — a draining or degraded server still
    reports its shape, and the loadgen record must carry the topology the
    measurement actually ran against (the bench-evidence honesty contract,
    extended to serving: a p99 from one lane must not masquerade as an
    8-chip number).
    """
    out = {
        "lanes": None, "mesh_shape": None, "buckets": None, "degraded": None,
        "capacity": None, "lanes_quarantined": None,
        "is_fleet": False, "replicas": None, "replicas_ready": None,
        "replicas_ejected": None,
    }
    req = urllib.request.Request(f"{url}/readyz", method="GET")
    try:
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:  # 503 still carries the payload
            body = e.read()
        st = json.loads(body or b"{}")
    except Exception:  # noqa: BLE001 — a probe failure must not fail the run
        return out
    lanes = (st.get("lanes") or {}).get("count")
    out["lanes"] = lanes
    out["mesh_shape"] = st.get("mesh_shape")
    out["buckets"] = st.get("buckets")
    out["degraded"] = st.get("degraded")
    # partial-capacity fields (ISSUE 8): the healthy-lane fraction and the
    # quarantined count a chaos run's plateau is explained by
    out["capacity"] = st.get("capacity")
    out["lanes_quarantined"] = (st.get("lanes") or {}).get("quarantined")
    # fleet front-end fields (ISSUE 13): when the probed URL is an
    # nm03-fleet router, `capacity` above is the ROUTED fraction and the
    # replicas block explains a chaos run's plateau one level up
    reps = st.get("replicas")
    if isinstance(reps, dict):
        out["is_fleet"] = True
        out["replicas"] = reps.get("count")
        out["replicas_ready"] = reps.get("ready")
        out["replicas_ejected"] = reps.get("ejected")
    return out


def probe_server_efficiency(url: str, timeout_s: float = 5.0) -> dict:
    """Best-effort saturation read from ``/metrics.json`` (ISSUE 10).

    Returns ``{busy_fraction, padding_waste_ratio, mfu, batches_total}``
    (Nones when unreachable or the server predates the saturation layer).
    The scrape itself refreshes the server's sliding-window gauges, so a
    poll DURING the run reads live utilization, not a stale publish.
    """
    out = {
        "busy_fraction": None, "padding_waste_ratio": None, "mfu": None,
        "batches_total": None,
    }
    req = urllib.request.Request(f"{url}/metrics.json", method="GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            snap = json.loads(resp.read())
    except Exception:  # noqa: BLE001 — a probe failure must not fail the run
        return out
    batches = 0.0
    for rec in snap.get("metrics", []):
        name, value = rec.get("name"), rec.get("value")
        if not isinstance(value, (int, float)):
            continue
        if rec.get("type") == "gauge" and not rec.get("labels"):
            if name == SERVING_BUSY_FRACTION:
                out["busy_fraction"] = float(value)
            elif name == SERVING_PADDING_WASTE_RATIO:
                out["padding_waste_ratio"] = float(value)
            elif name == SERVING_MFU:
                out["mfu"] = float(value)
        elif rec.get("type") == "counter" and name == SERVING_BATCHES_TOTAL:
            batches += float(value)
            out["batches_total"] = batches
    return out


class CapacityWatch:
    """Background ``/readyz`` + ``/metrics.json`` poller for a load run.

    A single post-run probe would miss a quarantine that probation already
    healed; polling during the run records the partial-capacity PLATEAU a
    chaos drill's throughput dip is explained by —
    ``lanes_quarantined_observed`` is the peak quarantined count and
    ``capacity_min_observed`` the floor the fleet served at. The
    efficiency join (ISSUE 10): ``busy_fraction_min_observed`` is the
    utilization floor once traffic began (samples before the first device
    batch are skipped — a cold fleet's honest 0.0 would say nothing about
    the run), ``padding_waste_max_observed``/``mfu_max_observed`` the
    worst padding and best flops utilization seen live.
    """

    def __init__(self, url: str, interval_s: float = 0.5):
        self.url = url
        self.interval_s = interval_s
        # written by the poller thread, read by main after stop(): the
        # lock (not the join fence alone) keeps start()'s inline sample,
        # the poller, and stop()'s final sample coherent
        self._lock = threading.Lock()
        self.max_quarantined: Optional[int] = None
        self.min_capacity: Optional[float] = None
        self.min_busy: Optional[float] = None
        self.max_padding: Optional[float] = None
        self.max_mfu: Optional[float] = None
        # fleet-level floors (ISSUE 13): only move when the watched URL
        # is an nm03-fleet front-end (its /readyz carries a replicas
        # block) — the ⅔ plateau a kill-a-replica drill is read from
        self.min_fleet_capacity: Optional[float] = None
        self.max_replicas_ejected: Optional[int] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="nm03-loadgen-capwatch", daemon=True
        )

    def _sample(self) -> None:
        topo = probe_server_topology(self.url, timeout_s=2.0)
        eff = probe_server_efficiency(self.url, timeout_s=2.0)
        q, c = topo["lanes_quarantined"], topo["capacity"]
        with self._lock:
            if q is not None:
                self.max_quarantined = max(self.max_quarantined or 0, int(q))
            if c is not None:
                self.min_capacity = (
                    float(c) if self.min_capacity is None
                    else min(self.min_capacity, float(c))
                )
            busy = eff["busy_fraction"]
            if busy is not None and (eff["batches_total"] or 0) > 0:
                self.min_busy = (
                    busy if self.min_busy is None else min(self.min_busy, busy)
                )
            if eff["padding_waste_ratio"] is not None:
                self.max_padding = max(
                    self.max_padding or 0.0, eff["padding_waste_ratio"]
                )
            if eff["mfu"] is not None:
                self.max_mfu = max(self.max_mfu or 0.0, eff["mfu"])
            if topo["is_fleet"]:
                if c is not None:
                    self.min_fleet_capacity = (
                        float(c) if self.min_fleet_capacity is None
                        else min(self.min_fleet_capacity, float(c))
                    )
                if topo["replicas_ejected"] is not None:
                    self.max_replicas_ejected = max(
                        self.max_replicas_ejected or 0,
                        int(topo["replicas_ejected"]),
                    )

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._sample()

    def start(self) -> "CapacityWatch":
        self._sample()  # one guaranteed sample even on a very short run
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._sample()  # the post-run view (reinstated fleets read 0 here)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-loadgen", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument(
        "--url", default="http://127.0.0.1:8077", help="server base URL"
    )
    p.add_argument(
        "--targets", default=None, metavar="URL[,URL...]",
        help="multi-target mode (ISSUE 13): comma list of base URLs — an "
        "nm03-fleet front-end or replicas driven directly; request i goes "
        "to targets[i %% n] and the summary gains replicas_observed / "
        "failovers_observed / fleet_capacity_min_observed. Overrides --url",
    )
    p.add_argument("--requests", type=int, default=100, help="total requests")
    p.add_argument(
        "--concurrency", type=int, default=8,
        help="closed-loop workers (ignored with --rate)",
    )
    p.add_argument(
        "--rate", type=float, default=0.0, metavar="RPS",
        help="open-loop arrival rate; 0 = closed loop",
    )
    p.add_argument(
        "--mode", choices=["mask", "jpeg"], default="mask",
        help="response payload: mask summary (cheap; throughput probe) or "
        "the full JPEG pair (the end-user path)",
    )
    p.add_argument("--height", type=int, default=128, help="phantom slice height")
    p.add_argument("--width", type=int, default=128, help="phantom slice width")
    p.add_argument(
        "--volume", action="store_true",
        help="whole-study mode (ISSUE 15): POST synthetic multi-slice "
        "studies to /v1/segment-volume instead of slices to /v1/segment; "
        "the summary gains a `volume` block (per-request z-shard counts "
        "and the gang-wait distribution from the response payload)",
    )
    p.add_argument(
        "--volume-depth", type=int, default=8, metavar="D",
        help="planes per synthetic study in --volume mode (must fit the "
        "server's --volume-depth-buckets)",
    )
    p.add_argument(
        "--dicom", action="store_true",
        help="send real Part-10 DICOM bytes (full parser path) instead of "
        "raw float32 arrays",
    )
    p.add_argument(
        "--distinct", type=int, default=4, help="distinct pre-built payloads"
    )
    p.add_argument(
        "--zipf", type=float, default=0.0, metavar="S",
        help="zipfian study-reuse replay (ISSUE 19): draw each request's "
        "payload with P(rank r) ∝ 1/r^S over --keyspace distinct studies "
        "(S≈1.1 is a realistic hot-study skew; 0 disables) — the mode "
        "that exercises the result tier; the summary gains "
        "cache_hit_ratio and the hit/miss latency and device-seconds "
        "split",
    )
    p.add_argument(
        "--keyspace", type=int, default=32, metavar="N",
        help="distinct synthetic studies the --zipf draw ranges over "
        "(replaces --distinct in zipf mode)",
    )
    p.add_argument("--timeout-s", type=float, default=30.0, help="per-request timeout")
    p.add_argument(
        "--warmup", type=int, default=4,
        help="unmeasured warmup requests before the run",
    )
    p.add_argument(
        "--results-json", default=None,
        help="write the summary JSON here (the serving evidence artifact)",
    )
    p.add_argument(
        "--self-serve", action="store_true",
        help="bring up an in-process server on an ephemeral port first "
        "(zero-setup smoke; tier-1 safe with small --requests on "
        "JAX_PLATFORMS=cpu)",
    )
    p.add_argument(
        "--self-serve-args", default="",
        help="extra nm03-serve flags for --self-serve, space-separated "
        '(e.g. "--canvas 128 --max-wait-ms 25")',
    )
    p.add_argument(
        "--expect-slo", default=None, metavar="SPEC",
        help="gate the run against a client-side SLO (ISSUE 14): "
        "'availability=99.5,p99_ms=500' (either key optional) — exit "
        "non-zero when the observed ok-fraction falls below the "
        "availability or the client p99 exceeds the target; the verdict "
        "rides the summary as `slo_gate`",
    )
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    expect_slo = None
    if args.expect_slo:
        try:
            expect_slo = parse_slo_spec(args.expect_slo)
        except ValueError as e:
            parser.error(str(e))
    httpd = app = None
    url = args.url
    if args.self_serve:
        from nm03_capstone_project_tpu.serving import server as serving_server

        serve_args = serving_server.build_parser().parse_args(
            ["--device", "cpu", *args.self_serve_args.split()]
        )
        from nm03_capstone_project_tpu.cli.common import apply_device_env

        apply_device_env("cpu")
        app = serving_server.app_from_args(serve_args)
        httpd, _, port = serving_server.serve_in_thread(app)
        url = f"http://127.0.0.1:{port}"
        print(f"loadgen: self-serve listening on {url}", flush=True)

    if args.targets:
        # multi-target mode (ISSUE 13): spread requests over the list; the
        # capacity watch and the topology probe read the FIRST target
        # (point it at the fleet front-end to watch the routed capacity)
        bases = [t.strip().rstrip("/") for t in args.targets.split(",")
                 if t.strip()]
        if not bases:
            print("loadgen: --targets needs at least one URL", flush=True)
            return 2
        url = bases[0]
    else:
        bases = [url]
    # zipf replay mode (ISSUE 19): the keyspace replaces --distinct and
    # the payload list becomes a pre-drawn per-request schedule
    zipf_on = args.zipf and args.zipf > 0
    n_distinct = max(1, args.keyspace) if zipf_on else args.distinct
    if args.volume:
        # whole-study mode: the summary payload (no mask bytes) keeps the
        # wire cheap — the gates read z_shards/gang_wait_s, not the mask
        endpoints = [f"{b}/v1/segment-volume?output=summary" for b in bases]
        payloads = _make_volume_payloads(
            args.volume_depth, args.height, args.width, n_distinct,
            args.dicom,
        )
    else:
        endpoints = [f"{b}/v1/segment?output={args.mode}" for b in bases]
        payloads = _make_payloads(
            args.height, args.width, n_distinct, args.dicom
        )
    if zipf_on:
        payloads = _zipf_schedule(payloads, args.requests, args.zipf)
    endpoint = endpoints[0]
    if args.warmup > 0:
        warm = LoadResult()  # discarded: compile/cache effects stay out
        run_load(endpoints, payloads, args.warmup, min(args.warmup, 4), 0.0,
                 args.timeout_s, warm)
    result = LoadResult()
    # poll /readyz through the run: a mid-run quarantine that probation
    # heals before the final probe must still land in the summary
    watch = CapacityWatch(url).start()
    summary = run_load(
        endpoints, payloads, args.requests, args.concurrency, args.rate,
        args.timeout_s, result,
    )
    watch.stop()
    summary["endpoint"] = endpoint
    if args.targets:
        summary["targets"] = bases
    if zipf_on:
        summary["zipf"] = {"s": args.zipf, "keyspace": n_distinct}
    # serving topology alongside the numbers (mesh_shape/lanes ride next to
    # the drivers' backend_requested/backend_actual honesty pair): probed
    # from the live server so the record describes what actually served
    topo = probe_server_topology(url, timeout_s=args.timeout_s)
    summary["lanes"] = topo["lanes"]
    summary["mesh_shape"] = topo["mesh_shape"]
    # the partial-capacity evidence (ISSUE 8): peak quarantined lanes and
    # the capacity floor observed DURING the run, plus the final fraction
    summary["lanes_quarantined_observed"] = watch.max_quarantined
    summary["capacity_min_observed"] = watch.min_capacity
    summary["capacity"] = topo["capacity"]
    # server-side efficiency joined to the client-side numbers (ISSUE 10):
    # a p99 means something different at 20% lane utilization than at 95%
    summary["busy_fraction_min_observed"] = watch.min_busy
    summary["padding_waste_max_observed"] = watch.max_padding
    summary["mfu_max_observed"] = watch.max_mfu
    # fleet-level evidence (ISSUE 13): the routed-capacity floor and the
    # peak ejected count observed DURING the run — the numbers that
    # explain a kill-a-replica drill's throughput dip (None when the
    # watched URL is not an nm03-fleet front-end)
    summary["fleet_capacity_min_observed"] = watch.min_fleet_capacity
    summary["replicas_ejected_max_observed"] = watch.max_replicas_ejected
    summary["replicas"] = topo["replicas"]
    summary["replicas_ready"] = topo["replicas_ready"]
    # the client-side SLO gate (ISSUE 14): judged on what clients SAW —
    # the verdict rides the artifact whether or not it passes
    if expect_slo is not None:
        summary["slo_gate"] = evaluate_slo(summary, expect_slo)
    if args.self_serve and app is not None:
        app.begin_drain(reason="loadgen_done")
        httpd.shutdown()
        httpd.server_close()
        app.close(status="ok")
        summary["server_status"] = app.status()
    if args.results_json:
        from nm03_capstone_project_tpu.utils.timing import write_results_json

        # per-request records (sent/echoed trace id, server-reported
        # queue-wait and lane) ride the artifact, not stdout
        write_results_json(
            args.results_json, {**summary, "requests": result.requests}
        )
    print(json.dumps(summary, indent=2))
    lat, qw = summary["latency_ms"], summary["queue_wait_ms"]
    cap = summary["capacity_min_observed"]

    def _pct(v):
        # 3 significant digits, not a fixed point: 8 virtual CPU lanes
        # sharing one core legitimately sit at 0.04% busy, and "0.0%"
        # would misread as "never worked"
        return "?" if v is None else f"{v * 100:.3g}%"

    fleet_cap = summary["fleet_capacity_min_observed"]
    vol_cols = ""
    if summary.get("volume"):
        vb = summary["volume"]
        vol_cols = (
            f"zshards={vb['zshards_observed']} "
            f"gang_wait_p95={vb['gang_wait_ms']['p95']}ms "
        )
    ds_cols = ""
    if summary.get("device_seconds_ms"):
        db = summary["device_seconds_ms"]
        ds_cols = (
            f"device_seconds_p50={db['p50']}ms "
            f"device_seconds_p95={db['p95']}ms "
        )
    cache_cols = ""
    if summary.get("cache_hit_ratio") is not None:
        # the result-tier columns (ISSUE 19): printed whenever any
        # response carried an X-Nm03-Cache verdict
        cb = summary["cache"]
        cache_cols = (
            f"cache_hit_ratio={summary['cache_hit_ratio']} "
            f"hit_p50={cb['hit_latency_ms']['p50']}ms "
            f"miss_p50={cb['miss_latency_ms']['p50']}ms "
        )
    fleet_cols = ""
    if summary.get("targets") or summary["replicas"] is not None:
        # the fleet columns (ISSUE 13): printed on --targets runs and
        # whenever the watched /readyz was a fleet front-end
        fleet_cols = (
            f"replicas={len(summary['replicas_observed']) or '?'} "
            f"failovers={summary['failovers_observed']} "
            f"fleet_cap_min={'?' if fleet_cap is None else fleet_cap} "
        )
    print(
        f"loadgen: ok={summary['requests_ok']}/{summary['requests_total']} "
        f"p50={lat['p50']}ms p95={lat['p95']}ms "
        f"queue_wait_p95={qw['p95']}ms "
        f"lanes={summary['lanes_observed'] or '{}'} "
        f"quarantined_max={summary['lanes_quarantined_observed']} "
        f"capacity_min={'?' if cap is None else cap} "
        f"busy_min={_pct(summary['busy_fraction_min_observed'])} "
        f"padding_max={_pct(summary['padding_waste_max_observed'])} "
        f"mfu_max={_pct(summary['mfu_max_observed'])} "
        f"{ds_cols}"
        f"{cache_cols}"
        f"{vol_cols}"
        f"{fleet_cols}"
        f"echo_mismatch={summary['trace_echo_mismatches']}",
        flush=True,
    )
    if expect_slo is not None:
        gate = summary["slo_gate"]
        detail = "  ".join(
            f"{k}: {'ok' if c['pass'] else 'FAIL'} "
            f"(want {c.get('expected_pct', c.get('expected_ms'))}, "
            f"got {c.get('observed_pct', c.get('observed_ms'))})"
            for k, c in sorted(gate["checks"].items())
        )
        print(
            f"loadgen: --expect-slo "
            f"{'PASSED' if gate['pass'] else 'FAILED'}  {detail}",
            flush=True,
        )
        if not gate["pass"]:
            return 1
    # exit non-zero when nothing succeeded: a load test that measured no
    # requests is a failed measurement, whatever the server said
    return 0 if summary["requests_ok"] > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
