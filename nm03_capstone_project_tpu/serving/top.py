"""``nm03-top``: a live one-screen saturation view of a serving replica.

``top`` for the fleet: polls a running ``nm03-serve``'s ``/metrics.json``
and ``/readyz`` and renders a refreshing console view of *how much of the
hardware the replica is using* — per-lane state + busy fraction + MFU,
queue depth, window occupancy, padding waste, and request/shed/requeue
RATES computed from counter deltas between polls (ISSUE 10). Where
``nm03-loadgen`` answers "what latency did clients see", this answers the
operator's capacity question: "are my chips actually working?"
(docs/OPERATIONS.md, "Capacity planning").

Pure stdlib, read-only — it issues only GETs, so pointing it at a
production replica is always safe. ``--once`` prints a single view and
exits (``--format json`` makes that machine-readable: the subprocess
drills assert nm03-top renders the same numbers the gauges carry).
``--fleet`` points it at an ``nm03-fleet`` front-end instead (ISSUE 13):
it reads the router's per-replica table and aggregates every replica's
``/metrics.json`` + ``/readyz`` into one screen — per-replica
state/capacity/busy/MFU rows plus fleet routed/failover/shed rates
(schema ``nm03.fleettop.v1``).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

from nm03_capstone_project_tpu.obs.metrics import (
    FLEET_FAILOVERS_TOTAL,
    FLEET_REQUESTS_ROUTED_TOTAL,
    FLEET_SHED_TOTAL,
    INGEST_DECODE_QUEUE_DEPTH,
    INGEST_RING_OCCUPANCY_RATIO,
    INGEST_UPLOAD_OVERLAP_RATIO,
    SLO_BURN_RATE_FAST,
    SLO_BURN_RATE_SLOW,
    SLO_ERROR_BUDGET_REMAINING,
)
from nm03_capstone_project_tpu.serving.metrics import (
    SERVING_BUSY_FRACTION,
    SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN,
    SERVING_DEVICE_TIME_SHARE,
    SERVING_LANE_BUSY_FRACTION,
    SERVING_LANE_MFU,
    SERVING_MFU,
    SERVING_PADDING_WASTE_RATIO,
    SERVING_REQUESTS_TOTAL,
    SERVING_REQUEUES_TOTAL,
    SERVING_RESULT_CACHE_BYTES,
    SERVING_RESULT_CACHE_EVICT_TOTAL,
    SERVING_RESULT_CACHE_HIT_TOTAL,
    SERVING_RESULT_CACHE_MISS_TOTAL,
    SERVING_SHED_TOTAL,
    SERVING_WINDOW_OCCUPANCY_RATIO,
)

CLEAR = "\x1b[2J\x1b[H"  # clear screen + home (ANSI)


class Sample:
    """One poll: parsed metrics snapshot + the /readyz status payload."""

    def __init__(self, metrics: dict, readyz: dict, ts: float):
        self.ts = ts
        self.readyz = readyz
        # gauges: (name, sorted label items) -> value; counters: summed by
        # name (rates never need label splits) and kept per-label for lanes
        self.gauges: Dict[Tuple[str, tuple], float] = {}
        self.counter_totals: Dict[str, float] = {}
        for rec in metrics.get("metrics", []):
            name, kind = rec.get("name"), rec.get("type")
            labels = tuple(sorted((rec.get("labels") or {}).items()))
            value = rec.get("value")
            if not isinstance(value, (int, float)):
                continue
            if kind == "gauge":
                self.gauges[(name, labels)] = float(value)
            elif kind == "counter":
                self.counter_totals[name] = (
                    self.counter_totals.get(name, 0.0) + float(value)
                )

    def gauge(self, name: str, **labels) -> Optional[float]:
        return self.gauges.get((name, tuple(sorted(labels.items()))))


def _pie_block(cur: "Sample") -> Optional[dict]:
    """The device-time pie (ISSUE 16), or None when the scraped process
    hasn't taken a profile sample yet (sampler off, or first cadence tick
    still pending) — top shows the ledger's gauges, it never profiles."""
    shares = {
        labels[0][1]: v
        for (name, labels), v in cur.gauges.items()
        if name == SERVING_DEVICE_TIME_SHARE and labels
    }
    if not shares:
        return None
    return {k: round(v, 4) for k, v in shares.items()}


def _pie_line(
    shares: Optional[dict], ds_per_req: Optional[float]
) -> Optional[str]:
    if shares is None and ds_per_req is None:
        return None
    parts = ["device pie"]
    for stage, v in sorted(
        (shares or {}).items(), key=lambda kv: -kv[1]
    ):
        parts.append(f"{stage} {_fmt(v, pct=True).strip()}")
    if ds_per_req is not None:
        parts.append(f"ds/req {ds_per_req * 1000:.3g}ms")
    return "   ".join(parts)


def _cache_block(cur: "Sample", prev: Optional["Sample"]) -> Optional[dict]:
    """The result-tier row (ISSUE 19), or None when the scraped process
    runs no tier — the bytes gauge exists (at 0) from startup on any
    tier-enabled process, so its absence IS the disabled signal; top
    renders the gauges, it never guesses."""
    bytes_g = cur.gauge(SERVING_RESULT_CACHE_BYTES)
    if bytes_g is None:
        return None
    hits = cur.counter_totals.get(SERVING_RESULT_CACHE_HIT_TOTAL, 0.0)
    misses = cur.counter_totals.get(SERVING_RESULT_CACHE_MISS_TOTAL, 0.0)
    lookups = hits + misses
    return {
        "bytes": int(bytes_g),
        "hits": int(hits),
        "misses": int(misses),
        "hit_ratio": round(hits / lookups, 4) if lookups else None,
        "hit_per_s": _rate(cur, prev, SERVING_RESULT_CACHE_HIT_TOTAL),
        "evict_per_s": _rate(cur, prev, SERVING_RESULT_CACHE_EVICT_TOTAL),
    }


def _cache_line(cache: Optional[dict]) -> Optional[str]:
    if cache is None:
        return None

    def _r(v):
        return "-" if v is None else v

    hr = cache["hit_ratio"]
    return (
        f"result cache {cache['bytes']}B   "
        f"hit ratio {'-' if hr is None else _fmt(hr, pct=True).strip()} "
        f"({cache['hits']}/{cache['hits'] + cache['misses']})   "
        f"hit/s {_r(cache['hit_per_s'])}   "
        f"evict/s {_r(cache['evict_per_s'])}"
    )


def _slo_block(cur: "Sample") -> Optional[dict]:
    """The SLO row's numbers (ISSUE 14), or None when no objective was
    declared on the scraped process — top shows the gauges, it never
    recomputes (or invents) an objective."""
    budget = cur.gauge(SLO_ERROR_BUDGET_REMAINING)
    if budget is None:
        return None
    return {
        "error_budget_remaining": budget,
        "burn_rate_fast": cur.gauge(SLO_BURN_RATE_FAST),
        "burn_rate_slow": cur.gauge(SLO_BURN_RATE_SLOW),
    }


def _slo_line(slo: Optional[dict]) -> Optional[str]:
    if slo is None:
        return None

    def _n(v):
        return "-" if v is None else f"{v:.3g}"

    return (
        f"slo burn fast {_n(slo['burn_rate_fast'])}   "
        f"slow {_n(slo['burn_rate_slow'])}   "
        f"budget {_fmt(slo['error_budget_remaining'], pct=True).strip()} left"
    )


def fetch_sample(url: str, timeout_s: float) -> Sample:
    """GET /metrics.json + /readyz (any status; a 503 body still carries
    the fleet payload). Raises URLError/OSError when the server is gone."""
    with urllib.request.urlopen(
        f"{url}/metrics.json", timeout=timeout_s
    ) as resp:
        metrics = json.loads(resp.read())
    try:
        with urllib.request.urlopen(f"{url}/readyz", timeout=timeout_s) as r:
            readyz = json.loads(r.read())
    except urllib.error.HTTPError as e:  # 503 carries the payload too
        try:
            readyz = json.loads(e.read() or b"{}")
        except json.JSONDecodeError:
            readyz = {}
    return Sample(metrics, readyz, time.monotonic())


def _rate(cur: Sample, prev: Optional[Sample], name: str) -> Optional[float]:
    if prev is None:
        return None
    dt = cur.ts - prev.ts
    if dt <= 0:
        return None
    return round(
        max(
            cur.counter_totals.get(name, 0.0)
            - prev.counter_totals.get(name, 0.0),
            0.0,
        )
        / dt,
        2,
    )


def build_view(cur: Sample, prev: Optional[Sample] = None) -> dict:
    """One renderable/JSON-able view from a poll (+ rates vs the prior).

    Every number is sourced from the same registry the ``/metrics``
    scrape and the ``check_telemetry`` gates read — nm03-top shows the
    gauges, it never recomputes them.
    """
    st = cur.readyz or {}
    lanes_info = st.get("lanes") or {}
    rows = []
    for lane_row in lanes_info.get("per_lane") or []:
        lane = lane_row.get("lane")
        busy = cur.gauge(SERVING_LANE_BUSY_FRACTION, lane=str(lane))
        mfu = cur.gauge(SERVING_LANE_MFU, lane=str(lane))
        rows.append(
            {
                "lane": lane,
                "state": lane_row.get("state", "?"),
                "busy_fraction": busy,
                "mfu": mfu,
                "inflight": lane_row.get("inflight"),
                "batches": lane_row.get("batches"),
                "quarantines": lane_row.get("quarantines"),
            }
        )
    return {
        "schema": "nm03.top.v1",
        "ready": st.get("ready"),
        "draining": st.get("draining"),
        "degraded": st.get("degraded"),
        "capacity": st.get("capacity"),
        "uptime_s": st.get("uptime_s"),
        "queue_depth": st.get("queue_depth"),
        "queue_capacity": st.get("queue_capacity"),
        "lanes": rows,
        "busy_fraction": cur.gauge(SERVING_BUSY_FRACTION),
        "mfu": cur.gauge(SERVING_MFU),
        "padding_waste_ratio": cur.gauge(SERVING_PADDING_WASTE_RATIO),
        "window_occupancy_ratio": cur.gauge(SERVING_WINDOW_OCCUPANCY_RATIO),
        # streaming-ingest column (ISSUE 11): present whenever the scraped
        # snapshot carries the ingest_* gauges (a process feeding the chip
        # through ingest/), null otherwise — nm03-top renders what the
        # registry knows, it never guesses
        "ingest": (
            {
                "ring_occupancy_ratio": cur.gauge(INGEST_RING_OCCUPANCY_RATIO),
                "decode_queue_depth": cur.gauge(INGEST_DECODE_QUEUE_DEPTH),
                "upload_overlap_ratio": cur.gauge(INGEST_UPLOAD_OVERLAP_RATIO),
            }
            if cur.gauge(INGEST_RING_OCCUPANCY_RATIO) is not None
            else None
        ),
        # the SLO row (ISSUE 14): burn rates + budget when the scraped
        # process declared an objective, null otherwise
        "slo": _slo_block(cur),
        # the result-tier row (ISSUE 19): bytes/hit-ratio/evict rate from
        # the serving_result_cache_* series, null when the tier is off
        "result_cache": _cache_block(cur, prev),
        # the device-time pie (ISSUE 16): per-stage shares of sampled
        # device time + mean prorated device-seconds per request — null
        # until the ledger's profile sampler has reduced a capture
        "device_time_share": _pie_block(cur),
        "device_seconds_per_request": cur.gauge(
            SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN
        ),
        # rates from counter deltas between polls (null on the first poll
        # and in --once mode: one sample has no delta)
        "rates_per_s": {
            "requests": _rate(cur, prev, SERVING_REQUESTS_TOTAL),
            "shed": _rate(cur, prev, SERVING_SHED_TOTAL),
            "requeues": _rate(cur, prev, SERVING_REQUEUES_TOTAL),
        },
    }


def _fmt(v, pct: bool = False, width: int = 7) -> str:
    if v is None:
        return "-".rjust(width)
    if pct:
        # 3 significant digits: a virtual-CPU lane's honest 0.04% busy
        # (or a 3e-4% MFU) must not render as a misleading "0.0%"
        return f"{v * 100:.3g}%".rjust(width)
    return f"{v:.6g}".rjust(width)


def render_text(view: dict, url: str) -> str:
    """The one-screen console rendering of a view."""
    state = (
        "DRAINING" if view.get("draining")
        else "DEGRADED" if view.get("degraded")
        else "ready" if view.get("ready")
        else "not-ready"
    )
    rates = view["rates_per_s"]
    lines = [
        f"nm03-top — {url}   [{state}]   uptime "
        f"{view.get('uptime_s') if view.get('uptime_s') is not None else '?'}s",
        (
            f"queue {view.get('queue_depth')}/{view.get('queue_capacity')}   "
            f"capacity {_fmt(view.get('capacity'), pct=True).strip()}   "
            f"busy {_fmt(view.get('busy_fraction'), pct=True).strip()}   "
            f"mfu {_fmt(view.get('mfu'), pct=True).strip()}"
        ),
        (
            f"occupancy {_fmt(view.get('window_occupancy_ratio'), pct=True).strip()}   "
            f"padding waste "
            f"{_fmt(view.get('padding_waste_ratio'), pct=True).strip()}   "
            f"req/s {rates['requests'] if rates['requests'] is not None else '-'}   "
            f"shed/s {rates['shed'] if rates['shed'] is not None else '-'}   "
            f"requeue/s {rates['requeues'] if rates['requeues'] is not None else '-'}"
        ),
        "",
        f"{'lane':>4} {'state':<12} {'busy':>8} {'mfu':>8} "
        f"{'inflight':>8} {'batches':>8} {'quar':>5}",
    ]
    ing = view.get("ingest")
    if ing is not None:
        lines.insert(
            3,
            (
                f"ingest ring "
                f"{_fmt(ing['ring_occupancy_ratio'], pct=True).strip()}   "
                f"decode-q {ing['decode_queue_depth'] if ing['decode_queue_depth'] is not None else '-'}   "
                f"upload overlap "
                f"{_fmt(ing['upload_overlap_ratio'], pct=True).strip()}"
            ),
        )
    cache_line = _cache_line(view.get("result_cache"))
    if cache_line is not None:
        lines.insert(3, cache_line)
    pie_line = _pie_line(
        view.get("device_time_share"), view.get("device_seconds_per_request")
    )
    if pie_line is not None:
        lines.insert(3, pie_line)
    slo_line = _slo_line(view.get("slo"))
    if slo_line is not None:
        lines.insert(3, slo_line)
    for row in view["lanes"]:
        lines.append(
            f"{str(row['lane']):>4} {str(row['state']):<12} "
            f"{_fmt(row['busy_fraction'], pct=True, width=8)} "
            f"{_fmt(row['mfu'], pct=True, width=8)} "
            f"{str(row['inflight']):>8} {str(row['batches']):>8} "
            f"{str(row['quarantines']):>5}"
        )
    if not view["lanes"]:
        lines.append("  (no lanes resolved yet — server still warming?)")
    return "\n".join(lines)


# -- the fleet view (ISSUE 13) ----------------------------------------------


def fetch_fleet_sample(url: str, timeout_s: float):
    """One fleet poll: the router's /readyz table + its /metrics.json,
    plus a per-replica :class:`Sample` for every reachable replica.

    Returns ``(fleet_sample, {target: replica Sample or None})``. Raises
    when the FLEET itself is unreachable; an unreachable replica is a row
    with nulls — exactly what an ejected replica should look like.
    """
    fleet = fetch_sample(url, timeout_s)
    per: Dict[str, Optional[Sample]] = {}
    table = (fleet.readyz.get("replicas") or {}).get("per_replica") or []
    for row in table:
        target = row.get("target")
        if not target:
            continue
        try:
            per[target] = fetch_sample(target, timeout_s)
        except Exception:  # noqa: BLE001 — a dead replica is a null row
            per[target] = None
    return fleet, per


def build_fleet_view(
    fleet: Sample,
    per: Dict[str, Optional[Sample]],
    prev_fleet: Optional[Sample] = None,
    prev_per: Optional[Dict[str, Optional[Sample]]] = None,
) -> dict:
    """One renderable/JSON-able aggregate of the whole fleet.

    Fleet-level numbers come from the router's own /readyz + fleet_*
    counters; each replica row aggregates that replica's /metrics.json
    (busy/MFU/queue) next to the router's verdict on it (state/cause) —
    the one-screen answer to "which replica is the outlier".
    """
    st = fleet.readyz or {}
    table = (st.get("replicas") or {}).get("per_replica") or []
    prev_per = prev_per or {}
    rows = []
    for entry in table:
        target = entry.get("target")
        s = per.get(target)
        ps = prev_per.get(target)
        r_ready = s.readyz if s is not None else {}
        rows.append({
            "replica": entry.get("replica"),
            "target": target,
            "state": entry.get("state", "?"),
            "cause": entry.get("cause"),
            "ejections": entry.get("ejections"),
            "capacity": entry.get("capacity"),
            "queue_depth": r_ready.get("queue_depth"),
            "lanes_ready": (r_ready.get("lanes") or {}).get("ready"),
            "busy_fraction": (
                s.gauge(SERVING_BUSY_FRACTION) if s is not None else None
            ),
            "mfu": s.gauge(SERVING_MFU) if s is not None else None,
            "device_seconds_per_request": (
                s.gauge(SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN)
                if s is not None else None
            ),
            "requests_per_s": (
                _rate(s, ps, SERVING_REQUESTS_TOTAL)
                if s is not None and ps is not None else None
            ),
            "id": (entry.get("identity") or {}).get("id"),
            "pid": (entry.get("identity") or {}).get("pid"),
        })
    # the fleet pie (ISSUE 16): each stage's share averaged across the
    # replicas that have sampled one — the fleet-wide "where do the
    # device-seconds go" answer, null until any replica has a pie
    pies = [p for p in (
        _pie_block(s) for s in per.values() if s is not None
    ) if p]
    fleet_pie: Optional[dict] = None
    if pies:
        stages = sorted({k for p in pies for k in p})
        fleet_pie = {
            st: round(
                sum(p.get(st, 0.0) for p in pies) / len(pies), 4
            )
            for st in stages
        }
    return {
        "schema": "nm03.fleettop.v1",
        "ready": st.get("ready"),
        "draining": st.get("draining"),
        "capacity": st.get("capacity"),
        "uptime_s": st.get("uptime_s"),
        "replicas_ready": (st.get("replicas") or {}).get("ready"),
        "replicas_ejected": (st.get("replicas") or {}).get("ejected"),
        # the fleet-level SLO row (ISSUE 14): the ROUTER's own burn
        # gauges — the whole-fleet verdict, not any one replica's
        "slo": _slo_block(fleet),
        # the ROUTER's own result tier (ISSUE 19): the front-end store
        # that answers repeats without a replica pick — null when off
        "result_cache": _cache_block(fleet, prev_fleet),
        "device_time_share": fleet_pie,
        "replicas": rows,
        "rates_per_s": {
            "routed": _rate(fleet, prev_fleet, FLEET_REQUESTS_ROUTED_TOTAL),
            "failovers": _rate(fleet, prev_fleet, FLEET_FAILOVERS_TOTAL),
            "shed": _rate(fleet, prev_fleet, FLEET_SHED_TOTAL),
        },
    }


def render_fleet_text(view: dict, url: str) -> str:
    """The one-screen console rendering of a fleet view."""
    state = (
        "DRAINING" if view.get("draining")
        else "ready" if view.get("ready")
        else "NOT-READY"
    )
    rates = view["rates_per_s"]

    def _r(k):
        return rates[k] if rates[k] is not None else "-"

    lines = [
        f"nm03-top — fleet {url}   [{state}]   uptime "
        f"{view.get('uptime_s') if view.get('uptime_s') is not None else '?'}s",
        (
            f"replicas {view.get('replicas_ready')}/"
            f"{(view.get('replicas_ready') or 0) + (view.get('replicas_ejected') or 0)} "
            f"ready   capacity {_fmt(view.get('capacity'), pct=True).strip()}   "
            f"routed/s {_r('routed')}   failover/s {_r('failovers')}   "
            f"shed/s {_r('shed')}"
        ),
        "",
        f"{'replica':<22} {'state':<10} {'cap':>6} {'lanes':>5} "
        f"{'queue':>5} {'busy':>8} {'mfu':>8} {'req/s':>7} "
        f"{'ds/req':>8} {'eject':>5}",
    ]
    cache_line = _cache_line(view.get("result_cache"))
    if cache_line is not None:
        lines.insert(2, cache_line)
    pie_line = _pie_line(view.get("device_time_share"), None)
    if pie_line is not None:
        lines.insert(2, pie_line)
    slo_line = _slo_line(view.get("slo"))
    if slo_line is not None:
        lines.insert(2, slo_line)
    for row in view["replicas"]:
        dsr = row["device_seconds_per_request"]
        dsr_s = "-" if dsr is None else f"{dsr * 1000:.3g}ms"
        lines.append(
            f"{str(row['replica']):<22} {str(row['state']):<10} "
            f"{_fmt(row['capacity'], pct=True, width=6)} "
            f"{str(row['lanes_ready'] if row['lanes_ready'] is not None else '-'):>5} "
            f"{str(row['queue_depth'] if row['queue_depth'] is not None else '-'):>5} "
            f"{_fmt(row['busy_fraction'], pct=True, width=8)} "
            f"{_fmt(row['mfu'], pct=True, width=8)} "
            f"{str(row['requests_per_s'] if row['requests_per_s'] is not None else '-'):>7} "
            f"{dsr_s:>8} "
            f"{str(row['ejections']):>5}"
        )
    if not view["replicas"]:
        lines.append("  (no replicas in the fleet table yet)")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-top", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument(
        "--url", default="http://127.0.0.1:8077", help="server base URL"
    )
    p.add_argument(
        "--fleet", action="store_true",
        help="treat --url as an nm03-fleet front-end: aggregate every "
        "replica's /metrics.json + /readyz behind it into one screen "
        "(per-replica state/capacity/busy/MFU + fleet routed/failover/"
        "shed rates; ISSUE 13)",
    )
    p.add_argument(
        "--interval-s", type=float, default=2.0,
        help="refresh period (each refresh is one /metrics.json + /readyz "
        "poll; rates are deltas over this period)",
    )
    p.add_argument(
        "--once", action="store_true",
        help="print one view and exit (rates are null: one sample has no "
        "delta)",
    )
    p.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="output format (json is the machine/CI interface)",
    )
    p.add_argument(
        "--timeout-s", type=float, default=5.0, help="per-poll HTTP timeout"
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.interval_s <= 0:
        print("nm03-top: --interval-s must be > 0", file=sys.stderr)
        return 2
    prev: Optional[Sample] = None
    prev_per: Optional[Dict[str, Optional[Sample]]] = None
    try:
        while True:
            try:
                if args.fleet:
                    cur, per = fetch_fleet_sample(args.url, args.timeout_s)
                else:
                    cur = fetch_sample(args.url, args.timeout_s)
            except Exception as e:  # noqa: BLE001 — unreachable server is the exit
                print(f"nm03-top: {args.url} unreachable: {e}", file=sys.stderr)
                return 2
            if args.fleet:
                view = build_fleet_view(cur, per, prev, prev_per)
            else:
                view = build_view(cur, prev)
            if args.format == "json":
                out = json.dumps(view, indent=None if args.once else 1)
                print(out, flush=True)
            else:
                screen = (
                    render_fleet_text(view, args.url) if args.fleet
                    else render_text(view, args.url)
                )
                if args.once:
                    print(screen, flush=True)
                else:
                    sys.stdout.write(CLEAR + screen + "\n")
                    sys.stdout.flush()
            if args.once:
                return 0
            prev = cur
            if args.fleet:
                prev_per = per
            time.sleep(args.interval_s)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
