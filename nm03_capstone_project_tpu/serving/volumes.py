"""Whole-volume multi-chip serving: one study, one mesh-wide request.

``POST /v1/segment-volume`` (ISSUE 15) makes "segment this entire study
in one online request" a served scenario instead of N client-stitched
slice calls — the OpenCLIPER thesis (PAPERS.md, arXiv:1807.11830) applied
to the request path: keep the study device-resident and amortize every
host round-trip over the whole volume. The compute is EXACTLY the batch
driver's z-sharded program (``nm03-volume --z-shard``): the same
shard_map'd halo-exchanged region-growing fixpoint
(:func:`~nm03_capstone_project_tpu.parallel.zshard.zshard_volume_callable`),
AOT-compiled per depth bucket through the compile hub — so the served
mask volume is bit-identical to a directly-driven run by construction,
and the persistent cache (PR 9) keeps the mesh executables warm across
restarts.

The scheduling construct this forces is the **gang lane**
(:class:`VolumeGang`): slice requests ride per-lane executables, but a
volume request needs EVERY healthy lane's chip at once. The gang owns

* its **own bounded admission queue** — volume traffic sheds on its own
  capacity, and bulk volumes can never occupy slice-admission slots (the
  admission-separation down-payment on ROADMAP item 4);
* the batcher's **gang gate**
  (:meth:`~nm03_capstone_project_tpu.serving.batcher.DynamicBatcher.gang_parked`):
  acquiring waits for the in-flight slice window and parks the lanes;
  the wait is the published ``serving_volume_gang_wait_seconds``;
* **fault-domain integration**: the mesh is built from the executor's
  *currently healthy* lanes, a mid-volume lane death re-meshes the retry
  onto the survivors (span ``volume_requeue``, the lane booked through
  the same quarantine machine slice traffic uses), and when no usable
  mesh remains the request sheds honestly with ``Retry-After`` — a wrong
  mask is never an outcome.

Depth buckets mirror the batch buckets: a study pads (with zero planes,
which segment empty — the same filler the driver uses for shard
divisibility) up to the smallest warm bucket, so the compile-shape set is
fixed at startup and online traffic never triggers a mesh recompile.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
from nm03_capstone_project_tpu.serving.executor import WarmExecutor
from nm03_capstone_project_tpu.serving.queue import AdmissionQueue
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("serving")

# depth buckets a study pads up into (one AOT mesh executable each);
# mirrors DEFAULT_BUCKETS' role for slices. 8 keeps the smallest volume
# cheap; 64 bounds the compile-shape set and the request body cap.
DEFAULT_VOLUME_DEPTH_BUCKETS: Tuple[int, ...] = (8, 16, 32)


class GangUnavailable(RuntimeError):
    """No usable mesh can serve this volume right now; shed with 503 +
    ``Retry-After`` (the server maps it). Raised instead of EVER returning
    a mask the gang cannot vouch for."""


@dataclass
class VolumeRequest:
    """One in-flight whole-volume request, admission to response.

    ``volume`` is the decoded host-side (depth, h, w) float32 stack,
    ``dims`` the true in-plane (h, w). The gang fills ``mask`` (cropped
    uint8 (depth, h, w)), ``converged``, ``z_shards``, ``gang_wait_s``
    (or ``error``) and sets ``done``.
    """

    request_id: str
    volume: object  # np.ndarray (depth, h, w) float32, raw intensities
    dims: tuple  # (h, w)
    depth: int
    t_admitted: float = field(default_factory=time.monotonic)
    trace: object = None  # obs.trace.TraceContext
    t_popped: float = 0.0  # stamped by AdmissionQueue.get_batch
    # filled by the gang
    mask: object = None  # np.ndarray (depth, h, w) uint8
    converged: bool = True
    z_shards: int = 0
    gang_wait_s: float = 0.0
    queue_wait_s: float = 0.0
    requeues: int = 0  # mesh rebuilds after a mid-volume lane death
    error: Optional[BaseException] = None
    done: threading.Event = field(default_factory=threading.Event)

    @property
    def trace_id(self) -> Optional[str]:
        return self.trace.trace_id if self.trace is not None else None

    def fail(self, exc: BaseException) -> None:
        # nm03-lint: disable=NM331 release ordering via the Event (ServeRequest.fail's contract)
        self.error = exc
        self.done.set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self.done.wait(timeout_s)


class VolumeGang:
    """The gang lane: one thread serving whole-volume requests mesh-wide.

    One consumer thread pops volume requests (strictly one at a time — a
    gang IS the whole mesh), parks the slice batcher through its gang
    gate, dispatches the z-sharded program over the healthy lanes'
    devices, and returns the lanes between volumes so interleaved slice
    traffic always gets a turn. Construction is backend-free; lanes
    resolve at :meth:`warmup` (call after the executor's own warmup).
    """

    def __init__(
        self,
        cfg: PipelineConfig,
        # typed so the lock-order analysis (NM42x) can trace the gang's
        # held-set through executor/batcher calls — the whole volume path
        # runs under gang_parked(), and every lock it reaches must be an
        # explained edge in the static may-hold graph
        executor: WarmExecutor,
        batcher: DynamicBatcher,
        obs=None,
        queue_capacity: int = 4,
        depth_buckets: Tuple[int, ...] = DEFAULT_VOLUME_DEPTH_BUCKETS,
        fault_plan=None,
        distributed: bool = False,
    ):
        buckets = tuple(int(b) for b in depth_buckets)
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"volume depth buckets must be strictly increasing, got "
                f"{depth_buckets}"
            )
        if any(b < 1 for b in buckets):
            raise ValueError(f"volume depth buckets must be >= 1, got {buckets}")
        self.cfg = cfg
        self.executor = executor
        self.batcher = batcher
        self.obs = obs
        self.depth_buckets = buckets
        self.fault_plan = fault_plan
        # --distributed-init (ROADMAP item 3 leftover): when this process
        # joined a jax.distributed job, the gang's mesh spans the GLOBAL
        # device set — a replica's volume mesh can cross processes the way
        # nm03-volume --z-shard --distributed does
        self.distributed = bool(distributed)
        self.queue = AdmissionQueue(queue_capacity)
        self._seq = itertools.count()
        self._warm_width = 0  # full-mesh z width pinned at warmup
        self._thread = threading.Thread(
            target=self._run, name="nm03-serve-gang", daemon=True
        )
        # nm03-lint: disable=NM331 owner-thread write before _thread.start(); the start() fence orders it
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    @property
    def max_depth(self) -> int:
        """The deepest study one request may carry (the largest bucket)."""
        return self.depth_buckets[-1]

    @property
    def z_shards(self) -> int:
        """The full-mesh z width (0 before warmup)."""
        return self._warm_width

    @property
    def default_cost(self) -> int:
        """The slice-equivalent cost the fleet router weighs an
        unsized volume request by (the smallest depth bucket)."""
        return self.depth_buckets[0]

    def _device_pool(self) -> List[Tuple[Optional[int], object]]:
        """``[(lane, device)]`` the next mesh is built from.

        Healthy local lanes normally; the GLOBAL device set when this
        replica joined a ``jax.distributed`` job (``--distributed-init``)
        — global devices carry no local lane id, so lane-death
        attribution is local-mode only.
        """
        if self.distributed:
            from nm03_capstone_project_tpu.compilehub import (
                distributed_is_initialized,
            )

            if distributed_is_initialized():
                import jax

                return [(None, d) for d in jax.devices()]
        return self.executor.healthy_lane_devices()

    def padded_depth(self, depth: int, n_shards: int) -> int:
        """The dispatch depth for a ``depth``-plane study on ``n_shards``.

        Smallest warm bucket that fits, rounded up to the next multiple
        of ``n_shards`` (shard_map needs even division; the extra planes
        are zero filler that segments empty — the driver's own
        divisibility pad, so bucketing preserves bit-identity). Raises
        ValueError past the largest bucket.
        """
        for b in self.depth_buckets:
            if depth <= b:
                return -(-b // n_shards) * n_shards
        raise ValueError(
            f"study of {depth} planes exceeds the largest volume depth "
            f"bucket {self.max_depth}"
        )

    def _usable_shards(self, pool_size: int, depth: int) -> int:
        """Largest mesh width <= pool_size the halo contract allows."""
        halo = self.cfg.morph_size // 2
        n = max(pool_size, 1)
        while n > 1 and self.padded_depth(depth, n) // n < max(halo, 1):
            n -= 1
        return n

    def _compiled(self, depth: int, devices: List):
        """(executable, padded_depth, mesh) for a study over ``devices``."""
        from nm03_capstone_project_tpu.compilehub import programs
        from nm03_capstone_project_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(len(devices), axis_names=("z",), devices=devices)
        padded = self.padded_depth(depth, len(devices))
        return programs.serve_volume(self.cfg, padded, mesh), padded, mesh

    def warmup(self) -> dict:
        """Compile + execute every depth bucket on its full mesh once.

        Call after the executor's warmup (lanes resolved). Returns
        ``{bucket: seconds}``. Each bucket warms at the SAME mesh width
        dispatch will compute for a study of that bucket's depth
        (``_usable_shards`` is bucket-dependent when the dilation halo
        constrains shallow buckets — e.g. ``morph_size=5`` caps an
        8-plane bucket at fewer shards than a 32-plane one), so the
        first volume request of ANY admissible depth finds its warm
        executable and never pays a trace+compile while holding the
        gang; the hub persists the executables when a compile cache is
        attached.
        """
        pool = self._device_pool()
        devices = [d for _, d in pool]
        timings = {}
        c = self.cfg.canvas
        width = 0
        for b in self.depth_buckets:
            n = self._usable_shards(len(devices), b)
            width = max(width, n)
            t0 = time.perf_counter()
            fn, padded, mesh = self._compiled(b, devices[:n])
            vol, dims = self._stage(
                np.zeros((padded, c, c), np.float32),
                np.asarray([self.cfg.min_dim, self.cfg.min_dim], np.int32),
                mesh,
            )
            out = fn(vol, dims)
            np.asarray(out["mask"])  # block until executed
            timings[b] = round(time.perf_counter() - t0, 3)
        # nm03-lint: disable=NM331 single writer: warmup() runs once on the startup thread before start(); concurrent /readyz readers see either 0 (warming) or the final width — an atomic int either way
        self._warm_width = width
        return timings

    @staticmethod
    def _stage(volume: np.ndarray, dims: np.ndarray, mesh):
        """Host -> mesh staging, through the ingest home (NM401)."""
        from nm03_capstone_project_tpu.ingest import stage_volume

        return stage_volume(volume, dims, mesh)

    def start(self) -> "VolumeGang":
        # nm03-lint: disable=NM331 owner-thread write before _thread.start(); see __init__
        self._started = True
        self._thread.start()
        return self

    def join(self, timeout_s: Optional[float] = None) -> bool:
        """Wait for the gang to drain (queue must be closed first)."""
        if not self._started:
            return True
        self._thread.join(timeout=timeout_s)
        return not self._thread.is_alive()

    # -- admission ---------------------------------------------------------

    def submit(
        self, volume: np.ndarray, dims: Tuple[int, int],
        trace_id: Optional[str] = None,
    ) -> VolumeRequest:
        """Admit one decoded study; QueueFull/QueueClosed shed at the door.

        Depth guards are the CALLER's job (the server rejects before
        admission so a too-deep study is a 413, never a wasted gang
        turn); this validates only what the gang itself depends on.
        """
        from nm03_capstone_project_tpu.obs.trace import (
            TraceContext,
            new_trace_id,
        )

        depth = int(volume.shape[0])
        self.padded_depth(depth, 1)  # raises past the largest bucket
        req = VolumeRequest(
            request_id=uuid.uuid4().hex[:12],
            volume=volume,
            dims=(int(dims[0]), int(dims[1])),
            depth=depth,
            trace=TraceContext(trace_id or new_trace_id()),
        )
        self.queue.put(req)  # raises QueueFull / QueueClosed
        return req

    # -- the gang loop -----------------------------------------------------

    def _run(self) -> None:
        while True:
            batch = self.queue.get_batch(1, 0.0)
            if not batch:  # closed and empty: drain complete
                return
            req = batch[0]
            try:
                self._execute(req)
            except BaseException as e:  # noqa: BLE001 — the loop must survive
                if not req.done.is_set():
                    req.fail(e)

    def _fire_fault(self, seq: int, lanes: List[Optional[int]]):
        """Consult the ``volume`` fault site; ``(blamed_lane, rule)`` or None.

        One check per mesh lane so a ``lane``-selected rule fires exactly
        when its lane is part of the dispatching mesh — the deterministic
        "lane k dies mid-volume" drill. A rule with no lane selector
        fires on the first check and reports no blame (an unattributable
        mesh failure: the gang sheds rather than guess).
        """
        plan = self.fault_plan
        if plan is None or not plan.has_site("volume"):
            return None
        for ln in lanes:
            rule = plan.fire("volume", obs=self.obs, index=seq, lane=ln)
            if rule is not None:
                return (rule.lane, rule)
        return None

    def _execute(self, req: VolumeRequest) -> None:
        now = time.monotonic()
        req.queue_wait_s = max(now - req.t_admitted, 0.0)
        if req.trace is not None:
            popped = req.t_popped or now
            req.trace.add_span("queue_wait", req.t_admitted, popped)
        seq = next(self._seq)
        t_gang0 = time.monotonic()
        with self.batcher.gang_parked():
            t_acquired = time.monotonic()
            req.gang_wait_s = t_acquired - t_gang0
            if req.trace is not None:
                req.trace.add_span("volume_gang_acquire", t_gang0, t_acquired)
            try:
                self._dispatch_volume(req, seq)
            except BaseException as e:  # noqa: BLE001 — per-request containment
                req.fail(e)
                return
        req.done.set()

    def _dispatch_volume(self, req: VolumeRequest, seq: int) -> None:
        """Run the mesh program, re-meshing onto survivors on lane death."""
        c = self.cfg.canvas
        h, w = req.dims
        excluded: set = set()
        # one hop per lane the mesh started with, plus one: bounded even
        # against pathological flapping
        hops_left = len(self._device_pool()) + 1
        while True:
            hops_left -= 1
            if hops_left < 0:
                raise GangUnavailable(
                    "volume request exhausted its re-mesh budget (lanes "
                    "are flapping; see serving_lane_quarantines_total)"
                )
            full_pool = [
                (ln, d) for ln, d in self._device_pool()
                if ln not in excluded
            ]
            if not full_pool:
                raise GangUnavailable(
                    "no healthy lane left to build a volume mesh on"
                )
            full_lanes = [ln for ln, _ in full_pool]
            n = self._usable_shards(len(full_pool), req.depth)
            pool = full_pool[:n]
            lanes = [ln for ln, _ in pool]
            devices = [d for _, d in pool]
            fn, padded, mesh = self._compiled(req.depth, devices)
            # zero filler planes segment empty (normalize(0) lands outside
            # the grow band) — the driver's own divisibility pad, extended
            # to the bucket, so cropping [:depth] recovers the exact
            # directly-driven mask
            stack = np.zeros((padded, c, c), np.float32)
            stack[: req.depth, :h, :w] = req.volume
            injected = self._fire_fault(seq, lanes)
            if injected is not None:
                blamed, _rule = injected
                if blamed is None or blamed not in lanes:
                    raise GangUnavailable(
                        "injected unattributable mesh failure "
                        "(volume dispatch_error)"
                    )
                # the drill's deterministic lane death: book it through
                # the real quarantine machine and re-mesh on the survivors
                log.warning(
                    "volume %s: injected death of lane %d mid-volume; "
                    "re-meshing onto survivors", req.request_id, blamed,
                )
                self.executor.quarantine_lane(blamed, "device_lost")
                excluded.add(blamed)
                self._note_requeue(req, blamed, "injected_device_lost")
                continue
            vol_dev, dims_dev = self._stage(
                stack, np.asarray([h, w], np.int32), mesh
            )
            sup = self.executor.new_supervisor()
            trace = req.trace

            def primary():
                with trace.span("volume_dispatch", z_shards=len(devices)):
                    out = fn(vol_dev, dims_dev)
                with trace.span("volume_gather"):
                    mask = np.asarray(out["mask"])  # nm03-lint: disable=NM321 the gather span MEASURES this mesh->host sync — that is its purpose
                    conv = np.asarray(out["grow_converged"])  # nm03-lint: disable=NM321 same deliberate sync, see above

                return mask, conv

            try:
                # nm03-lint: disable=NM422 the canonical gang hold: the WHOLE mesh program runs under the parked batcher — that exclusivity is what makes a volume dispatch safe (ISSUE 15)
                mask, conv = sup.run(
                    primary, fallback=None, label="volume_dispatch"
                )
            except BaseException as e:  # noqa: BLE001 — classified below
                cause = self._failure_cause(e)
                if cause is None:
                    raise  # deterministic failure: the requester's problem
                survivors = [
                    ln for ln, _ in self._device_pool()
                    if ln not in excluded
                ]
                if survivors != full_lanes:
                    # the fleet already booked a lane death (slice traffic
                    # or the probe loop saw it): retry on the survivors
                    log.warning(
                        "volume %s: mesh dispatch failed (%s); re-meshing "
                        "onto the surviving lanes", req.request_id, cause,
                    )
                    self._note_requeue(req, None, cause)
                    continue
                # unattributable with an unchanged fleet: shedding beats
                # guessing which chip to blame — the client retries
                raise GangUnavailable(
                    f"mesh-wide volume dispatch failed ({cause}) with no "
                    "attributable lane; retry after the fleet settles"
                ) from e
            req.mask = np.ascontiguousarray(mask[: req.depth, :h, :w])
            req.converged = bool(np.asarray(conv))
            req.z_shards = len(devices)
            return

    def _note_requeue(self, req: VolumeRequest, lane, cause: str) -> None:
        req.requeues += 1
        if req.trace is not None:
            t = time.monotonic()
            req.trace.add_span("volume_requeue", t, t, lane=lane, cause=cause)

    @staticmethod
    def _failure_cause(exc: BaseException) -> Optional[str]:
        """Lane-fault classification, shared with the slice executor."""
        from nm03_capstone_project_tpu.serving.executor import WarmExecutor

        return WarmExecutor._quarantine_cause(exc)

    # -- status ------------------------------------------------------------

    def status(self) -> dict:
        """The ``/readyz`` ``volumes`` block."""
        return {
            "enabled": True,
            "depth_buckets": list(self.depth_buckets),
            "max_depth": self.max_depth,
            "z_shards": self.z_shards,
            "queue_depth": len(self.queue),
            "queue_capacity": self.queue.capacity,
            "distributed": self.distributed,
            # the published routing cost (ISSUE 15): what the fleet
            # front-end weighs an unsized volume request by
            "default_cost": self.default_cost,
        }
