"""Per-lane fault domains: quarantine, probation, reinstatement.

PR 6 deliberately kept degradation process-wide — the first wedged lane
drained the whole replica onto the CPU fallback. That policy throws away
7 healthy chips' capacity to escape 1 sick one, which inverts the source
paper's own contribution (per-image fault isolation so one bad input
never kills a cohort). This module gives each replica lane its own fault
domain instead:

* **HEALTHY** — the lane takes traffic (the batcher fans windows over
  exactly these lanes);
* **QUARANTINED** — the lane's supervised dispatch expired its deadline
  or exhausted its retry budget; it takes no traffic, its in-flight
  chunk is re-dispatched to healthy lanes, and the flight recorder
  auto-dumps the transition (the wedged lane's ring is the post-mortem);
* **PROBATION** — a background probe thread has claimed the lane and is
  re-executing its warm hub executable on a canary batch, supervised,
  off the request path; success reinstates the lane to HEALTHY, failure
  returns it to QUARANTINED.

The process-wide one-way CPU fallback (PR 3) remains the last resort: it
fires only when **every** lane is quarantined. ``/readyz`` stays 200
while at least one lane is healthy, reporting the reduced ``capacity``.

Every transition is observable: ``serving_lane_state{lane}`` (0 healthy,
1 probation, 2 quarantined), ``serving_lane_quarantines_total{lane,cause}``,
``serving_lane_reinstated_total{lane}``, WARNING ``lane_quarantined`` /
INFO ``lane_reinstated`` events, and flight-recorder marks + an auto-dump
named ``lane<N>_quarantine_<cause>`` at each quarantine of a serving
lane. ``probe_failed`` re-quarantines mark and count but do NOT dump:
the lane's original quarantine already dumped the wedged dispatch's
ring, and a persistently sick chip fails its canary every probe
interval — dumping each failure would bury that post-mortem under
probe noise.

jax-free at import by contract (NM301 pins ``serving.lanes``, alongside
its ``serving.queue``/``serving.metrics`` siblings): the state machine
must be unit-testable — and its transitions dumpable — without a
backend. The module itself imports no numpy either, but the package
``__init__`` ancestor does, so only the jax ban is enforceable
transitively.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from nm03_capstone_project_tpu.obs import flightrec
from nm03_capstone_project_tpu.serving.metrics import (
    LANE_STATE_VALUES,
    SERVING_LANE_QUARANTINES_TOTAL,
    SERVING_LANE_REINSTATED_TOTAL,
    SERVING_LANE_STATE,
)
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("serving")

HEALTHY = "healthy"
PROBATION = "probation"
QUARANTINED = "quarantined"


class LaneQuarantined(RuntimeError):
    """One lane left the healthy set mid-dispatch; re-dispatch the chunk.

    Raised by the executor toward the batcher — NOT toward a client. The
    batcher catches it and re-fans the chunk onto the remaining healthy
    lanes (span ``requeue``); only when no healthy lane remains does the
    chunk fall through to the process-wide degraded path.
    """

    def __init__(self, lane: int, cause: str):
        super().__init__(f"lane {lane} quarantined ({cause})")
        self.lane = int(lane)
        self.cause = str(cause)


class LaneFaultDomains:
    """The per-lane state machine; one instance per :class:`WarmExecutor`.

    Transitions (all lock-guarded; every mutator returns what the caller
    needs to act without re-reading state):

    ``quarantine(lane, cause)`` — HEALTHY → QUARANTINED; idempotent for
    any lane already out of the healthy set (a racing second dispatch on
    a quarantined lane, or a STALE in-flight dispatch timing out after
    the prober claimed the lane for PROBATION, changes nothing and
    counts nothing — it is the same physical wedge, and stealing the
    probation claim would invalidate a passing canary). Returns
    ``(changed, healthy_remaining)`` so the caller can trip the
    process-wide fallback exactly when the LAST lane goes.

    ``begin_probation(lane)`` — QUARANTINED → PROBATION; the probe
    thread's claim, so two probers can never canary one lane at once.

    ``reinstate(lane)`` — PROBATION → HEALTHY (the probe passed);
    refused once the fleet is ``retired``.

    ``fail_probation(lane)`` — PROBATION → QUARANTINED (the probe
    failed; cause ``probe_failed``, counted as a fresh quarantine).

    ``retired`` flips one-way, in the same critical section, when the
    quarantine that drains the LAST healthy lane lands: the caller trips
    the one-way process-wide CPU degradation on that outcome, and a
    probe whose canary was already in flight must not resurrect a lane
    into the dead replica — ``reinstate`` checks the flag under the same
    lock, so there is no check-then-act window.
    """

    def __init__(self, n_lanes: int, obs=None):
        if n_lanes < 1:
            raise ValueError(f"n_lanes must be >= 1, got {n_lanes}")
        self._lock = threading.Lock()
        self._states: List[str] = [HEALTHY] * int(n_lanes)
        self._causes: List[Optional[str]] = [None] * int(n_lanes)
        self._quarantines: List[int] = [0] * int(n_lanes)
        self._retired = False
        self.obs = obs
        # the gauge series exist from lane 0 of warmup on, so a topology
        # assertion (--expect-gauge serving_lane_state{lane=N}=0) can
        # distinguish "healthy" from "never reported"
        for lane in range(int(n_lanes)):
            self._set_state_gauge(lane, HEALTHY)

    # -- reads -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._states)

    def state(self, lane: int) -> str:
        with self._lock:
            return self._states[lane]

    def cause(self, lane: int) -> Optional[str]:
        with self._lock:
            return self._causes[lane]

    def is_healthy(self, lane: int) -> bool:
        with self._lock:
            return self._states[lane] == HEALTHY

    def healthy_lanes(self) -> List[int]:
        with self._lock:
            return [i for i, s in enumerate(self._states) if s == HEALTHY]

    def lanes_in(self, state: str) -> List[int]:
        with self._lock:
            return [i for i, s in enumerate(self._states) if s == state]

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._states if s == HEALTHY)

    def quarantined_count(self) -> int:
        """Lanes currently out of the healthy set (quarantined OR under
        probation — neither takes traffic)."""
        with self._lock:
            return sum(1 for s in self._states if s != HEALTHY)

    @property
    def retired(self) -> bool:
        """One-way True once a quarantine drained the last healthy lane
        (the caller's process-wide CPU degradation tripped on the same
        outcome); a retired fleet refuses reinstatement."""
        with self._lock:
            return self._retired

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {"lane": i, "state": s, "cause": self._causes[i],
                 "quarantines": self._quarantines[i]}
                for i, s in enumerate(self._states)
            ]

    # -- transitions -------------------------------------------------------

    def quarantine(
        self, lane: int, cause: str, trace_ids: Sequence[str] = (),
    ):
        """HEALTHY → QUARANTINED; ``(changed, healthy_left)``.

        ``trace_ids`` are the wedged chunk's riders — they ride the
        WARNING event and the flight-recorder mark so the post-mortem
        names the requests the quarantine stranded.

        Idempotent unless the lane is HEALTHY: new dispatches never land
        on a non-healthy lane (``run_batch`` bounces them at entry), so a
        quarantine call for a QUARANTINED — or prober-claimed PROBATION —
        lane is a STALE in-flight dispatch reporting the wedge that
        already quarantined it. Counting/dumping it again would
        double-book one incident, and flipping PROBATION back would
        steal the prober's claim mid-canary (its reinstate would then
        no-op, idling the lane one extra probe round).
        """
        with self._lock:
            if not 0 <= lane < len(self._states):
                raise ValueError(f"lane {lane} outside [0, {len(self._states)})")
            if self._states[lane] != HEALTHY:
                changed = False
            else:
                self._transition_to_quarantined(lane, cause)
                changed = True
            healthy_left = sum(1 for s in self._states if s == HEALTHY)
            if changed and healthy_left == 0:
                # retire in the SAME critical section that drains the last
                # healthy lane: reinstate() checks the flag under this
                # lock, so a probe whose canary raced this quarantine can
                # never resurrect a lane into the degraded replica
                self._retired = True
        if not changed:
            return False, healthy_left
        self._emit_quarantined(lane, cause, healthy_left, list(trace_ids))
        # the quarantine transition IS the post-mortem moment for this
        # lane: dump while the wedged thread's ring still holds the
        # dispatch that never returned. Inert unless a dump dir is
        # configured (nm03-serve --flight-dir / NM03_FLIGHTREC_DIR).
        flightrec.auto_dump(reason=f"lane{int(lane)}_quarantine_{cause}")
        return True, healthy_left

    def begin_probation(self, lane: int) -> bool:
        """QUARANTINED → PROBATION (the probe thread's exclusive claim)."""
        with self._lock:
            if self._states[lane] != QUARANTINED:
                return False
            self._states[lane] = PROBATION
            self._set_state_gauge(lane, PROBATION)
        flightrec.note("mark", "lane_probation", lane=int(lane))
        if self.obs is not None:
            try:
                self.obs.events.emit("lane_probation", lane=int(lane))
            except Exception:  # noqa: BLE001
                pass
        return True

    def reinstate(self, lane: int) -> bool:
        """PROBATION → HEALTHY: the canary passed; the lane takes traffic.

        Refused once the fleet is retired — the check shares the lock
        with the quarantine that retires, so a canary that passed just
        as the last healthy lane drained cannot reinstate its lane into
        a replica whose one-way CPU degradation already tripped (the
        lane stays in PROBATION; gauges never claim capacity the
        degraded executor will not use).
        """
        with self._lock:
            if self._retired or self._states[lane] != PROBATION:
                return False
            self._states[lane] = HEALTHY
            self._causes[lane] = None
            self._set_state_gauge(lane, HEALTHY)
        if self.obs is not None:
            try:
                self.obs.registry.counter(
                    SERVING_LANE_REINSTATED_TOTAL,
                    help="lanes reinstated to HEALTHY by a passing "
                    "probation probe",
                    lane=str(lane),
                ).inc()
                self.obs.events.emit("lane_reinstated", lane=int(lane))
            except Exception:  # noqa: BLE001
                pass
        flightrec.note("mark", "lane_reinstated", lane=int(lane))
        log.warning("lane %d reinstated by probation probe", lane)
        return True

    def fail_probation(self, lane: int, cause: str = "probe_failed") -> bool:
        """PROBATION → QUARANTINED: the canary failed; keep the lane out.

        Counted as a fresh quarantine (the cause tells it apart) but
        deliberately NOT auto-dumped — see the module docstring: the
        original quarantine's dump carries the wedged dispatch's ring,
        and a still-sick chip fails a canary every probe interval.
        """
        with self._lock:
            if self._states[lane] != PROBATION:
                return False
            self._transition_to_quarantined(lane, cause)
            healthy_left = sum(1 for s in self._states if s == HEALTHY)
        self._emit_quarantined(lane, cause, healthy_left, [])
        return True

    # -- telemetry ---------------------------------------------------------

    def _transition_to_quarantined(self, lane: int, cause: str) -> None:
        """The one QUARANTINED transition body (caller holds ``_lock``).

        Gauge/counter INSIDE the lock: racing transitions must publish
        in state order, or ``--expect-gauge`` reads a state the fleet is
        not in (the registry lock is a leaf — no ordering cycle).
        Events/log/dump stay outside: they do I/O and carry their own
        timestamps.
        """
        # nm03-lint: disable=NM331 caller holds _lock by contract (quarantine/fail_probation); the shared helper exists so the two transition paths cannot drift
        self._states[lane] = QUARANTINED
        # nm03-lint: disable=NM331 caller holds _lock, see above
        self._causes[lane] = str(cause)
        # nm03-lint: disable=NM331 caller holds _lock, see above
        self._quarantines[lane] += 1
        self._set_state_gauge(lane, QUARANTINED)
        self._count_quarantine(lane, cause)

    def _emit_quarantined(
        self, lane: int, cause: str, healthy_left: int, trace_ids: List[str]
    ) -> None:
        """The quarantine transition's log line, WARNING event, and
        flight-recorder mark (shared by ``quarantine``/``fail_probation``
        so the two paths can never drift apart)."""
        log.warning(
            "lane %d quarantined (%s); %d healthy lane(s) remain",
            lane, cause, healthy_left,
        )
        if self.obs is not None:
            try:
                self.obs.events.emit(
                    "lane_quarantined", level="WARNING", lane=int(lane),
                    cause=str(cause), healthy_remaining=healthy_left,
                    trace_ids=trace_ids,
                )
            except Exception:  # noqa: BLE001 — telemetry never blocks triage
                pass
        flightrec.note(
            "mark", "lane_quarantined", lane=int(lane), cause=str(cause),
            trace_ids=trace_ids,
        )

    def _set_state_gauge(self, lane: int, state: str) -> None:
        if self.obs is None:
            return
        try:
            self.obs.registry.gauge(
                SERVING_LANE_STATE,
                help="per-lane fault-domain state "
                "(0 healthy, 1 probation, 2 quarantined)",
                lane=str(lane),
            ).set(LANE_STATE_VALUES[state])
        except Exception:  # noqa: BLE001
            pass

    def _count_quarantine(self, lane: int, cause: str) -> None:
        if self.obs is None:
            return
        try:
            self.obs.registry.counter(
                SERVING_LANE_QUARANTINES_TOTAL,
                help="lane quarantine transitions by lane and cause "
                "(deadline / device_lost / probe_failed)",
                lane=str(lane),
                cause=str(cause),
            ).inc()
        except Exception:  # noqa: BLE001
            pass
