"""Warm per-bucket executables behind the PR-3 dispatch supervision.

The r05 bench showed per-batch dispatch overhead — not device FLOPs — is
what a cold path pays on every call: tracing, compilation, and executable
lookup all sit between an arriving request and the chip. An online service
cannot amortize that over a cohort, so this executor compiles ONE
executable per batch-size bucket at startup (``warmup``) and serve-time
dispatch is a dictionary lookup plus an XLA execute — the always-warm
model that makes dynamic batching worth doing at all.

Supervision is inherited, not reimplemented: every batch dispatch runs
through the PR-3 :class:`DispatchSupervisor`, so online traffic gets the
same deadline guard, transient-error retry, and one-way CPU degradation
as the batch drivers — a wedged accelerator turns into slower responses
and a not-ready ``/readyz``, never a hung service. The CPU fallback
recomputes from the host arrays the batcher already holds (fetching from
a wedged device would BE the wedge).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.resilience import (
    DispatchSupervisor,
    FaultPlan,
    InjectedTransientError,
    ResilienceConfig,
    execute_hang,
)

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16)


class WarmExecutor:
    """One compiled ``slice_pipeline`` executable per (batch-bucket, config).

    ``buckets`` is the ascending list of batch sizes an executable exists
    for; a coalesced batch is padded up to the smallest bucket that fits
    (:meth:`bucket_for`), so the compile-shape set is fixed at startup and
    serve-time traffic can never trigger a recompile stall.
    """

    def __init__(
        self,
        cfg: PipelineConfig,
        buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
        resilience: Optional[ResilienceConfig] = None,
        obs=None,
        fault_plan: Optional[FaultPlan] = None,
    ):
        if not buckets or list(buckets) != sorted(set(int(b) for b in buckets)):
            raise ValueError(
                f"buckets must be strictly increasing, got {buckets}"
            )
        if any(b < 1 for b in buckets):
            raise ValueError(f"buckets must be >= 1, got {buckets}")
        self.cfg = cfg
        self.buckets: Tuple[int, ...] = tuple(int(b) for b in buckets)
        self.obs = obs
        self.res = resilience if resilience is not None else ResilienceConfig()
        self.fault_plan = fault_plan
        retry = self.res.make_retry_policy(
            seed=fault_plan.seed if fault_plan is not None else 0
        )
        retry.obs = obs
        self.supervisor = DispatchSupervisor(self.res, retry=retry, obs=obs)
        self._compiled: Dict[int, object] = {}
        self._fallback_fn = None
        self._lock = threading.Lock()
        self._dispatch_seq = itertools.count()
        self._warm = False

    # -- state -------------------------------------------------------------

    @property
    def warm(self) -> bool:
        """True once every bucket's executable is built and executed.

        Read by handler threads (via ``/readyz``) while ``warmup`` runs on
        the startup thread; the write is lock-guarded (nm03-lint NM331) so
        a reader observing True also observes the fully-populated
        ``_compiled`` dict, not just the flag.
        """
        with self._lock:
            return self._warm

    @warm.setter
    def warm(self, value: bool) -> None:
        with self._lock:
            self._warm = bool(value)

    @property
    def degraded(self) -> bool:
        """True once the one-way CPU degradation has tripped (PR 3)."""
        return self.supervisor.degraded

    @property
    def degraded_cause(self) -> Optional[str]:
        return self.supervisor.degraded_cause

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest warm bucket that fits ``n`` requests."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket {self.buckets[-1]}"
        )

    # -- compilation -------------------------------------------------------

    def _build(self, bucket: int):
        """Compile the mask-only vmapped pipeline for one bucket shape.

        AOT (``jit(...).lower(...).compile()``) so the executable exists
        the moment warmup returns — serve-time calls never trace. Falls
        back to a plain jitted callable (first call compiles) on backends
        where AOT lowering is unavailable.
        """
        import jax
        import jax.numpy as jnp

        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        cfg = self.cfg

        def one(px, dm):
            out = process_slice(px, dm, cfg)
            return out["mask"], out["grow_converged"]

        # no donation: a supervised retry re-runs the primary with the SAME
        # host arrays, and serving's per-batch HBM footprint is tiny
        fn = jax.jit(jax.vmap(one))
        c = cfg.canvas
        try:
            return fn.lower(
                jax.ShapeDtypeStruct((bucket, c, c), jnp.float32),
                jax.ShapeDtypeStruct((bucket, 2), jnp.int32),
            ).compile()
        except Exception:  # noqa: BLE001 — AOT is an optimization, not a contract
            return fn

    def _get_compiled(self, bucket: int):
        with self._lock:
            fn = self._compiled.get(bucket)
        if fn is not None:
            return fn
        fn = self._build(bucket)
        with self._lock:
            self._compiled.setdefault(bucket, fn)
            return self._compiled[bucket]

    def warmup(self) -> Dict[int, float]:
        """Compile + execute every bucket once; {bucket: seconds}.

        The execute (on zeros) is part of warmup on purpose: first-run
        allocator/executable setup must be paid here, behind ``/readyz``,
        not by the first unlucky request.
        """
        c = self.cfg.canvas
        timings: Dict[int, float] = {}
        for b in self.buckets:
            t0 = time.perf_counter()
            fn = self._get_compiled(b)
            px = np.zeros((b, c, c), np.float32)
            dm = np.full((b, 2), self.cfg.min_dim, np.int32)
            mask, conv = fn(px, dm)
            np.asarray(mask), np.asarray(conv)  # block until executed
            timings[b] = round(time.perf_counter() - t0, 3)
        if self.obs is not None:
            for b, s in timings.items():
                self.obs.registry.gauge(
                    "serving_warmup_seconds",
                    help="startup compile+first-execute time per batch bucket",
                    bucket=str(b),
                ).set(s)
        # nm03-lint: disable=NM331 goes through the lock-guarded property setter above; the linter cannot see through the descriptor
        self.warm = True
        return timings

    # -- degradation target ------------------------------------------------

    def _fallback_call(self):
        """CPU recompute of the same batch from host arrays (PR-3 ladder).

        One jitted callable shared across buckets — XLA retraces per bucket
        shape, which is acceptable on the degraded path (correct-but-slower
        is the contract; the service flips not-ready either way).
        """
        with self._lock:
            if self._fallback_fn is not None:
                return self._fallback_fn
        import dataclasses

        import jax

        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        cpu = jax.local_devices(backend="cpu")[0]
        cfg = (
            dataclasses.replace(self.cfg, use_pallas=False)
            if self.cfg.use_pallas
            else self.cfg
        )

        def one(px, dm):
            out = process_slice(px, dm, cfg)
            return out["mask"], out["grow_converged"]

        inner = jax.jit(jax.vmap(one))

        def call(px, dm):
            with jax.default_device(cpu):
                out = inner(
                    jax.device_put(np.asarray(px), cpu),
                    jax.device_put(np.asarray(dm), cpu),
                )
            return tuple(np.asarray(a) for a in out)

        # first builder wins: concurrent degraded dispatches must agree on
        # ONE callable (two jitted twins would double the retrace cost)
        with self._lock:
            if self._fallback_fn is None:
                self._fallback_fn = call
            return self._fallback_fn

    # -- chaos hook --------------------------------------------------------

    def _pre(self, index: int):
        """Dispatch-site fault hook (resilience.FaultPlan); None when off."""
        plan = self.fault_plan
        if plan is None or not plan.has_site("dispatch"):
            return None

        def pre(cancel):
            rule = plan.fire("dispatch", obs=self.obs, index=index)
            if rule is None:
                return
            if rule.kind == "hang":
                execute_hang(rule, cancel)
            else:  # transient
                raise InjectedTransientError(
                    f"injected transient device error (serve dispatch {index})"
                )

        return pre

    # -- the serve-time entry point ----------------------------------------

    def run_batch(self, pixels: np.ndarray, dims: np.ndarray):
        """Execute one bucket-padded batch under supervision.

        ``pixels`` is (bucket, canvas, canvas) float32, ``dims`` (bucket, 2)
        int32 — already padded by the batcher. Returns host-side
        ``(mask, converged)`` arrays. Raises only when the PR-3 ladder is
        exhausted (deterministic error, or degraded with fallback disabled);
        the batcher fails the batch's requests with it.
        """
        bucket = int(pixels.shape[0])
        fn = self._get_compiled(bucket)
        index = next(self._dispatch_seq)

        def primary():
            # fetch INSIDE the supervised call: a wedged fetch is the same
            # wedge as a wedged dispatch (supervisor contract)
            mask, conv = fn(pixels, dims)
            return np.asarray(mask), np.asarray(conv)

        def fallback():
            return self._fallback_call()(pixels, dims)

        return self.supervisor.run(
            primary,
            fallback=fallback,
            pre=self._pre(index),
            label="serve_dispatch",
        )
