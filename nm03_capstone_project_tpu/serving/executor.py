"""Warm per-bucket, per-lane executables behind the PR-3 dispatch supervision.

The r05 bench showed per-batch dispatch overhead — not device FLOPs — is
what a cold path pays on every call: tracing, compilation, and executable
lookup all sit between an arriving request and the chip. An online service
cannot amortize that over a cohort, so this executor warms ONE executable
per (replica lane, batch-size bucket) at startup and serve-time dispatch
is a registry lookup plus an XLA execute — the always-warm model that
makes dynamic batching worth doing at all.

**Replica lanes** are the sharded-serving unlock (ROADMAP item 1): every
local device becomes a lane, each lane holds its own compile-hub
executables pinned to its chip (``SingleDeviceSharding``), and the
batcher fans coalesced batches out across lanes so capacity scales with
chips, not processes. One device degenerates to exactly the PR-4
single-executable behavior. Compilation itself lives in
:mod:`nm03_capstone_project_tpu.compilehub` — this class holds no compile
cache of its own, only lane state.

Supervision is inherited, not reimplemented: every lane dispatch runs
through the PR-3 :class:`DispatchSupervisor`, so online traffic gets the
same deadline guard, transient-error retry, and one-way CPU degradation
as the batch drivers. Degradation is process-wide by design: the CPU
fallback serves every lane's traffic (correct-but-slower), ``/readyz``
flips not-ready, and the load balancer drains the whole replica — a
single sick chip is not worth per-lane triage inside one process (see
docs/OPERATIONS.md, "Multi-chip serving").
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from nm03_capstone_project_tpu.compilehub import programs
from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.obs.trace import NULL_TRACE
from nm03_capstone_project_tpu.resilience import (
    DispatchSupervisor,
    FaultPlan,
    InjectedTransientError,
    ResilienceConfig,
    execute_hang,
)
from nm03_capstone_project_tpu.serving.metrics import (
    SERVING_LANE_BATCHES_TOTAL,
    SERVING_LANE_INFLIGHT,
    SERVING_LANES_READY,
)

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16)


class WarmExecutor:
    """Per-lane, per-bucket warm ``slice_pipeline`` executables.

    ``supports_trace`` tells the batcher this executor accepts the
    ``trace=`` chunk-trace argument on :meth:`run_batch` (test fakes
    without it get a coarse batcher-side dispatch span instead).

    ``buckets`` is the ascending list of batch sizes an executable exists
    for; a coalesced chunk is padded up to the smallest bucket that fits
    (:meth:`bucket_for`), so the compile-shape set is fixed at startup and
    serve-time traffic can never trigger a recompile stall. ``lanes``
    caps the replica-lane count (None = every local device, resolved
    lazily so constructing the executor never initializes a backend).
    """

    supports_trace = True

    def __init__(
        self,
        cfg: PipelineConfig,
        buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
        resilience: Optional[ResilienceConfig] = None,
        obs=None,
        fault_plan: Optional[FaultPlan] = None,
        lanes: Optional[int] = None,
    ):
        if not buckets or list(buckets) != sorted(set(int(b) for b in buckets)):
            raise ValueError(
                f"buckets must be strictly increasing, got {buckets}"
            )
        if any(b < 1 for b in buckets):
            raise ValueError(f"buckets must be >= 1, got {buckets}")
        if lanes is not None and lanes < 1:
            raise ValueError(f"lanes must be >= 1 (or None = all), got {lanes}")
        self.cfg = cfg
        self.buckets: Tuple[int, ...] = tuple(int(b) for b in buckets)
        self.obs = obs
        self.res = resilience if resilience is not None else ResilienceConfig()
        self.fault_plan = fault_plan
        retry = self.res.make_retry_policy(
            seed=fault_plan.seed if fault_plan is not None else 0
        )
        retry.obs = obs
        self.supervisor = DispatchSupervisor(self.res, retry=retry, obs=obs)
        self._fallback_fn = None
        self._lock = threading.Lock()
        self._dispatch_seq = itertools.count()
        self._warm = False
        self._requested_lanes = lanes
        self._lane_devices: Optional[List] = None
        self._lane_warm: List[bool] = []
        self._lane_inflight: List[int] = []
        self._lane_batches: List[int] = []

    # -- lanes -------------------------------------------------------------

    def _resolve_lanes(self) -> List:
        """The lane device list, resolving (and initializing jax) once."""
        with self._lock:
            if self._lane_devices is not None:
                return self._lane_devices
        devs = programs.lane_devices(self._requested_lanes)
        with self._lock:
            if self._lane_devices is None:
                self._lane_devices = devs
                self._lane_warm = [self._warm] * len(devs)
                self._lane_inflight = [0] * len(devs)
                self._lane_batches = [0] * len(devs)
            return self._lane_devices

    @property
    def lane_count(self) -> Optional[int]:
        """Resolved lane count; the requested cap before resolution (None
        = unknown until a backend exists)."""
        with self._lock:
            if self._lane_devices is not None:
                return len(self._lane_devices)
        return self._requested_lanes

    @property
    def lanes_ready(self) -> int:
        """Warm lanes — the ``serving_lanes_ready`` gauge's value."""
        with self._lock:
            if self._lane_devices is not None:
                return sum(1 for w in self._lane_warm if w)
            return (self._requested_lanes or 1) if self._warm else 0

    def lane_state(self) -> List[dict]:
        """Per-lane readiness/inflight/dispatch state (the ``/readyz``
        ``lanes.per_lane`` payload); [] before lane resolution."""
        with self._lock:
            if self._lane_devices is None:
                return []
            return [
                {
                    "lane": i,
                    "device": str(d),
                    "warm": self._lane_warm[i],
                    "inflight": self._lane_inflight[i],
                    "batches": self._lane_batches[i],
                }
                for i, d in enumerate(self._lane_devices)
            ]

    def _set_lanes_ready_gauge(self) -> None:
        if self.obs is not None:
            self.obs.registry.gauge(
                SERVING_LANES_READY,
                help="warm replica lanes (chips) in this serving process",
            ).set(self.lanes_ready)

    # -- state -------------------------------------------------------------

    @property
    def warm(self) -> bool:
        """True once every lane's every bucket is built and executed.

        Read by handler threads (via ``/readyz``) while ``warmup`` runs on
        the startup thread; the write is lock-guarded (nm03-lint NM331) so
        a reader observing True also observes the fully-populated lane
        registry, not just the flag.
        """
        with self._lock:
            return self._warm

    @warm.setter
    def warm(self, value: bool) -> None:
        with self._lock:
            self._warm = bool(value)
            if self._lane_devices is not None:
                for i in range(len(self._lane_warm)):
                    self._lane_warm[i] = bool(value)

    @property
    def degraded(self) -> bool:
        """True once the one-way CPU degradation has tripped (PR 3)."""
        return self.supervisor.degraded

    @property
    def degraded_cause(self) -> Optional[str]:
        return self.supervisor.degraded_cause

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest warm bucket that fits ``n`` requests."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket {self.buckets[-1]}"
        )

    # -- compilation (delegated to the compile hub) ------------------------

    def _get_compiled(self, bucket: int, lane: int = 0):
        """The (lane, bucket) executable from the hub's registry.

        AOT lowered+compiled at the bucket shape and pinned to the lane's
        device; the hub caches, so two executors with one config share
        warm executables and a post-warmup call here is a dict lookup.
        """
        devs = self._resolve_lanes()
        if not 0 <= lane < len(devs):
            raise ValueError(f"lane {lane} outside [0, {len(devs)})")
        return programs.serve_mask(self.cfg, bucket=bucket, device=devs[lane])

    def warmup(self) -> Dict[str, Dict[int, float]]:
        """Compile + execute every (lane, bucket) once; nested timings.

        Returns ``{"lane0": {bucket: seconds}, ...}``. The execute (on
        zeros) is part of warmup on purpose: first-run allocator and
        executable setup must be paid here, behind ``/readyz``, not by the
        first unlucky request. Lanes warm in order and the
        ``serving_lanes_ready`` gauge rises as each completes, so a probe
        mid-warmup sees honest partial readiness.
        """
        c = self.cfg.canvas
        devs = self._resolve_lanes()
        timings: Dict[str, Dict[int, float]] = {}
        for lane in range(len(devs)):
            lane_t: Dict[int, float] = {}
            for b in self.buckets:
                t0 = time.perf_counter()
                fn = self._get_compiled(b, lane)
                px = np.zeros((b, c, c), np.float32)
                dm = np.full((b, 2), self.cfg.min_dim, np.int32)
                mask, conv = fn(px, dm)
                np.asarray(mask), np.asarray(conv)  # block until executed
                lane_t[b] = round(time.perf_counter() - t0, 3)
            timings[f"lane{lane}"] = lane_t
            with self._lock:
                self._lane_warm[lane] = True
            self._set_lanes_ready_gauge()
        if self.obs is not None:
            for lane_key, lane_t in timings.items():
                for b, s in lane_t.items():
                    self.obs.registry.gauge(
                        "serving_warmup_seconds",
                        help="startup compile+first-execute time per lane and batch bucket",
                        bucket=str(b),
                        lane=lane_key[len("lane"):],
                    ).set(s)
        # nm03-lint: disable=NM331 goes through the lock-guarded property setter above; the linter cannot see through the descriptor
        self.warm = True
        self._set_lanes_ready_gauge()
        return timings

    # -- degradation target ------------------------------------------------

    def _fallback_call(self):
        """CPU recompute of the same batch from host arrays (PR-3 ladder).

        One deferred-trace hub program shared across buckets and lanes —
        XLA retraces per bucket shape, which is acceptable on the degraded
        path (correct-but-slower is the contract; the service flips
        not-ready either way, and every lane funnels here: a wedged chip
        drains the replica, it does not get per-lane triage).
        """
        with self._lock:
            if self._fallback_fn is not None:
                return self._fallback_fn
        import dataclasses

        import jax

        cpu = jax.local_devices(backend="cpu")[0]
        cfg = (
            dataclasses.replace(self.cfg, use_pallas=False)
            if self.cfg.use_pallas
            else self.cfg
        )
        inner = programs.serve_mask(cfg)  # deferred-trace, default device

        def call(px, dm):
            with jax.default_device(cpu):
                out = inner(
                    jax.device_put(np.asarray(px), cpu),
                    jax.device_put(np.asarray(dm), cpu),
                )
            return tuple(np.asarray(a) for a in out)

        # first builder wins: concurrent degraded dispatches must agree on
        # ONE callable (two jitted twins would double the retrace cost)
        with self._lock:
            if self._fallback_fn is None:
                self._fallback_fn = call
            return self._fallback_fn

    # -- chaos hook --------------------------------------------------------

    def _pre(self, index: int):
        """Dispatch-site fault hook (resilience.FaultPlan); None when off."""
        plan = self.fault_plan
        if plan is None or not plan.has_site("dispatch"):
            return None

        def pre(cancel):
            rule = plan.fire("dispatch", obs=self.obs, index=index)
            if rule is None:
                return
            if rule.kind == "hang":
                execute_hang(rule, cancel)
            else:  # transient
                raise InjectedTransientError(
                    f"injected transient device error (serve dispatch {index})"
                )

        return pre

    # -- the serve-time entry point ----------------------------------------

    def run_batch(
        self, pixels: np.ndarray, dims: np.ndarray, lane: int = 0, trace=None
    ):
        """Execute one bucket-padded batch on one lane, under supervision.

        ``pixels`` is (bucket, canvas, canvas) float32, ``dims`` (bucket, 2)
        int32 — already padded by the batcher; ``lane`` picks the replica
        lane whose pinned executable (and chip) runs it. ``trace`` is the
        chunk's :class:`~nm03_capstone_project_tpu.obs.trace.ChunkTrace`:
        each supervised attempt records a ``device_dispatch`` + ``fetch``
        span pair (and the degraded path a ``cpu_fallback`` span) shared
        by every rider — retries show up as repeated attempts on the
        timeline. Returns host-side ``(mask, converged)`` arrays. Raises
        only when the PR-3 ladder is exhausted (deterministic error, or
        degraded with fallback disabled); the batcher fails the batch's
        requests with it.
        """
        trace = trace if trace is not None else NULL_TRACE
        bucket = int(pixels.shape[0])
        fn = self._get_compiled(bucket, lane)
        index = next(self._dispatch_seq)
        reg = self.obs.registry if self.obs is not None else None
        if reg is not None:
            inflight_g = reg.gauge(
                SERVING_LANE_INFLIGHT,
                help="device batches in flight per replica lane",
                lane=str(lane),
            )
            inflight_g.inc()
        with self._lock:
            if lane < len(self._lane_inflight):
                self._lane_inflight[lane] += 1

        attempts = {"n": 0}  # shared so retried primaries number their spans

        def primary():
            # fetch INSIDE the supervised call: a wedged fetch is the same
            # wedge as a wedged dispatch (supervisor contract)
            attempts["n"] += 1
            with trace.span("device_dispatch", attempt=attempts["n"]):
                mask, conv = fn(pixels, dims)
            with trace.span("fetch", attempt=attempts["n"]):
                # nm03-lint: disable=NM321 the fetch span MEASURES this device sync — that is its entire purpose (trace schema, docs/OBSERVABILITY.md)
                return np.asarray(mask), np.asarray(conv)

        def fallback():
            with trace.span("cpu_fallback"):
                return self._fallback_call()(pixels, dims)

        try:
            out = self.supervisor.run(
                primary,
                fallback=fallback,
                pre=self._pre(index),
                label="serve_dispatch",
            )
        finally:
            if reg is not None:
                inflight_g.dec()
            with self._lock:
                if lane < len(self._lane_inflight):
                    self._lane_inflight[lane] -= 1
        with self._lock:
            if lane < len(self._lane_batches):
                self._lane_batches[lane] += 1
        if reg is not None:
            reg.counter(
                SERVING_LANE_BATCHES_TOTAL,
                help="device batches dispatched per replica lane",
                lane=str(lane),
            ).inc()
        return out
